"""JaxTrainer: fit() a train_loop_per_worker across a TPU worker gang.

Role-equivalent of ray: python/ray/train/data_parallel_trainer.py:25
(DataParallelTrainer — training_loop:428) + base_trainer.py:567 (fit).
The reference routes fit() through a Tune trial; here the trainer runs
the gang directly and tune-lite wraps *it* (the layering inverted on
purpose — the SPMD gang is the primitive, HPO is a consumer).

Gang failure policy: any worker death restarts the WHOLE group from the
latest persisted checkpoint (FailureConfig.max_failures), matching SPMD
reality — a multi-host XLA program cannot lose one participant.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.backend import BackendConfig, JaxConfig
from ray_tpu.train.backend_executor import (
    BackendExecutor,
    TrainWorkerGroupError,
)
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import FailureConfig, RunConfig, ScalingConfig


@dataclasses.dataclass
class Result:
    """Outcome of a run (ray: python/ray/air/result.py Result)."""

    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    path: str
    metrics_dataframe: Optional[List[Dict[str, Any]]] = None
    error: Optional[BaseException] = None


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable[[Dict[str, Any]], Any],
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        backend_config: Optional[BackendConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self._train_fn = train_loop_per_worker
        self._config = dict(train_loop_config or {})
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.backend_config = backend_config or JaxConfig()
        self._resume_from = resume_from_checkpoint
        # Data ingest (reference: data_parallel_trainer.py:52-111
        # `datasets=` → per-worker streaming_split shards surfaced in the
        # loop via train.get_dataset_shard)
        self._datasets = dict(datasets or {})

    def fit(self) -> Result:
        failure = self.run_config.failure_config or FailureConfig()
        failures_left = failure.max_failures
        latest_checkpoint = self._resume_from
        history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        executor = BackendExecutor(
            self.backend_config, self.scaling_config, self.run_config
        )
        while True:
            try:
                executor.start()
                executor.start_training(
                    self._train_fn, self._config, latest_checkpoint,
                    datasets=self._datasets,
                )
                while True:
                    reports = executor.next_reports()
                    if reports is None:
                        break
                    # rank 0's metrics are canonical (reference semantics)
                    last_metrics = reports[0]["metrics"]
                    last_metrics.setdefault("_timestamp", time.time())
                    history.append(dict(last_metrics))
                    # checkpoints were already persisted worker-side;
                    # just track the newest handle
                    ckpt = next(
                        (
                            r["checkpoint"]
                            for r in reports
                            if r["checkpoint"] is not None
                        ),
                        None,
                    )
                    if ckpt is not None:
                        latest_checkpoint = ckpt
                        self._prune_checkpoints(executor.trial_dir)
                executor.finish()
                executor.shutdown()
                return Result(
                    metrics=last_metrics,
                    checkpoint=latest_checkpoint,
                    path=executor.trial_dir,
                    metrics_dataframe=history,
                )
            except (TrainWorkerGroupError, TimeoutError) as e:
                # TimeoutError covers placement-group reservation failure;
                # the executor maps worker/get failures (incl. driver-side
                # get timeouts) to TrainWorkerGroupError.  Either way the
                # gang is torn down before deciding to retry or surface.
                executor.shutdown()
                if failures_left == 0:
                    return Result(
                        metrics=last_metrics,
                        checkpoint=latest_checkpoint,
                        path=executor.trial_dir,
                        metrics_dataframe=history,
                        error=e,
                    )
                if failures_left > 0:
                    failures_left -= 1
                # Gang restart: workers persist checkpoints before report()
                # returns, so storage may be ahead of the last handle the
                # driver saw — rescan and take the newest.  When it IS
                # ahead, also adopt its metrics sidecar: the resumed loop
                # starts past that step and may report nothing new, and
                # Result.metrics must match Result.checkpoint.
                rescanned = self._latest_persisted(executor.trial_dir)
                if rescanned is not None:
                    seen = (
                        self._ckpt_round(latest_checkpoint.path)
                        if latest_checkpoint is not None
                        else None
                    )
                    found = self._ckpt_round(rescanned.path)
                    if found is not None and (seen is None or found > seen):
                        side = self._sidecar_metrics(rescanned.path)
                        if side is not None:
                            last_metrics = side
                            last_metrics.setdefault(
                                "_timestamp", time.time()
                            )
                            history.append(dict(last_metrics))
                    latest_checkpoint = rescanned

    @staticmethod
    def _ckpt_round(ckpt_path: str) -> Optional[int]:
        """Report round parsed from a ``checkpoint_{round}_rank{rank}`` dir
        name (None for foreign names, e.g. resume_from_checkpoint dirs)."""
        import os

        parts = os.path.basename(ckpt_path.rstrip("/")).split("_")
        if len(parts) >= 2 and parts[0] == "checkpoint":
            try:
                return int(parts[1])
            except ValueError:
                return None
        return None

    @staticmethod
    def _sidecar_metrics(ckpt_path: str) -> Optional[Dict[str, Any]]:
        import os
        import pickle

        from ray_tpu.train.checkpoint import _METRICS_FILE

        p = os.path.join(ckpt_path, _METRICS_FILE)
        if not os.path.exists(p):
            return None
        try:
            with open(p, "rb") as f:
                return pickle.load(f)
        except Exception:
            return None

    def _latest_persisted(self, trial_dir: str) -> Optional[Checkpoint]:
        import os

        if not os.path.isdir(trial_dir):
            return None
        ckpts = sorted(
            d for d in os.listdir(trial_dir) if d.startswith("checkpoint_")
        )
        if not ckpts:
            return None
        # newest round wins; within a round the LOWEST rank (rank 0's
        # metrics are canonical, and its dir sorts first for same round)
        newest = ckpts[-1]
        top = self._ckpt_round(newest)
        if top is not None:
            for d in ckpts:
                if self._ckpt_round(d) == top:
                    newest = d
                    break
        return Checkpoint(os.path.join(trial_dir, newest))

    def _prune_checkpoints(self, trial_dir: str):
        import os
        import shutil

        cc = self.run_config.checkpoint_config
        if cc is None or cc.num_to_keep is None:
            return
        ckpts = sorted(
            d for d in os.listdir(trial_dir) if d.startswith("checkpoint_")
        )
        for stale in ckpts[: -cc.num_to_keep]:
            shutil.rmtree(os.path.join(trial_dir, stale), ignore_errors=True)
