"""Flax integration helpers for Train.

Role-equivalent of ray: python/ray/train/torch/train_loop_utils.py
(prepare_model — wrap the user's model for data-parallel/FSDP
execution) translated to the TPU stack: a flax ``nn.Module`` becomes a
sharded functional train state, with parameters laid out over the mesh
by the same FSDP convention the reference gets from torch FSDP — shard
each parameter's largest dim over the fsdp axis, replicate the rest.

Use inside `train_loop_per_worker` with the worker group's mesh:

    state = create_train_state(module, optax.adamw(3e-4), rng, batch,
                               mesh=mesh)
    step = make_train_step(loss_fn, state)
    for batch in loader:
        state, metrics = step(state, batch)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ray_tpu.parallel.mesh import FSDP_AXIS


def fsdp_spec(shape, mesh: Mesh) -> PartitionSpec:
    """Shard the largest dim divisible by the fsdp axis size; replicate
    everything else (torch-FSDP-flat-param analogue, XLA-style)."""
    n_fsdp = mesh.shape.get(FSDP_AXIS, 1)
    if n_fsdp <= 1 or len(shape) == 0:
        return PartitionSpec()
    dims = sorted(
        range(len(shape)), key=lambda i: shape[i], reverse=True
    )
    for d in dims:
        if shape[d] % n_fsdp == 0 and shape[d] >= n_fsdp:
            entry = [None] * len(shape)
            entry[d] = FSDP_AXIS
            return PartitionSpec(*entry)
    return PartitionSpec()


def shard_params(params, mesh: Optional[Mesh]):
    """device_put a flax param pytree with per-leaf FSDP shardings."""
    if mesh is None:
        return params
    shardings = jax.tree.map(
        lambda a: NamedSharding(mesh, fsdp_spec(a.shape, mesh)), params
    )
    return jax.device_put(params, shardings)


def create_train_state(
    module,
    optimizer,
    rng,
    sample_batch,
    mesh: Optional[Mesh] = None,
    apply_kwargs: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Init a flax module and build the sharded functional train state.

    Returns {"params", "opt_state", "apply_fn", "optimizer", "step"} —
    a plain dict pytree (jit/pjit-friendly; no flax TrainState class
    needed)."""
    variables = module.init(rng, sample_batch, **(apply_kwargs or {}))
    params = variables["params"] if "params" in variables else variables
    params = shard_params(params, mesh)
    # optax moment tensors are created with zeros_like over the (already
    # sharded) params, so they inherit each param's sharding; scalars
    # replicate — no explicit placement needed
    opt_state = optimizer.init(params)
    return {
        "params": params,
        "opt_state": opt_state,
        "apply_fn": module.apply,
        "optimizer": optimizer,
        "step": 0,
    }


def make_train_step(
    loss_fn: Callable[..., Any],
    state: Dict[str, Any],
) -> Callable:
    """(state, batch) -> (state, metrics), jit-compiled.

    `loss_fn(params, apply_fn, batch) -> scalar`.  The module's apply_fn
    and the optax optimizer are captured statically in the closure; only
    the array pytrees (params/opt_state/step) flow through jit."""
    apply_fn = state["apply_fn"]
    optimizer = state["optimizer"]

    @jax.jit
    def step(params, opt_state, batch):
        def scalar_loss(p):
            return loss_fn(p, apply_fn, batch)

        loss, grads = jax.value_and_grad(scalar_loss)(params)
        updates, opt_state2 = optimizer.update(grads, opt_state, params)
        import optax

        return optax.apply_updates(params, updates), opt_state2, loss

    def run(st: Dict[str, Any], batch):
        params, opt_state, loss = step(
            st["params"], st["opt_state"], batch
        )
        new_state = dict(
            st, params=params, opt_state=opt_state, step=st["step"] + 1
        )
        # loss stays a device scalar: float()-ing here would block every
        # step on a host round-trip and kill async dispatch pipelining
        # (call float(metrics["loss"]) when you actually need the value)
        return new_state, {"loss": loss}

    return run
