"""ray_tpu.train: distributed training orchestration for TPU gangs.

Role-equivalent of ray: python/ray/train/.  Worker-side API (report /
get_checkpoint / get_context) + driver-side JaxTrainer.
"""

from ray_tpu.train.backend import Backend, BackendConfig, JaxBackend, JaxConfig  # noqa: F401
from ray_tpu.train.checkpoint import Checkpoint  # noqa: F401
from ray_tpu.train.config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.session import (  # noqa: F401
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train.trainer import JaxTrainer, Result  # noqa: F401

# MPMD pipeline-parallel training lives in ray_tpu.train.pipeline
# (imported lazily by callers: the subpackage pulls in jax/optax).
