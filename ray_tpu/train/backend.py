"""Training backends: per-framework process-group setup on the worker gang.

Role-equivalent of ray: python/ray/train/backend.py:32,16 (Backend/
BackendConfig) and train/torch/config.py:153,112 (_TorchBackend.on_start →
dist.init_process_group).  The TPU-native backend wires
`jax.distributed.initialize` instead of NCCL: worker 0 of node 0 is the
coordinator, every worker learns (coordinator_address, num_processes,
process_id), and from there all numeric collectives live INSIDE compiled
XLA programs over ICI — no runtime collective library.
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Dict, List

if TYPE_CHECKING:
    from ray_tpu.train.worker_group import WorkerGroup


@dataclasses.dataclass
class BackendConfig:
    @property
    def backend_cls(self):
        return Backend


class Backend:
    """Hooks around the worker gang's lifecycle."""

    def on_start(self, worker_group: "WorkerGroup", backend_config: BackendConfig):
        pass

    def on_training_start(
        self, worker_group: "WorkerGroup", backend_config: BackendConfig
    ):
        pass

    def on_shutdown(self, worker_group: "WorkerGroup", backend_config: BackendConfig):
        pass


@dataclasses.dataclass
class JaxConfig(BackendConfig):
    """Configuration of the jax.distributed bootstrap.

    ``coordinator_port``: port the rank-0 process binds for the
    distributed service; 0 (default) asks the coordinator worker for a
    free port at gang start — re-picked on every gang (re)start, so
    restarts never trip over TIME_WAIT and concurrent gangs on one host
    never collide.  ``init_distributed``: call
    `jax.distributed.initialize` on each worker at training start (True
    for real multi-host SPMD; False leaves single-process jax, used by
    single-worker runs and CPU tests).
    """

    coordinator_port: int = 0
    init_distributed: bool = False

    @property
    def backend_cls(self):
        return JaxBackend


def _jax_distributed_init(coordinator: str, num_processes: int, process_id: int):
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    # prove the gang actually formed — callers gate training on this
    return jax.process_count() == num_processes


def _find_free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class JaxBackend(Backend):
    def __init__(self):
        self._resolved_port: int = 0

    def on_start(self, worker_group: "WorkerGroup", backend_config: JaxConfig):
        """Publish the SPMD bootstrap env to every worker.

        (ray: _TorchBackend picks MASTER_ADDR/PORT from worker 0 —
        train/torch/config.py:94-112; here worker 0 of node 0 hosts the
        jax coordinator.)  Resolved fresh per gang start: a restarted
        gang must not inherit a dead coordinator's port.
        """
        coord = worker_group.workers[0]
        port = backend_config.coordinator_port
        if not port:
            import ray_tpu

            port = ray_tpu.get(
                coord.actor.execute.remote(_find_free_port), timeout=60
            )
        self._resolved_port = port
        coordinator = f"{coord.ip}:{port}"
        envs: List[Dict[str, str]] = []
        for w in worker_group.workers:
            envs.append(
                {
                    "RT_COORDINATOR_ADDRESS": coordinator,
                    "RT_NUM_PROCESSES": str(len(worker_group.workers)),
                    "RT_PROCESS_ID": str(w.rank),
                    "RT_NODE_RANK": str(w.node_rank),
                }
            )
        worker_group.set_envs(envs)

    def on_training_start(
        self, worker_group: "WorkerGroup", backend_config: JaxConfig
    ):
        if not backend_config.init_distributed:
            return
        coord = worker_group.workers[0]
        coordinator = f"{coord.ip}:{self._resolved_port}"
        n = len(worker_group.workers)
        import ray_tpu

        ok = ray_tpu.get(
            [
                w.actor.execute.remote(
                    _jax_distributed_init, coordinator, n, w.rank
                )
                for w in worker_group.workers
            ],
            timeout=300,
        )
        if not all(ok):
            # surface as a gang failure so the trainer's teardown +
            # FailureConfig restart policy run (a bare RuntimeError would
            # escape fit()'s retry loop and leak the worker group)
            from ray_tpu.train.backend_executor import TrainWorkerGroupError

            raise TrainWorkerGroupError(
                f"jax.distributed gang formed with wrong process count "
                f"(expected {n}): {ok}"
            )
