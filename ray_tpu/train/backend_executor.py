"""Drives the worker gang through a training run.

Role-equivalent of ray: python/ray/train/_internal/backend_executor.py:66
(BackendExecutor — start:124, start_training:436) plus the report-polling
loop of train/trainer.py:31 (TrainingIterator).

Report flow: each round, one report is taken from EVERY worker (soft
barrier, like the reference); rank-0's metrics win; any worker's
checkpoint is persisted to run storage.  Worker failure surfaces as
TrainWorkerGroupError for the trainer's gang-restart policy.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.errors import ActorDiedError, GetTimeoutError, TaskError
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.checkpoint import Checkpoint, _ckpt_round
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.session import TrainContext
from ray_tpu.train.worker_group import WorkerGroup


class TrainWorkerGroupError(RuntimeError):
    """A worker died or errored; the gang must restart."""


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
        run_config: RunConfig,
    ):
        self.backend_config = backend_config
        self.backend = backend_config.backend_cls()
        self.scaling = scaling_config
        self.run_config = run_config
        self.worker_group: Optional[WorkerGroup] = None
        self.experiment_name = run_config.name or "train_run"
        self.trial_dir = os.path.join(
            run_config.resolved_storage_path(), self.experiment_name
        )

    # -- lifecycle -------------------------------------------------------
    def start(self):
        self.worker_group = WorkerGroup(
            self.scaling.num_workers,
            self.scaling.bundle(),
            placement_strategy=self.scaling.placement_strategy,
        )
        self.backend.on_start(self.worker_group, self.backend_config)

    def shutdown(self):
        if self.worker_group is not None:
            try:
                self.backend.on_shutdown(self.worker_group, self.backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None

    # -- training --------------------------------------------------------
    def start_training(
        self,
        train_fn: Callable[[Dict[str, Any]], Any],
        config: Dict[str, Any],
        latest_checkpoint: Optional[Checkpoint],
        datasets: Optional[Dict[str, Any]] = None,
    ):
        os.makedirs(self.trial_dir, exist_ok=True)
        # Computed ONCE, before any worker starts: every rank numbers its
        # reports from past the highest round already persisted in this
        # trial dir, so rounds stay monotonic across gang restarts and
        # consistent across ranks (see TrainSession.__init__).
        # Unreadable trial storage must surface (silently falling back to
        # round 0 would re-issue numbers an earlier attempt persisted and
        # corrupt the newest-round rescan ordering) — but as a gang error,
        # so fit()'s handler still tears the already-started workers down.
        start_round = 0
        try:
            listing = os.listdir(self.trial_dir)
        except OSError as e:
            raise TrainWorkerGroupError(
                f"trial storage unreadable: {e}"
            ) from e
        for d in listing:
            r = _ckpt_round(d)
            if r is not None and r >= start_round:
                start_round = r + 1
        self.backend.on_training_start(self.worker_group, self.backend_config)
        wg = self.worker_group
        node_count = len({w.node_id for w in wg.workers})
        local_sizes: Dict[str, int] = {}
        for w in wg.workers:
            local_sizes[w.node_id] = local_sizes.get(w.node_id, 0) + 1
        # `datasets=` ingest: each named dataset is streaming_split across
        # the gang; worker w receives split[w.rank] and reads it with
        # train.get_dataset_shard(name) (reference:
        # data_parallel_trainer.py:52-111 + dataset.py streaming_split).
        # equal=True: SPMD loops iterate in lockstep, so every worker must
        # see the same number of batches.
        shard_table: Dict[str, list] = {}
        for name, ds in (datasets or {}).items():
            shard_table[name] = ds.streaming_split(
                len(wg.workers), equal=len(wg.workers) > 1
            )
        starts = []
        for w in wg.workers:
            ctx = TrainContext(
                world_size=len(wg.workers),
                world_rank=w.rank,
                local_rank=w.local_rank,
                local_world_size=local_sizes[w.node_id],
                node_rank=w.node_rank,
                experiment_name=self.experiment_name,
                trial_dir=self.trial_dir,
            )
            shards = {
                name: splits[w.rank] for name, splits in shard_table.items()
            }
            starts.append(
                w.actor.start_training.remote(
                    train_fn, config, ctx, latest_checkpoint, shards,
                    start_round,
                )
            )
        try:
            ray_tpu.get(starts)
        except (ActorDiedError, TaskError) as e:
            raise TrainWorkerGroupError(f"worker failed to start: {e}") from e

    def next_reports(self, poll_s: float = 10.0) -> Optional[List[dict]]:
        """One report from every worker, or None when all loops finished.

        Liveness-based: each worker is polled in ``poll_s`` slices with no
        overall deadline — a loop stuck in its first XLA compile for minutes
        is healthy, while a dead worker fails the poll call itself with
        ActorDiedError (raised here as TrainWorkerGroupError).
        """
        wg = self.worker_group
        reports: List[Optional[dict]] = [None] * len(wg.workers)
        have: List[bool] = [False] * len(wg.workers)
        try:
            while not all(have):
                pend = [i for i in range(len(wg.workers)) if not have[i]]
                polled = ray_tpu.get(
                    [
                        wg.workers[i].actor.next_report.remote(timeout=poll_s)
                        for i in pend
                    ]
                )
                for i, r in zip(pend, polled):
                    if isinstance(r, dict) and r.get("pending"):
                        continue
                    reports[i] = r
                    have[i] = True
        except ActorDiedError as e:
            raise TrainWorkerGroupError(f"worker died mid-training: {e}") from e
        except TaskError as e:
            raise TrainWorkerGroupError(f"training loop failed: {e}") from e
        except GetTimeoutError as e:
            raise TrainWorkerGroupError(f"workers unresponsive: {e}") from e
        done = [r is None for r in reports]
        if all(done):
            return None
        if any(done):
            raise TrainWorkerGroupError(
                "training loops finished out of step: some workers reported "
                "while others already returned — SPMD loops must report the "
                "same number of times"
            )
        return reports

    def finish(self) -> List[Any]:
        wg = self.worker_group
        try:
            return ray_tpu.get(
                [w.actor.get_result.remote() for w in wg.workers]
            )
        except (ActorDiedError, TaskError, GetTimeoutError) as e:
            raise TrainWorkerGroupError(str(e)) from e
