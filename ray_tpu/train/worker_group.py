"""Gang of training worker actors inside a placement group.

Role-equivalent of ray: python/ray/train/_internal/worker_group.py:102
(WorkerGroup, RayTrainWorker:19).  Workers are created via a placement
group so the gang reserves its hosts/chips atomically; each worker is a
process that will own its TPU chips for its lifetime (raylet lease-time
chip binding).
"""

from __future__ import annotations

import os
import socket
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import (
    TrainContext,
    TrainSession,
    init_session,
    shutdown_session,
)
from ray_tpu.util import PlacementGroupSchedulingStrategy, placement_group


def actor_node_info() -> dict:
    """Topology facts WorkerGroup needs from any gang actor class —
    shared by TrainWorkerActor and the pipeline stage actors."""
    from ray_tpu.core.runtime import get_runtime

    ctx = ray_tpu.get_runtime_context()
    # the raylet address host is this node's reachable IP (loopback in
    # single-host tests, the real interface on a pod)
    ip = get_runtime().raylet_address.rsplit(":", 1)[0]
    return {
        "node_id": ctx.node_id,
        "hostname": socket.gethostname(),
        "ip": ip,
        "pid": os.getpid(),
        "tpu_chips": os.environ.get("TPU_VISIBLE_CHIPS", ""),
    }


@ray_tpu.remote
class TrainWorkerActor:
    """One training worker process (ray: RayTrainWorker analogue)."""

    def __init__(self):
        self._session: Optional[TrainSession] = None
        self._thread: Optional[threading.Thread] = None

    # -- topology discovery ---------------------------------------------
    def node_info(self) -> dict:
        return actor_node_info()

    def set_env(self, env: Dict[str, str]) -> bool:
        os.environ.update(env)
        return True

    def execute(self, fn: Callable, *args, **kwargs):
        """Run an arbitrary function in the worker (setup hooks etc.)."""
        return fn(*args, **kwargs)

    # -- training loop lifecycle ----------------------------------------
    def start_training(
        self,
        train_fn: Callable,
        config: Dict[str, Any],
        context: TrainContext,
        latest_checkpoint: Optional[Checkpoint],
        dataset_shards: Optional[Dict[str, Any]] = None,
        start_round: int = 0,
    ) -> bool:
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("training loop already running on this worker")
        session = TrainSession(
            context, latest_checkpoint=latest_checkpoint, train_config=config,
            dataset_shards=dataset_shards, start_round=start_round,
        )
        self._session = session
        init_session(session)

        def run():
            try:
                session.result = train_fn(config)
            except BaseException as e:  # noqa: BLE001 — reported to driver
                session.error = e
            finally:
                session.finished.set()

        self._thread = threading.Thread(
            target=run, name="train-loop", daemon=True
        )
        self._thread.start()
        return True

    def next_report(self, timeout: float = 300.0) -> Optional[dict]:
        """Blocks until the loop reports, finishes (None), or errors (raises)."""
        assert self._session is not None
        return self._session.next_report(timeout)

    def finished(self) -> bool:
        return self._session is not None and self._session.finished.is_set()

    def get_result(self):
        assert self._session is not None
        self._thread.join()
        if self._session.error is not None:
            raise self._session.error
        return self._session.result

    def shutdown_training(self) -> bool:
        shutdown_session()
        return True


@dataclass
class WorkerMeta:
    actor: Any
    node_id: str
    ip: str
    rank: int
    local_rank: int
    node_rank: int


class WorkerGroup:
    """N gang actors placed atomically via one placement group.

    ``actor_cls`` defaults to TrainWorkerActor (the data-parallel train
    path); the MPMD pipeline passes its stage actor class — any
    ``@ray_tpu.remote`` class exposing ``node_info()`` rides the same
    reservation + rank-assignment machinery.  ``actor_options`` merges
    into each actor's ``.options()`` (max_restarts, max_task_retries,
    on_drain, ...).
    """

    def __init__(
        self,
        num_workers: int,
        bundle: Dict[str, float],
        placement_strategy: str = "PACK",
        actor_cls=None,
        actor_options: Optional[Dict[str, Any]] = None,
    ):
        self.num_workers = num_workers
        self._actor_cls = actor_cls if actor_cls is not None else TrainWorkerActor
        self._pg = placement_group(
            [dict(bundle) for _ in range(num_workers)],
            strategy=placement_strategy,
        )
        if not self._pg.wait(timeout_seconds=120):
            from ray_tpu.util import remove_placement_group

            remove_placement_group(self._pg)
            raise TimeoutError(
                f"could not reserve {num_workers} x {bundle} within 120s"
            )
        self.workers: List[WorkerMeta] = []
        # The actor's lease carries the whole bundle: the raylet binds TPU
        # chip visibility (TPU_VISIBLE_CHIPS) from lease resources, so the
        # worker process must own its chips through its own demand.
        extra = {k: v for k, v in bundle.items() if k != "CPU"}
        actors = []
        for i in range(num_workers):
            opts = {
                "num_cpus": bundle.get("CPU", 0),
                "resources": extra or None,
                "scheduling_strategy": PlacementGroupSchedulingStrategy(
                    placement_group=self._pg,
                    placement_group_bundle_index=i,
                ),
            }
            # merge, not collide: an explicit actor_options key (e.g.
            # num_cpus) overrides the bundle-derived default
            opts.update(actor_options or {})
            actors.append(self._actor_cls.options(**opts).remote())
        # No wall-clock bound: actor startup length is unbounded under load
        # and liveness is tracked by the core (a dead worker surfaces as
        # ActorDiedError on this get).
        infos = ray_tpu.get([a.node_info.remote() for a in actors])
        # Rank assignment: group workers by node; node_rank by first
        # appearance; worker 0 of node 0 is the SPMD coordinator
        # (reference pattern: TPU-<pod>-head resource, tpu.py:376-397).
        node_order: List[str] = []
        local_counts: Dict[str, int] = {}
        for i, (a, info) in enumerate(zip(actors, infos)):
            nid = info["node_id"]
            if nid not in node_order:
                node_order.append(nid)
            local_rank = local_counts.get(nid, 0)
            local_counts[nid] = local_rank + 1
            self.workers.append(
                WorkerMeta(
                    actor=a,
                    node_id=nid,
                    ip=info["ip"],
                    rank=i,
                    local_rank=local_rank,
                    node_rank=node_order.index(nid),
                )
            )

    @property
    def placement_group(self):
        return self._pg

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Run ``fn`` on every worker, gathered (no fixed deadline — worker
        death fails the get; slow jax/XLA init is legal)."""
        return ray_tpu.get(
            [w.actor.execute.remote(fn, *args, **kwargs) for w in self.workers]
        )

    def set_envs(self, envs: List[Dict[str, str]]):
        ray_tpu.get(
            [
                w.actor.set_env.remote(env)
                for w, env in zip(self.workers, envs)
            ],
            timeout=120,
        )

    def shutdown(self):
        from ray_tpu.util import remove_placement_group

        for w in self.workers:
            try:
                ray_tpu.kill(w.actor)
            except Exception:
                pass
        remove_placement_group(self._pg)
        self.workers = []
