"""Checkpoint handle: a directory of files, wherever it lives.

Role-equivalent of ray: python/ray/train/_checkpoint.py:56 (Checkpoint) and
the storage layer (train/_internal/storage.py:349), collapsed to a
filesystem-path abstraction: TPU pods mount shared storage (GCS fuse /
NFS), so "upload" is a directory copy and zero-copy restore is a path.

For model state prefer orbax/msgpack inside the directory; `from_dict` /
`to_dict` cover small python-object checkpoints (pickle).
"""

from __future__ import annotations

import contextlib
import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Optional

_DICT_FILE = "_dict_checkpoint.pkl"
# Metrics persisted beside the state by Session.report(); read back by the
# trainer when a gang restart rescans storage that ran ahead of the driver.
_METRICS_FILE = "_report_metrics.pkl"


def _ckpt_round(path: str) -> Optional[int]:
    """Report round parsed from a trainer-issued
    ``checkpoint_{round}_rank{rank}`` dir name; None for foreign names
    (user-made, resume_from, or default uuid-suffixed ``persist()`` dirs
    — the rank segment is required so an all-digit uuid prefix can't
    masquerade as a round)."""
    parts = os.path.basename(path.rstrip("/")).split("_")
    if (
        len(parts) >= 3
        and parts[0] == "checkpoint"
        and parts[2].startswith("rank")
    ):
        try:
            return int(parts[1])
        except ValueError:
            return None
    return None


def _atomic_write(path: str, blob: bytes) -> None:
    """tmp + fsync + rename (same shape as workflow/storage.py): durable
    files must never be readable half-written."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _write_metrics_sidecar(ckpt_path: str, metrics: Dict[str, Any]) -> None:
    """Best-effort: written AFTER persist() returns, so its presence also
    marks the checkpoint directory as completely persisted.  Serialized
    before any file exists and moved in atomically — a pickling error or
    mid-write crash must not leave a truncated sidecar that wins the
    completeness tie-break while being unreadable."""
    try:
        _atomic_write(
            os.path.join(ckpt_path, _METRICS_FILE),
            pickle.dumps(dict(metrics)),
        )
    except Exception:
        pass  # unpicklable metrics must not fail report()


def _read_metrics_sidecar(ckpt_path: str) -> Optional[Dict[str, Any]]:
    p = os.path.join(ckpt_path, _METRICS_FILE)
    if not os.path.exists(p):
        return None
    try:
        with open(p, "rb") as f:
            return pickle.load(f)
    except Exception:
        return None


class Checkpoint:
    """Handle to a checkpoint directory.

    ``_temp=True`` marks a scratch directory owned by this handle:
    ``persist()`` *moves* it into run storage instead of copying, so
    per-step ``from_dict`` checkpoints don't accumulate in /tmp.
    """

    def __init__(self, path: str, _temp: bool = False):
        self.path = os.path.abspath(path)
        self._temp = _temp

    # -- constructors ----------------------------------------------------
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="rt_ckpt_")
        # atomic even inside the fresh scratch dir: persist() may later
        # shutil.move() it across filesystems (copy, not rename), and
        # recovery must never see a truncated pickle win a completeness
        # tie-break
        _atomic_write(os.path.join(d, _DICT_FILE), pickle.dumps(data))
        return cls(d, _temp=True)

    # -- accessors -------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        p = os.path.join(self.path, _DICT_FILE)
        if not os.path.exists(p):
            raise ValueError(
                f"checkpoint at {self.path} was not created with from_dict"
            )
        with open(p, "rb") as f:
            return pickle.load(f)

    def to_directory(self, path: Optional[str] = None) -> str:
        """Copy the checkpoint into ``path`` (or a fresh temp dir)."""
        dest = path or tempfile.mkdtemp(prefix="rt_ckpt_")
        os.makedirs(dest, exist_ok=True)
        shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextlib.contextmanager
    def as_directory(self):
        """Local read access without copying (path is already local/mounted)."""
        yield self.path

    def persist(self, storage_dir: str, name: Optional[str] = None) -> "Checkpoint":
        """Move/copy into run storage and return the durable handle.

        Scratch checkpoints (from_dict) are moved; user-owned directories
        are copied.
        """
        name = name or f"checkpoint_{uuid.uuid4().hex[:8]}"
        dest = os.path.join(storage_dir, name)
        if os.path.abspath(self.path) == os.path.abspath(dest):
            return self
        os.makedirs(storage_dir, exist_ok=True)
        if self._temp and not os.path.exists(dest):
            shutil.move(self.path, dest)
        else:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
            if self._temp:
                shutil.rmtree(self.path, ignore_errors=True)
        return Checkpoint(dest)

    # -- jax pytree checkpoints (orbax) ----------------------------------
    @classmethod
    def from_pytree(cls, tree: Any) -> "Checkpoint":
        """Save a jax pytree (params/opt state, sharded arrays included)
        with orbax — the SPMD-native model-state path (the reference
        delegates to torch.save/lightning; ray:
        python/ray/train/torch/torch_checkpoint.py role)."""
        import orbax.checkpoint as ocp

        d = tempfile.mkdtemp(prefix="rt_ckpt_")
        target = os.path.join(d, "pytree")
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(target, tree)
        ckptr.wait_until_finished()
        return cls(d, _temp=True)

    def to_pytree(self, abstract_tree: Any = None) -> Any:
        """Restore an orbax pytree.  Pass ``abstract_tree`` (e.g.
        jax.eval_shape output with shardings attached) to restore
        sharded onto a mesh; None restores as host arrays."""
        import orbax.checkpoint as ocp

        target = os.path.join(self.path, "pytree")
        if not os.path.isdir(target):
            raise ValueError(
                f"checkpoint at {self.path} was not created with from_pytree"
            )
        ckptr = ocp.StandardCheckpointer()
        if abstract_tree is None:
            return ckptr.restore(target)
        return ckptr.restore(target, abstract_tree)

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))
