"""The long-lived pipeline stage actor.

One process per (stage, dp-lane).  Holds the stage's parameter slice,
optimizer state, the 1F1B activation stash, and per-step grad
accumulators; executes forward/backward micro-ops in the queue order
the driver enqueued (sync actors run per-caller calls in admission
order, so the actor queue IS the 1F1B schedule for this stage).

Preemption survival contract (the reason this is an actor and not a
task): every micro-op is EXACTLY-ONCE under migration —

- a per-step ledger caches each completed op's reply keyed by
  (kind, step, micro); a call retried after a migration (lost reply,
  or a call in flight when the node died) returns the cached value
  without re-applying its state effects;
- ``__rt_checkpoint__`` captures params + optimizer state + the grad
  accumulators + the stash + the ledger, so the drain plane
  (PR 9) migrates the stage MID-STEP with its in-flight microbatches
  intact — the restored actor continues the step, it does not restart
  it;
- dp>1 stages are ranks of a util.collective group registered at
  configure time; the drain plane's proactive reform re-forms the
  group around the migrated member BEFORE the old node dies.

Micro-batch handoff (spec["handoff"]):

- ``"p2p"`` (default): adjacent stages of one dp lane are ranks of a
  per-lane collective group and stream activations/grads directly over
  persistent channels (util/collective/channel.py) — the driver's
  calls carry no data, only control; ops self-synchronize by fetching
  ``seq = step·n_micro + micro`` (the ledger key extended to the wire),
  and async sends overlap the next micro-op's compute.  Channel
  outboxes ride the checkpoint, so a migrated member re-offers its
  in-flight payloads into the re-formed group.
- ``"driver"``: PR 13's path — every activation an ObjectRef through
  the driver (kept for A/B benching and as the fallback).

Everything crossing the process boundary is numpy (bit-exact buffers);
jit re-ingests on entry.
"""

from __future__ import annotations

import os
import socket
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.train.pipeline.partition import (
    StagePrograms,
    flatten_grads,
    get_partition,
    to_numpy,
    to_wire,
    unflatten_grads,
)


@ray_tpu.remote
class PipelineStageActor:
    """One pipeline stage lane (rank ``lane`` of the stage's dp group)."""

    def __init__(self):
        self._spec: Optional[dict] = None
        self._progs: Optional[StagePrograms] = None
        self._blocks = None
        self._tail = None
        self._opt_blocks = None
        self._opt_tail = None
        self._acc_blocks = None
        self._acc_tail = None
        self._stash: Dict[int, Any] = {}
        self._ledger: Dict[tuple, Any] = {}
        self._losses: Dict[int, Dict[int, Any]] = {}
        self._executed = 0
        self._deduped = 0
        self._ch: Dict[str, Any] = {}
        self._p2p = False

    # -- topology discovery (WorkerGroup rank assignment) ----------------
    def node_info(self) -> dict:
        from ray_tpu.train.worker_group import actor_node_info

        return actor_node_info()

    def set_env(self, env: Dict[str, str]) -> bool:
        os.environ.update(env)
        return True

    # -- lifecycle -------------------------------------------------------
    def configure(self, spec: dict, blocks, tail=None) -> dict:
        """Install the stage: build programs, adopt the param slice,
        init optimizer state, and (dp > 1) join the stage's collective
        group under rank ``lane``.

        spec keys: model, model_config, n_stages, stage_idx, n_micro,
        dp, lane, optimizer, scale, group_name, collective_backend,
        collective_options (optional dict: wire_dtype / algorithm /
        chunk_bytes for the dp grad allreduce — default None keeps the
        bit-exact fp32 ring), handoff, lane_group (p2p channel group).
        """
        self._build(spec)
        self._blocks = blocks
        self._opt_blocks = to_numpy(self._progs.init_opt(blocks))
        if self._progs.is_first or self._progs.is_last:
            if tail is None:
                raise ValueError(
                    "first/last pipeline stages need the tail params"
                )
            self._tail = tail
            self._opt_tail = to_numpy(self._progs.init_opt(tail))
        if spec.get("handoff", "driver") == "p2p" and spec["n_stages"] > 1:
            from ray_tpu.util import collective as col

            # every actor joins its LANE group before its dp group: the
            # two group families partition the actors two ways, and one
            # consistent join order keeps the concurrent configure()
            # rendezvous rounds cycle-free.  No options: activations
            # must cross the wire bit-exact (quantization is a dp
            # grad-allreduce concern, never a channel one).
            col.init_collective_group(
                spec["n_stages"], spec["stage_idx"],
                backend=spec.get("collective_backend", "rpc"),
                group_name=spec["lane_group"],
            )
            self._open_channels()
        if spec["dp"] > 1:
            from ray_tpu.util import collective as col

            # group options (not per-op args) so the wire format rides
            # the rendezvous records: a drain-migration reform restores
            # the exact same data path without re-plumbing anything
            col.init_collective_group(
                spec["dp"], spec["lane"],
                backend=spec.get("collective_backend", "rpc"),
                group_name=spec["group_name"],
                options=spec.get("collective_options"),
            )
        return {"pid": os.getpid(), "host": socket.gethostname()}

    def _build(self, spec: dict) -> None:
        part = get_partition(spec["model"], spec["model_config"])
        self._progs = StagePrograms(
            part, spec["n_stages"], spec["stage_idx"], spec["optimizer"],
            spec["scale"],
        )
        self._spec = spec

    # -- p2p channels ------------------------------------------------------
    def _open_channels(self) -> None:
        """Open this stage's persistent channel ends on the lane group
        (group-lazy: only registers endpoints + reform listeners, so
        the restore path may call it BEFORE the group re-join)."""
        from ray_tpu.train.pipeline import schedule as sched
        from ray_tpu.util.collective.channel import (
            ChannelReceiver,
            ChannelSender,
        )

        spec = self._spec
        s, S, M = spec["stage_idx"], spec["n_stages"], spec["n_micro"]
        g = spec["lane_group"]
        depth = sched.inflight_micros(s, S, M)
        self._ch = {}
        if s > 0:
            self._ch["fwd_in"] = ChannelReceiver(g, "F", s - 1)
            self._ch["grad_out"] = ChannelSender(g, "B", s - 1,
                                                 window=depth)
        if s < S - 1:
            self._ch["fwd_out"] = ChannelSender(g, "F", s + 1,
                                                window=depth)
            self._ch["grad_in"] = ChannelReceiver(g, "B", s + 1)
        if s in (0, S - 1):
            # the edge stages exchange their raw tail-grad sums at
            # apply time over a dedicated "T" stream (seq = step) —
            # the last driver-mediated data ref gone from the step
            peer = S - 1 if s == 0 else 0
            self._ch["tail_out"] = ChannelSender(g, "T", peer)
            self._ch["tail_in"] = ChannelReceiver(g, "T", peer)
        self._p2p = True

    def _seq(self, step: int, micro: int) -> int:
        # the exactly-once ledger key, extended to the wire: pure in
        # (step, micro), so a migrated retry re-fetches/re-posts the
        # SAME stream position and dedupes identically
        return step * self._spec["n_micro"] + micro

    def _reap_sends(self) -> None:
        """Surface terminal async-send failures on the next micro-op
        (the overlap engine completes transfers in the background;
        nothing else would ever observe a late error)."""
        for ch in self._ch.values():
            reap = getattr(ch, "reap", None)
            if reap is not None:
                reap()

    # -- exactly-once ledger ---------------------------------------------
    def _cached(self, key):
        if key in self._ledger:
            self._deduped += 1
            return True, self._ledger[key]
        return False, None

    # -- micro-ops ---------------------------------------------------------
    def forward(self, step: int, micro: int, payload=None, targets=None):
        """First stage: payload = tokens (mb, S) int32, returns h.
        Mid stage: payload = h from the previous stage, returns h.
        Last stage: payload = h, targets = (mb, S); fused
        forward+loss+backward-begin — returns the grad flowing DOWN to
        the previous stage (the per-micro loss is kept here; the driver
        reads the step mean once via step_loss).

        p2p handoff: non-first stages ignore ``payload`` and fetch
        ``seq`` off the lane channel; the output is POSTED downstream
        (async — the transfer overlaps the next op's compute) and the
        driver gets a tiny control ack instead of the array."""
        key = ("F", step, micro)
        hit, val = self._cached(key)
        if hit:
            return val
        p = self._progs
        seq = None
        if self._p2p:
            seq = self._seq(step, micro)
            self._reap_sends()
            if not p.is_first:
                # fetch BEFORE counting the execution: an op that dies
                # waiting on the wire did no work to dedupe
                payload = self._ch["fwd_in"].fetch(seq)
        self._executed += 1
        if p.is_last:
            loss, (gb, gt, gh) = p.fwd_loss(
                self._blocks, self._tail, payload, targets
            )
            self._accumulate(gb, gt)
            self._losses.setdefault(step, {})[micro] = np.float32(loss)
            out = to_numpy(gh)
            if self._p2p:
                self._ch["grad_out"].post(seq, to_wire(out))
                out = True
        else:
            if p.is_first:
                h = p.fwd(self._blocks, self._tail, payload)
            else:
                h = p.fwd(self._blocks, payload)
            self._stash[micro] = payload
            out = to_numpy(h)
            if self._p2p:
                self._ch["fwd_out"].post(seq, to_wire(out))
                out = True
        self._ledger[key] = out
        return out

    def backward(self, step: int, micro: int, g_out=None):
        """Recompute-from-stash backward for first/mid stages; returns
        the grad for the stage below (True on the first stage — token
        grads stop here).  p2p handoff: ``g_out`` is fetched off the
        lane channel and the produced grad posted downstream."""
        key = ("B", step, micro)
        hit, val = self._cached(key)
        if hit:
            return val
        p = self._progs
        if p.is_last:
            raise RuntimeError(
                "last-stage backward is fused into forward; the driver "
                "must not submit B ops to the last stage"
            )
        seq = None
        if self._p2p:
            seq = self._seq(step, micro)
            self._reap_sends()
            g_out = self._ch["grad_in"].fetch(seq)
        self._executed += 1
        h_in = self._stash.pop(micro)
        if p.is_first:
            gb, gt = p.bwd(self._blocks, self._tail, h_in, g_out)
            self._accumulate(gb, gt)
            out = True
        else:
            gb, gh = p.bwd(self._blocks, h_in, g_out)
            self._accumulate(gb, None)
            out = to_numpy(gh)
            if self._p2p:
                self._ch["grad_out"].post(seq, to_wire(out))
                out = True
        self._ledger[key] = out
        return out

    def run_ops(self, step: int, ops, tokens=None, targets=None) -> bool:
        """ONE control RPC per stage per step (p2p): execute this
        stage's whole 1F1B op list in admission order; activations and
        grads move on the lane channels, so the call carries only the
        edge stages' token/target slices — (n_micro, lane_mb, seq_len)
        — and returns a single ack.  Every micro-op still ledgers
        individually, so a batch retried after a migration re-executes
        only the ops actually lost."""
        for kind, m in ops:
            if kind == "F":
                self.forward(
                    step, m,
                    tokens[m] if tokens is not None else None,
                    targets[m] if targets is not None else None,
                )
            else:
                self.backward(step, m)
        return True

    def _accumulate(self, g_blocks, g_tail):
        p = self._progs
        self._acc_blocks = (
            to_numpy(g_blocks) if self._acc_blocks is None
            else to_numpy(p.tree_add(self._acc_blocks, g_blocks))
        )
        if g_tail is not None:
            self._acc_tail = (
                to_numpy(g_tail) if self._acc_tail is None
                else to_numpy(p.tree_add(self._acc_tail, g_tail))
            )

    # -- step end ---------------------------------------------------------
    def tail_grads(self, step: int):
        """This side's RAW accumulated tail-grad sum (first and last
        stages exchange these; see partition module docstring)."""
        key = ("TG", step)
        hit, val = self._cached(key)
        if hit:
            return val
        out = to_numpy(self._acc_tail)
        self._ledger[key] = out
        return out

    def apply_gradients(self, step: int, other_tail_grads=None) -> bool:
        """Allreduce (dp > 1) + scale + optimizer update; clears the
        step's accumulators and expires ledger entries of PAST steps
        (the current step's stay — a lost apply reply must dedupe)."""
        key = ("A", step)
        hit, val = self._cached(key)
        if hit:
            return val
        p = self._progs
        self._executed += 1
        g_blocks = self._acc_blocks
        g_tail = None
        if p.is_first or p.is_last:
            if (self._p2p and other_tail_grads is None
                    and "tail_in" in self._ch):
                other_tail_grads = self._exchange_tail(step)
            # canonical operand order (first_side, last_side): both tail
            # copies compute the identical sum bitwise
            own, other = self._acc_tail, other_tail_grads
            first_side = own if p.is_first else other
            last_side = other if p.is_first else own
            g_tail = to_numpy(p.tree_add(first_side, last_side))
        if self._spec["dp"] > 1:
            g_blocks, g_tail = self._allreduce(g_blocks, g_tail)
        g_blocks = p.tree_scale(g_blocks)
        self._blocks, self._opt_blocks = map(to_numpy, p.apply(
            self._blocks, self._opt_blocks, g_blocks
        ))
        if g_tail is not None:
            g_tail = p.tree_scale(g_tail)
            self._tail, self._opt_tail = map(to_numpy, p.apply(
                self._tail, self._opt_tail, g_tail
            ))
        self._acc_blocks = None
        self._acc_tail = None
        self._stash.clear()
        if self._p2p:
            # PAST steps only (seq < step·M): the CURRENT step's
            # payloads stay re-deliverable until the NEXT apply proves
            # every cross-stage fetch of this step completed — the
            # driver finishes step k (all acks) before submitting k+1,
            # so by the apply of k+1 step k is certainly consumed
            base = step * self._spec["n_micro"]
            for ch in self._ch.values():
                # the "T" stream counts in steps, not micro seqs — its
                # current entry must likewise outlive THIS apply (the
                # peer edge stage may still be fetching it)
                ch.purge_below(step if ch.stream == "T" else base)
        self._ledger = {
            k: v for k, v in self._ledger.items() if k[1] >= step
        }
        self._losses = {s: v for s, v in self._losses.items() if s >= step}
        self._ledger[key] = True
        return True

    def _exchange_tail(self, step: int):
        """Edge-stage tail-grad swap over the lane "T" stream: post the
        own RAW sum (flattened to one f32 vector), fetch the peer's,
        unflatten against the local tree (both edges hold the same tail
        structure).  seq = step — pure, so a migrated retry re-posts
        and re-fetches the identical position and dedupes on the wire
        exactly like the micro-op streams."""
        self._ch["tail_out"].post(
            step, to_wire(flatten_grads(to_numpy(self._acc_tail)))
        )
        peer_flat = self._ch["tail_in"].fetch(step)
        return unflatten_grads(to_numpy(self._acc_tail), peer_flat)

    def _allreduce(self, g_blocks, g_tail):
        """Grad allreduce over the stage group, riding out a migration
        window: between a peer's old worker dying and the proactive
        reform completing, the group is transiently poisoned (or
        mid-reform, i.e. locally uninitialized).  The op mutates no
        actor state, so retrying against the re-formed group is exact —
        both sides re-enter with their checkpoint-intact accumulators.
        A peer that is REALLY gone keeps the group poisoned past the
        budget and the error surfaces as before."""
        import time as _time

        from ray_tpu.common.config import cfg
        from ray_tpu.util import collective as col
        from ray_tpu.util.collective.types import CollectiveError

        group = self._spec["group_name"]
        deadline = _time.monotonic() + float(
            self._spec.get("allreduce_retry_timeout_s")
            or cfg.collective_rendezvous_timeout_s
        )
        # ONE op per apply: blocks (and tail, when this stage holds one)
        # concatenated into a single f32 vector — one ring pass, and no
        # partially-reduced multi-op state to reason about under retry
        flat_b = flatten_grads(g_blocks)
        if g_tail is not None:
            flat = np.concatenate([flat_b, flatten_grads(g_tail)])
        else:
            flat = flat_b
        while True:
            try:
                summed = col.allreduce(flat, group_name=group)
                break
            except CollectiveError:
                if _time.monotonic() >= deadline:
                    raise
                _time.sleep(0.5)
        out_blocks = unflatten_grads(g_blocks, summed[:flat_b.size])
        out_tail = (
            unflatten_grads(g_tail, summed[flat_b.size:])
            if g_tail is not None else None
        )
        return out_blocks, out_tail

    def step_loss(self, step: int) -> float:
        """Mean per-micro loss of this lane for ``step`` (last stage)."""
        per = self._losses.get(step)
        if per is None:
            raise RuntimeError(f"no losses recorded for step {step}")
        vals = np.array(
            [per[m] for m in sorted(per)], dtype=np.float32
        )
        return float(np.float32(vals.sum() / np.float32(len(vals))))

    # -- introspection ----------------------------------------------------
    def get_params(self):
        return {"blocks": self._blocks, "tail": self._tail}

    def group_rank(self):
        from ray_tpu.util import collective as col

        return col.get_rank(self._spec["group_name"])

    def counters(self) -> dict:
        from ray_tpu.common import faults, serialization as ser
        from ray_tpu.core.runtime import get_runtime

        return {
            "pid": os.getpid(),
            "executed": self._executed,
            "deduped": self._deduped,
            "copy_trace": dict(ser.COPY_TRACE),
            "slab_hits": get_runtime().store.stats().get("slab_hits", 0),
            # RT_FAULTS firings in THIS worker process — chaos tests arm
            # plans via the env var and can only read the trace through
            # the actor (faults.trace() is per-process state)
            "fault_trace": faults.trace(),
        }

    # -- migration hooks (PR 9 drain plane) -------------------------------
    def __rt_checkpoint__(self):
        return {
            "spec": self._spec,
            "blocks": self._blocks,
            "tail": self._tail,
            "opt_blocks": self._opt_blocks,
            "opt_tail": self._opt_tail,
            "acc_blocks": self._acc_blocks,
            "acc_tail": self._acc_tail,
            "stash": dict(self._stash),
            "ledger": dict(self._ledger),
            "losses": {s: dict(v) for s, v in self._losses.items()},
            "executed": self._executed,
            "deduped": self._deduped,
            # unpurged channel payloads: the restored twin re-offers
            # these into the re-formed lane group (acked sends may have
            # died unconsumed in a co-migrating peer's mailbox)
            "send_outbox": {
                name: ch.outbox_state()
                for name, ch in self._ch.items()
                if hasattr(ch, "outbox_state")
            },
        }

    def __rt_restore__(self, state):
        self._build(state["spec"])
        self._blocks = state["blocks"]
        self._tail = state["tail"]
        self._opt_blocks = state["opt_blocks"]
        self._opt_tail = state["opt_tail"]
        self._acc_blocks = state["acc_blocks"]
        self._acc_tail = state["acc_tail"]
        self._stash = state["stash"]
        self._ledger = state["ledger"]
        self._losses = state["losses"]
        self._executed = state["executed"]
        self._deduped = state["deduped"]
        spec = state["spec"]
        if spec.get("handoff") == "p2p" and spec["n_stages"] > 1:
            # endpoints + reform listeners only — the lane-group
            # re-join runs AFTER this hook (worker_main's
            # _rejoin_collective_group), and its install fires the
            # listeners, which re-offer the restored outboxes
            self._open_channels()
            for name, st in (state.get("send_outbox") or {}).items():
                ch = self._ch.get(name)
                if ch is not None and hasattr(ch, "restore_outbox"):
                    ch.restore_outbox(st)
