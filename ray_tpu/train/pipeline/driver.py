"""MPMD pipeline driver: stage gangs + the 1F1B dispatch loop.

``PipelineTrainer`` places each stage as a gang of ``dp`` long-lived
actors (one ``train.worker_group.WorkerGroup`` per stage — atomic
placement-group reservation, node-aware lane ranks) and drives the
1F1B schedule over the batched task plane.  Two data planes
(``PipelineConfig.handoff``):

- ``"p2p"`` (default): adjacent stages stream activations/grads over
  persistent per-lane channels (util/collective/channel.py) and the
  driver ships NO data per micro-op — ONE ``run_ops`` control RPC per
  stage per step carries the stage's whole 1F1B op list (stages
  self-synchronize on channel seq arrival), the edge stages swap tail
  grads over the lane "T" stream, and stage compute overlaps the
  in-flight transfers (async channel sends).  Driver RPCs per step
  collapse from O(micro-ops) to O(stages).
- ``"driver"``: PR 13's path — every micro-op call's activation/grad
  inputs arrive as ObjectRefs, riding the data plane's vectored put
  path (small activations on the inline slab, large ones
  worker-stored in the shm arena and pulled by the consuming stage).

``LocalPipelineRunner`` executes the SAME per-stage programs (same
partition, same accumulation order, same optimizer math) sequentially
in one process — the bit-exact single-gang reference the parity tests
and the bench compare against.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.train.backend_executor import TrainWorkerGroupError
from ray_tpu.train.pipeline import schedule as sched
from ray_tpu.train.pipeline.partition import (
    StagePrograms,
    get_partition,
    to_numpy,
)
from ray_tpu.train.pipeline.stage import PipelineStageActor


@dataclasses.dataclass
class PipelineConfig:
    """One MPMD pipeline run's shape."""

    model_config: Any
    model: str = "gpt2"
    n_stages: int = 2
    n_micro: int = 4
    micro_batch: int = 2       # rows per microbatch (global, split over dp)
    seq_len: int = 32
    dp: int = 1                # lanes per stage (ranks of the stage group)
    optimizer: Dict[str, Any] = dataclasses.field(
        default_factory=lambda: {"name": "sgd", "lr": 0.1}
    )
    seed: int = 0
    name: str = "pipeline"
    collective_backend: str = "rpc"
    # Collectives v2 data path for the dp grad allreduce: e.g.
    # {"wire_dtype": "int8"} block-quantizes the concatenated grad
    # vector (~4x fewer wire bytes per apply), {"algorithm": "auto"}
    # enables size-based ring/rd selection.  None (default) keeps the
    # fp32 ring bit-for-bit — the dp parity pin depends on it.
    collective_options: Optional[Dict[str, Any]] = None
    # in-flight micro-ops ride retries across a stage migration
    max_task_retries: int = 8
    get_timeout_s: float = 600.0
    # micro-batch handoff plane: "p2p" streams activations over
    # persistent stage-to-stage channels; "driver" ships ObjectRefs
    # through the driver per micro-op (see module docstring)
    handoff: str = "p2p"

    def __post_init__(self):
        if self.micro_batch % self.dp:
            raise ValueError(
                f"micro_batch {self.micro_batch} must divide over "
                f"dp {self.dp}"
            )
        if self.handoff not in ("p2p", "driver"):
            raise ValueError(
                f"handoff must be 'p2p' or 'driver', got "
                f"{self.handoff!r}"
            )

    @property
    def scale(self) -> float:
        return 1.0 / float(self.n_micro * self.dp)

    @property
    def lane_mb(self) -> int:
        return self.micro_batch // self.dp

    def tokens_per_step(self) -> int:
        return self.n_micro * self.micro_batch * self.seq_len

    def stage_spec(self, stage_idx: int, lane: int) -> dict:
        return {
            "model": self.model,
            "model_config": self.model_config,
            "n_stages": self.n_stages,
            "stage_idx": stage_idx,
            "n_micro": self.n_micro,
            "dp": self.dp,
            "lane": lane,
            "optimizer": dict(self.optimizer),
            "scale": self.scale,
            "group_name": f"{self.name}:stage{stage_idx}",
            "collective_backend": self.collective_backend,
            "collective_options": self.collective_options,
            "handoff": self.handoff,
            "lane_group": f"{self.name}:lane{lane}:pp",
        }


def synthetic_batches(config: PipelineConfig, steps: int,
                      seed: Optional[int] = None
                      ) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Deterministic token batches shared by the cluster run, the local
    reference, and the bench: (tokens, targets) each
    (n_micro, micro_batch, seq_len) int32."""
    rng = np.random.default_rng(config.seed if seed is None else seed)
    vocab = config.model_config.vocab_size
    out = []
    for _ in range(steps):
        toks = rng.integers(
            0, vocab,
            (config.n_micro, config.micro_batch, config.seq_len + 1),
            dtype=np.int32,
        )
        out.append((toks[..., :-1], toks[..., 1:]))
    return out


def init_pp_params(config: PipelineConfig):
    """Driver-side model init + stage cut (numpy trees, ready to ship).
    All family knowledge comes from the partition registry, so a new
    family registered in models.pp.PARTITIONS just works here."""
    import jax

    part = get_partition(config.model, config.model_config)
    params = part.init(jax.random.key(config.seed))
    return to_numpy(part.to_pp(params, config.n_stages))


class PipelineTrainer:
    """Drives a 1F1B MPMD pipeline over stage actor gangs.

    Default placement: one WorkerGroup (placement group) of ``dp``
    actors per stage.  Tests that need exact node control (chaos
    placement) pass ``stage_actor_options`` — a [stage][lane] matrix of
    ``.options()`` dicts — and actors are created directly instead.
    """

    def __init__(self, config: PipelineConfig, *,
                 bundle: Optional[Dict[str, float]] = None,
                 placement_strategy: str = "PACK",
                 stage_actor_options: Optional[List[List[dict]]] = None):
        self.config = config
        self.bundle = bundle or {"CPU": 1}
        self.placement_strategy = placement_strategy
        self.stage_actor_options = stage_actor_options
        self.actors: List[List[Any]] = []   # [stage][lane]
        self.worker_groups: List[Any] = []
        self.step = 0
        self.losses: List[float] = []

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        cfg = self.config
        actor_opts = {
            "max_task_retries": cfg.max_task_retries,
            # crash recovery is the trainer-level gang-restart policy;
            # drain MIGRATION (the preemption path) consumes no budget
            "max_restarts": 0,
        }
        if self.stage_actor_options is not None:
            for s in range(cfg.n_stages):
                lanes = []
                for r in range(cfg.dp):
                    opts = dict(actor_opts)
                    opts.update(self.stage_actor_options[s][r])
                    lanes.append(PipelineStageActor.options(**opts).remote())
                self.actors.append(lanes)
        else:
            from ray_tpu.train.worker_group import WorkerGroup

            for s in range(cfg.n_stages):
                wg = WorkerGroup(
                    cfg.dp, dict(self.bundle),
                    placement_strategy=self.placement_strategy,
                    actor_cls=PipelineStageActor,
                    actor_options=actor_opts,
                )
                self.worker_groups.append(wg)
                # lane = gang rank (node-grouped, deterministic)
                self.actors.append(
                    [w.actor for w in sorted(wg.workers,
                                             key=lambda w: w.rank)]
                )
        pp = init_pp_params(cfg)
        import jax

        refs = []
        for s in range(cfg.n_stages):
            blocks = jax.tree.map(lambda a, _s=s: a[_s], pp["stages"])
            tail = (
                pp["tail"] if s in (0, cfg.n_stages - 1) else None
            )
            for r in range(cfg.dp):
                refs.append(self.actors[s][r].configure.remote(
                    cfg.stage_spec(s, r), blocks, tail
                ))
        try:
            ray_tpu.get(refs, timeout=cfg.get_timeout_s)
        except Exception as e:
            raise TrainWorkerGroupError(
                f"pipeline stage configure failed: {e}"
            ) from e

    def shutdown(self) -> None:
        for wg in self.worker_groups:
            try:
                wg.shutdown()
            except Exception:
                pass
        if not self.worker_groups:
            for lanes in self.actors:
                for a in lanes:
                    try:
                        ray_tpu.kill(a)
                    except Exception:
                        pass
        self.actors = []
        self.worker_groups = []

    # -- the 1F1B dispatch loop -------------------------------------------
    def run_step(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        """One training step: submit the full 1F1B graph, block on the
        applies, return the global mean loss.

        tokens/targets: (n_micro, micro_batch, seq_len) int32; lane r
        takes the contiguous row slice [r·lane_mb, (r+1)·lane_mb).
        """
        cfg = self.config
        S, M, dp, step = cfg.n_stages, cfg.n_micro, cfg.dp, self.step
        mb = cfg.lane_mb
        A = self.actors
        sink = []  # refs gathered only to surface errors
        if cfg.handoff == "p2p":
            # pure control plane, O(1) RPCs per stage per step: ONE
            # run_ops call ships a stage's whole 1F1B op list (plus the
            # edge stages' token/target slices); the stages move every
            # activation, grad, and tail-grad between themselves on the
            # lane channels, self-synchronizing on seq arrival, and
            # each reply is a tiny ack
            for s in range(S):
                ops = sched.stage_ops(s, S, M)
                for r in range(dp):
                    rows = slice(r * mb, (r + 1) * mb)
                    sink.append(A[s][r].run_ops.remote(
                        step, ops,
                        tokens[:, rows] if s == 0 else None,
                        targets[:, rows] if s == S - 1 else None,
                    ))
            applies = [
                A[s][r].apply_gradients.remote(step)
                for r in range(dp) for s in range(S)
            ]
        else:
            h: Dict[Tuple[int, int, int], Any] = {}   # (s, m, r) -> ref
            g: Dict[Tuple[int, int, int], Any] = {}
            for s, kind, m in sched.submission_order(S, M):
                for r in range(dp):
                    rows = slice(r * mb, (r + 1) * mb)
                    if kind == "F":
                        if s == 0:
                            ref = A[0][r].forward.remote(
                                step, m, tokens[m, rows]
                            )
                            h[(0, m, r)] = ref
                        elif s == S - 1:
                            ref = A[s][r].forward.remote(
                                step, m, h[(s - 1, m, r)],
                                targets[m, rows]
                            )
                            g[(s, m, r)] = ref   # fused: F returns grad
                        else:
                            ref = A[s][r].forward.remote(
                                step, m, h[(s - 1, m, r)]
                            )
                            h[(s, m, r)] = ref
                    else:
                        ref = A[s][r].backward.remote(
                            step, m, g[(s + 1, m, r)]
                        )
                        if s == 0:
                            sink.append(ref)
                        else:
                            g[(s, m, r)] = ref
            tg_first = [A[0][r].tail_grads.remote(step) for r in range(dp)]
            tg_last = [
                A[S - 1][r].tail_grads.remote(step) for r in range(dp)
            ]
            applies = []
            for r in range(dp):
                applies.append(
                    A[0][r].apply_gradients.remote(step, tg_last[r])
                )
                applies.append(
                    A[S - 1][r].apply_gradients.remote(step, tg_first[r])
                )
                for s in range(1, S - 1):
                    applies.append(A[s][r].apply_gradients.remote(step))
        loss_refs = [A[S - 1][r].step_loss.remote(step) for r in range(dp)]
        try:
            ray_tpu.get(sink + applies, timeout=cfg.get_timeout_s)
            lane_losses = ray_tpu.get(loss_refs, timeout=cfg.get_timeout_s)
        except Exception as e:
            raise TrainWorkerGroupError(
                f"pipeline step {step} failed: {e}"
            ) from e
        loss = float(
            np.float32(np.sum(np.float32(lane_losses), dtype=np.float32)
                       / np.float32(dp))
        )
        self.step += 1
        self.losses.append(loss)
        return loss

    def train(self, batches) -> List[float]:
        return [self.run_step(x, y) for x, y in batches]

    # -- introspection ----------------------------------------------------
    def gather_params(self):
        """Merged full-model params pulled from lane 0 of every stage."""
        import jax

        cfg = self.config
        per = ray_tpu.get(
            [self.actors[s][0].get_params.remote()
             for s in range(cfg.n_stages)],
            timeout=cfg.get_timeout_s,
        )
        stages = jax.tree.map(
            lambda *leaves: np.stack(leaves),
            *[p["blocks"] for p in per],
        )
        part = get_partition(cfg.model, cfg.model_config)
        return to_numpy(part.from_pp(
            {"stages": stages, "tail": per[0]["tail"]}
        ))

    def counters(self) -> List[List[dict]]:
        cfg = self.config
        return [
            ray_tpu.get(
                [a.counters.remote() for a in lanes],
                timeout=cfg.get_timeout_s,
            )
            for lanes in self.actors
        ]

    def ideal_micro_ops(self, steps: int) -> int:
        """Micro-op executions per lane actor set for ``steps`` clean
        steps: F+B per micro per non-last stage, fused F per micro on
        the last, one apply per stage — times dp lanes."""
        cfg = self.config
        per_step = (
            (2 * (cfg.n_stages - 1) + 1) * cfg.n_micro + cfg.n_stages
        )
        return per_step * cfg.dp * steps


class LocalPipelineRunner:
    """The single-gang reference: same partition, same per-stage
    programs, same micro order, same optimizer math — in one process.

    dp lanes are simulated sequentially; lane grad sums use the same
    canonical operand order as the 2-rank ring (elementwise a+b), so
    for dp ≤ 2 the cluster run matches this runner bit-for-bit.
    """

    def __init__(self, config: PipelineConfig):
        self.config = config
        part = get_partition(config.model, config.model_config)
        self.progs = [
            StagePrograms(part, config.n_stages, s, config.optimizer,
                          config.scale)
            for s in range(config.n_stages)
        ]
        pp = init_pp_params(config)
        import jax

        self.blocks = [
            jax.tree.map(lambda a, _s=s: a[_s], pp["stages"])
            for s in range(config.n_stages)
        ]
        self.tails = {
            0: pp["tail"],
            config.n_stages - 1: to_numpy(
                jax.tree.map(np.copy, pp["tail"])
            ),
        }
        self.opt_blocks = [
            to_numpy(self.progs[s].init_opt(self.blocks[s]))
            for s in range(config.n_stages)
        ]
        self.opt_tails = {
            s: to_numpy(self.progs[s].init_opt(t))
            for s, t in self.tails.items()
        }
        self.losses: List[float] = []

    def run_step(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        cfg = self.config
        S, M, dp, mb = cfg.n_stages, cfg.n_micro, cfg.dp, cfg.lane_mb
        P = self.progs
        acc_b: List[List[Any]] = [[None] * S for _ in range(dp)]
        acc_t: List[Dict[int, Any]] = [
            {0: None, S - 1: None} for _ in range(dp)
        ]
        lane_loss: List[List[np.float32]] = [[] for _ in range(dp)]

        def add(s, lane, g_blocks, g_tail=None):
            acc_b[lane][s] = (
                to_numpy(g_blocks) if acc_b[lane][s] is None
                else to_numpy(P[s].tree_add(acc_b[lane][s], g_blocks))
            )
            if g_tail is not None:
                acc_t[lane][s] = (
                    to_numpy(g_tail) if acc_t[lane][s] is None
                    else to_numpy(P[s].tree_add(acc_t[lane][s], g_tail))
                )

        for m in range(M):
            for lane in range(dp):
                rows = slice(lane * mb, (lane + 1) * mb)
                toks, tgt = tokens[m, rows], targets[m, rows]
                stash = {0: toks}
                h = to_numpy(P[0].fwd(self.blocks[0], self.tails[0], toks))
                for s in range(1, S - 1):
                    stash[s] = h
                    h = to_numpy(P[s].fwd(self.blocks[s], h))
                loss, (gb, gt, gh) = P[S - 1].fwd_loss(
                    self.blocks[S - 1], self.tails[S - 1], h, tgt
                )
                lane_loss[lane].append(np.float32(loss))
                add(S - 1, lane, gb, gt)
                gdown = to_numpy(gh)
                for s in range(S - 2, 0, -1):
                    gb, gh = P[s].bwd(self.blocks[s], stash[s], gdown)
                    add(s, lane, gb)
                    gdown = to_numpy(gh)
                gb, gt = P[0].bwd(
                    self.blocks[0], self.tails[0], stash[0], gdown
                )
                add(0, lane, gb, gt)

        # lane reduction: elementwise sum in lane order (== the 2-rank
        # ring's a+b); dp == 1 skips it, matching the cluster path
        for s in range(S):
            g = acc_b[0][s]
            for lane in range(1, dp):
                g = to_numpy(P[s].tree_add(g, acc_b[lane][s]))
            g = P[s].tree_scale(g)
            self.blocks[s], self.opt_blocks[s] = map(to_numpy, P[s].apply(
                self.blocks[s], self.opt_blocks[s], g
            ))
        # tail: canonical (first_side, last_side) then lane reduction
        for s in (0, S - 1):
            gt = to_numpy(P[s].tree_add(acc_t[0][0], acc_t[0][S - 1]))
            for lane in range(1, dp):
                gt = to_numpy(P[s].tree_add(
                    gt,
                    P[s].tree_add(acc_t[lane][0], acc_t[lane][S - 1]),
                ))
            gt = P[s].tree_scale(gt)
            self.tails[s], self.opt_tails[s] = map(to_numpy, P[s].apply(
                self.tails[s], self.opt_tails[s], gt
            ))
        lane_means = [
            float(np.float32(
                np.array(l, dtype=np.float32).sum()
                / np.float32(len(l))
            ))
            for l in lane_loss
        ]
        loss = float(
            np.float32(np.sum(np.float32(lane_means), dtype=np.float32)
                       / np.float32(dp))
        )
        self.losses.append(loss)
        return loss

    def train(self, batches) -> List[float]:
        return [self.run_step(x, y) for x, y in batches]

    def gather_params(self):
        import jax

        cfg = self.config
        part = get_partition(cfg.model, cfg.model_config)
        stages = jax.tree.map(
            lambda *leaves: np.stack(leaves), *self.blocks
        )
        return to_numpy(part.from_pp(
            {"stages": stages, "tail": self.tails[0]}
        ))
