"""ray_tpu.train.pipeline: MPMD pipeline-parallel training.

Stages of a jax model (cut by the reusable ``models.pp`` partitioner)
run as long-lived actor gangs; a 1F1B micro-batch schedule hands
activations/grads between them as shm objects over the batched task
plane; stage actors carry params + optimizer state through the drain
plane's ``__rt_checkpoint__``/``__rt_restore__`` hooks, so a preempted
stage host costs one pipeline bubble, not a run restart (JaxPP shape,
arxiv 2412.14374; survival story per arxiv 2510.20171).
"""

from ray_tpu.train.pipeline.driver import (  # noqa: F401
    LocalPipelineRunner,
    PipelineConfig,
    PipelineTrainer,
    init_pp_params,
    synthetic_batches,
)
from ray_tpu.train.pipeline.partition import (  # noqa: F401
    StagePrograms,
    make_optimizer,
)
from ray_tpu.train.pipeline.schedule import (  # noqa: F401
    bubble_micro_ops,
    stage_ops,
    submission_order,
)
from ray_tpu.train.pipeline.stage import PipelineStageActor  # noqa: F401
