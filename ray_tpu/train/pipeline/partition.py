"""Per-stage MPMD programs built from a models.pp ModelPartition.

The in-program schedule (parallel/pipeline.py) compiles the whole
fwd+bwd+update into one XLA program; here each stage gets its OWN small
set of jitted programs so a stage can live in its own actor process
(JaxPP's MPMD shape, arxiv 2412.14374):

- first stage:  fwd(blocks, tail, tokens) -> h
                bwd(blocks, tail, tokens, g_out) -> (g_blocks, g_tail)
- mid stage:    fwd(blocks, h) -> h
                bwd(blocks, h_in, g_out) -> (g_blocks, g_h_in)
- last stage:   fwd_loss(blocks, tail, h_in, targets)
                    -> (loss, (g_blocks, g_tail, g_h_in))
  (forward + loss + backward-begin fused: 1F1B's last stage always runs
  B immediately after F for a microbatch, so one program saves a
  host round-trip and the activation stash entirely.)

Backward uses activation recomputation: the stash keeps only each
microbatch's stage INPUT; ``jax.vjp`` re-runs the stage forward inside
the backward program.  That bounds stash memory at
O(in_flight_micros · activation) — the 1F1B steady state — instead of
O(layers · activation).

The tied embedding/head tail is replicated on the first and last
stages.  Each accumulates its own tail-grad contribution; at step end
the two exchange RAW accumulated sums and both apply
``add(first_side, last_side)`` in that canonical operand order — with
identical optimizer math on identical inputs the two tail copies stay
bitwise in lockstep, with no parameter traffic (grads only).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.pp import ModelPartition, get_partition  # noqa: F401

Params = Any


def make_optimizer(spec: dict):
    """Build an optax transform from a plain-dict spec.

    Declarative on purpose: every process (driver, each stage actor,
    the local reference runner) reconstructs the SAME transform from
    the same spec, so per-stage optimizer states — including the two
    tail copies — evolve bitwise identically.
    """
    import optax

    kind = spec.get("name", "sgd")
    lr = spec.get("lr", 0.1)
    extra = {k: v for k, v in spec.items() if k not in ("name", "lr")}
    if kind == "sgd":
        return optax.sgd(lr, **extra)
    if kind == "adam":
        return optax.adam(lr, **extra)
    if kind == "adamw":
        return optax.adamw(lr, **extra)
    raise ValueError(f"unknown optimizer {kind!r} (sgd/adam/adamw)")


def to_numpy(tree):
    """Materialize a jax pytree as numpy for cross-process handoff
    (bit-exact: np.asarray of a CPU jax array copies the raw buffer;
    bf16 leaves come back as ml_dtypes.bfloat16 ndarrays)."""
    return jax.tree.map(np.asarray, tree)


def to_wire(arr):
    """A channel-ready view of one handoff array: C-contiguous numpy.
    The p2p channel ships the raw buffer as chunked uint8 views, which
    requires contiguity; copy-free for ``to_numpy`` outputs (already
    contiguous), a single copy for strided slices."""
    return np.ascontiguousarray(arr)


class StagePrograms:
    """The jitted programs for ONE pipeline stage.

    Role is derived from (stage_idx, n_stages); ``scale`` is the
    grad-normalization constant 1/(n_micro·dp) applied once at
    ``apply`` time (per-micro losses are means, so the summed grads
    divide by the total microbatch count across lanes).
    """

    def __init__(self, part: ModelPartition, n_stages: int, stage_idx: int,
                 optimizer_spec: dict, scale: float):
        if n_stages < 2:
            raise ValueError("MPMD pipeline needs n_stages >= 2 "
                             "(single-stage training is the plain path)")
        if not (0 <= stage_idx < n_stages):
            raise ValueError(f"stage_idx {stage_idx} out of range")
        self.part = part
        self.n_stages = n_stages
        self.stage_idx = stage_idx
        self.is_first = stage_idx == 0
        self.is_last = stage_idx == n_stages - 1
        self.optimizer = make_optimizer(optimizer_spec)
        part_self = part

        # A stage program runs in its own process with no mesh in scope:
        # trace with sharding constraints disabled, exactly like the
        # in-program schedule's shard_map body (tailed_pipeline_train_step)
        from ray_tpu.parallel import sharding as sharding_mod

        def sf(blocks, h):
            with sharding_mod.no_constraints():
                return part_self.stage_fn(blocks, h)

        def pre(tail, tokens):
            with sharding_mod.no_constraints():
                return part_self.prelude(tail, tokens)

        if self.is_first:
            def _fwd(blocks, tail, tokens):
                return sf(blocks, pre(tail, tokens))

            def _bwd(blocks, tail, tokens, g_out):
                _, vjp = jax.vjp(
                    lambda b, t: sf(b, pre(t, tokens)), blocks, tail
                )
                return vjp(g_out)  # (g_blocks, g_tail)

            self.fwd: Callable = jax.jit(_fwd)
            self.bwd: Callable = jax.jit(_bwd)
        elif not self.is_last:
            def _bwd(blocks, h_in, g_out):
                _, vjp = jax.vjp(sf, blocks, h_in)
                return vjp(g_out)  # (g_blocks, g_h_in)

            self.fwd = jax.jit(sf)
            self.bwd = jax.jit(_bwd)
        if self.is_last:
            def _fwd_loss(blocks, tail, h_in, targets):
                def f(b, t, h):
                    with sharding_mod.no_constraints():
                        return part_self.micro_loss(t, sf(b, h), targets)

                return jax.value_and_grad(f, argnums=(0, 1, 2))(
                    blocks, tail, h_in
                )  # (loss, (g_blocks, g_tail, g_h_in))

            self.fwd_loss = jax.jit(_fwd_loss)

        self.tree_add = jax.jit(
            lambda a, b: jax.tree.map(jnp.add, a, b)
        )
        s = jnp.float32(scale)
        self.tree_scale = jax.jit(
            lambda t: jax.tree.map(
                lambda x: (x * s).astype(x.dtype), t
            )
        )
        opt = self.optimizer

        def _apply(params, opt_state, grads):
            import optax

            updates, new_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_state

        self.apply = jax.jit(_apply)

    # -- state init ------------------------------------------------------
    def init_opt(self, params):
        return self.optimizer.init(params)


def flatten_grads(tree) -> np.ndarray:
    """Deterministic leaf-order concat into one f32 vector — the wire
    shape for the per-stage dp allreduce (one collective op per stage
    per step instead of one per leaf)."""
    leaves = jax.tree.leaves(tree)
    return np.concatenate(
        [np.asarray(x, dtype=np.float32).reshape(-1) for x in leaves]
    )


def unflatten_grads(tree, flat: np.ndarray):
    """Inverse of flatten_grads against the same tree structure."""
    leaves, treedef = jax.tree.flatten(tree)
    out, off = [], 0
    for leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        seg = flat[off:off + n].reshape(leaf.shape)
        out.append(seg.astype(np.asarray(leaf).dtype))
        off += n
    return jax.tree.unflatten(treedef, out)
