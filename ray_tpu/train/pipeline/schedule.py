"""1F1B micro-batch schedule for the MPMD pipeline.

Two pure functions the driver (and tests) share:

- ``stage_ops(s, n_stages, n_micro)``: the op sequence ONE stage
  executes — this is exactly the order the driver enqueues calls on
  that stage's actor, and sync actors execute per-caller calls in
  admission order, so the actor's queue IS the schedule.  Dataflow
  (activation/grad refs) enforces the cross-stage dependencies; queue
  order enforces the rest.

- ``submission_order(n_stages, n_micro)``: a global interleaving of
  every stage's op list in which each op appears after the op that
  produces its input ref — the order the driver must CREATE the calls
  in (a ref must exist before it can be passed as an argument; it need
  not be resolved).  Only the driver-ref handoff needs this: under the
  p2p channel handoff there are no refs to thread, and the driver
  ships each stage its own ``stage_ops`` list in ONE batched
  ``run_ops`` control call (stages self-synchronize on channel
  arrival).

The last stage has no separate B ops: its forward fuses loss + the
first backward step (see partition.StagePrograms), which is what makes
the schedule 1F1B rather than GPipe — memory stays bounded by the
warmup depth, not the microbatch count.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

Op = Tuple[str, int]  # ("F" | "B", micro_index)


def stage_ops(s: int, n_stages: int, n_micro: int) -> List[Op]:
    """The 1F1B op order for stage ``s``: warmup forwards (pipeline
    depth remaining below this stage), steady-state F/B alternation,
    cooldown backwards.  The last stage is all (fused) forwards."""
    if n_micro < 1:
        raise ValueError("n_micro must be >= 1")
    if s == n_stages - 1:
        return [("F", m) for m in range(n_micro)]
    warmup = min(n_micro, n_stages - 1 - s)
    ops: List[Op] = [("F", m) for m in range(warmup)]
    f, b = warmup, 0
    while f < n_micro:
        ops.append(("F", f))
        ops.append(("B", b))
        f += 1
        b += 1
    while b < n_micro:
        ops.append(("B", b))
        b += 1
    return ops


def op_dep(s: int, kind: str, m: int,
           n_stages: int) -> Optional[Tuple[int, str, int]]:
    """The producing op whose ref this op consumes (None: driver input)."""
    if kind == "F":
        return None if s == 0 else (s - 1, "F", m)
    # B on stage s < last consumes the grad from the stage above; the
    # stage right below the last consumes the last stage's FUSED F
    if s == n_stages - 2:
        return (n_stages - 1, "F", m)
    return (s + 1, "B", m)


def submission_order(n_stages: int,
                     n_micro: int) -> List[Tuple[int, str, int]]:
    """Dependency-respecting global merge of every stage's op list.

    Deterministic; preserves each stage's own op order (the per-actor
    queue order) and emits an op only after its producer."""
    lists = [stage_ops(s, n_stages, n_micro) for s in range(n_stages)]
    ptr = [0] * n_stages
    total = sum(len(l) for l in lists)
    done = set()
    order: List[Tuple[int, str, int]] = []
    while len(order) < total:
        progressed = False
        for s in range(n_stages):
            while ptr[s] < len(lists[s]):
                kind, m = lists[s][ptr[s]]
                dep = op_dep(s, kind, m, n_stages)
                if dep is not None and dep not in done:
                    break
                order.append((s, kind, m))
                done.add((s, kind, m))
                ptr[s] += 1
                progressed = True
        if not progressed:  # pragma: no cover — 1F1B is always feasible
            raise RuntimeError(
                f"1F1B submission deadlock at {ptr} "
                f"(n_stages={n_stages}, n_micro={n_micro})"
            )
    return order


def inflight_micros(s: int, n_stages: int, n_micro: int) -> int:
    """Peak in-flight microbatches at stage ``s`` under 1F1B — warmup
    depth + the one steady-state forward.  Sizes the channel window
    (pre-posted receive slots / unreaped async sends): the schedule can
    never put more than this many of one stage's payloads in flight."""
    if s == n_stages - 1:
        return 1  # fused fwd+loss+bwd: consumed as it arrives
    return min(n_micro, n_stages - s)


def bubble_micro_ops(n_stages: int) -> int:
    """Micro-op count of ONE pipeline bubble: the fill/drain ramp is
    (n_stages - 1) microbatches deep, each a forward + a backward —
    the acceptance bound on work lost to a preemption."""
    return 2 * (n_stages - 1)
