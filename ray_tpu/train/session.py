"""Per-worker training session: the worker↔driver reporting channel.

Role-equivalent of ray: python/ray/train/_internal/session.py:110
(_TrainSession, report:402) and train/context.py:80 (TrainContext).

The user's ``train_loop_per_worker`` runs on a thread inside the train
worker actor; ``report()`` enqueues (metrics, checkpoint) and, like the
reference, acts as a soft barrier — the driver consumes one report per
round from every worker before continuing, keeping SPMD workers in step.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint, _write_metrics_sidecar

_session_lock = threading.Lock()
_session: Optional["TrainSession"] = None


@dataclasses.dataclass
class TrainContext:
    world_size: int
    world_rank: int
    local_rank: int
    local_world_size: int
    node_rank: int
    experiment_name: str
    trial_dir: str

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_trial_dir(self) -> str:
        return self.trial_dir


class TrainSession:
    def __init__(
        self,
        context: TrainContext,
        latest_checkpoint: Optional[Checkpoint] = None,
        train_config: Optional[Dict[str, Any]] = None,
        dataset_shards: Optional[Dict[str, Any]] = None,
        start_round: int = 0,
    ):
        self.context = context
        self.train_config = train_config or {}
        self.latest_checkpoint = latest_checkpoint
        self.dataset_shards = dataset_shards or {}
        self.reports: "queue.Queue" = queue.Queue()
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        self.result: Any = None
        # Rounds stay monotonic ACROSS gang restarts into the same trial
        # dir: a fresh attempt must not re-issue round numbers an earlier
        # attempt already persisted, or the trainer's newest-round rescan
        # would prefer a stale pre-restart checkpoint.  The driver computes
        # the start round ONCE before dispatching the gang (a per-worker
        # directory scan here would race with fast peers' first persists
        # and desynchronize round numbers across ranks).
        self._report_idx = start_round

    # -- worker-side API -------------------------------------------------
    def report(
        self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None
    ):
        """Persist the checkpoint, enqueue the report, and block until the
        driver consumes it.

        Durability is worker-side (reference semantics: the worker uploads
        via its StorageContext, train/_internal/storage.py:349): the
        checkpoint hits run storage BEFORE report() returns, so a crash at
        any later point can never lose it.  The post-enqueue block is the
        pacing barrier — the loop cannot sprint ahead of the driver.
        """
        if checkpoint is not None:
            checkpoint = checkpoint.persist(
                self.context.trial_dir,
                name=(
                    f"checkpoint_{self._report_idx:06d}"
                    f"_rank{self.context.world_rank:05d}"
                ),
            )
            self.latest_checkpoint = checkpoint
            # Metrics sidecar: a gang restart can rescan a checkpoint the
            # driver never saw the report for (this worker is acked for
            # round k, a peer dies in the same round, and this worker
            # persists round k+1 before the teardown lands).  Persisting
            # the metrics beside the state lets the trainer keep
            # Result.metrics consistent with Result.checkpoint.
            _write_metrics_sidecar(checkpoint.path, metrics)
        self._report_idx += 1
        self.reports.put({"metrics": dict(metrics), "checkpoint": checkpoint})
        self.reports.join()

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.latest_checkpoint

    # -- executor-side API ----------------------------------------------
    def next_report(self, timeout: float) -> Optional[dict]:
        """Next report, None if the loop finished (raising its error), or
        the sentinel ``{"pending": True}`` if nothing arrived within
        ``timeout``.

        The sentinel (not an exception) is deliberate: how long a loop may
        go without reporting is unbounded — the first report sits behind an
        XLA compile that can take minutes — so the driver polls in short
        slices and relies on actor liveness (worker death fails the poll
        call itself) rather than any fixed report deadline."""
        while True:
            try:
                item = self.reports.get(timeout=min(timeout, 0.2))
                self.reports.task_done()  # unblocks the reporting loop
                return item
            except queue.Empty:
                if self.finished.is_set() and self.reports.empty():
                    if self.error is not None:
                        raise self.error
                    return None
                timeout -= 0.2
                if timeout <= 0:
                    return {"pending": True}


def init_session(session: TrainSession) -> None:
    global _session
    with _session_lock:
        _session = session


def shutdown_session() -> None:
    global _session
    with _session_lock:
        _session = None


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError(
            "No training session active; this API must be called from inside "
            "a train_loop_per_worker"
        )
    return _session


# -- module-level user API (ray: train/_internal/session.py:666+) ---------


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().get_checkpoint()


def get_context() -> TrainContext:
    return get_session().context


def get_dataset_shard(name: str = "train"):
    """This worker's DataIterator for the trainer's `datasets[name]`
    (reference: ray.train.get_dataset_shard, fed by streaming_split in
    data_parallel_trainer.py:52-111)."""
    shards = get_session().dataset_shards
    if name not in shards:
        raise KeyError(
            f"no dataset shard {name!r}: pass datasets={{{name!r}: ds}} to "
            f"JaxTrainer (available: {sorted(shards)})"
        )
    return shards[name]
