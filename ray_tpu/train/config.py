"""Train configuration dataclasses.

Role-equivalent of ray: python/ray/air/config.py (ScalingConfig:103,
RunConfig:617, FailureConfig) and ray: python/ray/train/_checkpoint
CheckpointConfig — reshaped for TPU: scaling is expressed in workers
(processes) × chips per worker, and maps onto a placement group whose
bundles follow slice topology.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional


@dataclasses.dataclass
class ScalingConfig:
    """How many training workers and what each one holds.

    A worker is one process that owns ``tpus_per_worker`` chips (libtpu:
    one process per chip set).  On a v5e-8 host, 1 worker × 8 chips is
    the canonical layout; a v5e-256 pod is 32 workers × 8 chips.
    """

    num_workers: int = 1
    use_tpu: bool = False
    tpus_per_worker: Optional[float] = None  # default: all chips of a host
    cpus_per_worker: float = 1.0
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"  # workers per reference default

    def bundle(self) -> Dict[str, float]:
        b = dict(self.resources_per_worker or {})
        b["CPU"] = b.get("CPU", self.cpus_per_worker)
        if self.use_tpu:
            b.setdefault("TPU", self.tpus_per_worker or 1)
        return b


@dataclasses.dataclass
class FailureConfig:
    """Gang restart policy: an SPMD group is all-or-nothing, so any worker
    failure restarts the whole group from the latest checkpoint
    (SURVEY.md §7 "hard parts": one host dies ⇒ whole mesh restarts).

    max_failures < 0 means retry forever.
    """

    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None  # None = keep all
    checkpoint_frequency: int = 0  # informational; loops decide when


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None  # default: /tmp/ray_tpu_results
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None

    def resolved_storage_path(self) -> str:
        return self.storage_path or "/tmp/ray_tpu_results"
