"""Device-mesh construction for SPMD parallelism.

The TPU-native replacement for the reference's process-group bootstrap
(ray: python/ray/train/torch/config.py:112 `dist.init_process_group`,
ray: python/ray/util/collective/collective.py:120): instead of wiring a
NCCL communicator between worker processes, we build a
`jax.sharding.Mesh` over the slice's devices and let XLA compile
collectives onto ICI.

Axis convention (outer → inner, matching physical locality on a pod):

  dp    data parallelism (pure replication of params, gradient psum)
  fsdp  fully-sharded data parallelism (params sharded, all-gathered
        per layer; gradients reduce-scattered)
  ep    expert parallelism (MoE experts sharded; token dispatch is an
        all_to_all over this axis)
  pp    pipeline parallelism (layer stages; activations ppermute to the
        next stage once per microbatch — most latency-tolerant of the
        model axes)
  sp    sequence/context parallelism (ring attention neighbors — must
        map to an ICI ring)
  tp    tensor/model parallelism (innermost: highest-bandwidth axis)

Any axis may have size 1; the mesh is always constructed with all six
named axes so sharding rules never need to special-case missing axes.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DP_AXIS = "dp"
FSDP_AXIS = "fsdp"
EP_AXIS = "ep"
PP_AXIS = "pp"
SP_AXIS = "sp"
TP_AXIS = "tp"

#: Mesh axes ordered outer→inner. dp/fsdp vary slowest (their collectives
#: tolerate the most latency: once-per-step gradient reductions), tp varies
#: fastest (per-layer all-gathers/reduce-scatters want nearest neighbors).
AXIS_ORDER = (DP_AXIS, FSDP_AXIS, EP_AXIS, PP_AXIS, SP_AXIS, TP_AXIS)

#: Axes over which a gradient psum runs for data parallelism.
DATA_AXES = (DP_AXIS, FSDP_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical shape of the device mesh.

    ``-1`` for at most one axis means "absorb all remaining devices",
    mirroring the reference's ScalingConfig(num_workers=...) ergonomics
    (ray: python/ray/air/config.py:103) but in mesh terms.
    """

    dp: int = -1
    fsdp: int = 1
    ep: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        sizes = {"dp": self.dp, "fsdp": self.fsdp, "ep": self.ep,
                 "pp": self.pp, "sp": self.sp, "tp": self.tp}
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {wild}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n_devices}"
            )
        return MeshConfig(**sizes)

    @property
    def shape(self) -> tuple:
        return (self.dp, self.fsdp, self.ep, self.pp, self.sp, self.tp)

    def describe(self) -> str:
        return "x".join(
            f"{a}={s}" for a, s in zip(AXIS_ORDER, self.shape) if s != 1
        ) or "single-device"


#: Process-wide active mesh, set by make_mesh / set_current_mesh.  Library
#: code (ring attention, train steps) that needs the concrete mesh for
#: shard_map fetches it here rather than threading it through every call.
_CURRENT_MESH: Optional[Mesh] = None


def set_current_mesh(mesh: Optional[Mesh]) -> None:
    global _CURRENT_MESH
    _CURRENT_MESH = mesh


def current_mesh() -> Optional[Mesh]:
    return _CURRENT_MESH


def use(mesh: Mesh):
    """Context manager binding ``mesh`` for PartitionSpec resolution."""
    return jax.set_mesh(mesh)


def make_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build the 6-axis mesh over ``devices`` (default: all local devices).

    Uses `jax.experimental.mesh_utils` device ordering when available so
    the innermost axes land on physically adjacent chips (ICI neighbors);
    falls back to a plain reshape on CPU meshes where topology is flat.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    config = (config or MeshConfig()).resolve(len(devices))
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(
            config.shape, devices=devices
        )
    except ImportError:
        dev_array = np.asarray(devices).reshape(config.shape)
    except Exception as e:
        # A failed topology-aware layout on real hardware means sp/tp
        # neighbors may not be ICI-adjacent — degraded, not incorrect,
        # so warn loudly instead of failing or silently falling back.
        import warnings

        warnings.warn(
            f"mesh_utils.create_device_mesh failed ({e!r}); falling back "
            f"to flat device order — collective bandwidth may suffer"
        )
        dev_array = np.asarray(devices).reshape(config.shape)
    mesh = Mesh(dev_array, AXIS_ORDER)
    set_current_mesh(mesh)
    from ray_tpu.parallel import sharding as _sharding

    _sharding.set_active_rules(_sharding.DEFAULT_RULES)
    return mesh


#: Outermost axis of a multi-slice mesh: crosses the data-center network
#: between TPU slices, so ONLY once-per-step collectives (data-parallel
#: gradient psums) should map onto it.
DCN_AXIS = "dcn"


def make_multislice_mesh(
    n_slices: int,
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """A dcn x ici product mesh over ``n_slices`` TPU slices.

    The SURVEY §2.5 DCN story (role-equivalent of the reference's
    hierarchical NCCL topology / MegaScale multi-slice training): the
    ``dcn`` axis is OUTERMOST — its collectives ride the slower
    inter-slice fabric exactly once per step (grad psum) while every
    model axis (fsdp/ep/pp/sp/tp) stays inside a slice on ICI.

    On real multislice hardware, devices group by their
    ``slice_index``; on a virtual CPU mesh any even partition of the
    devices validates the compile path.  Use MULTISLICE_RULES (or any
    rule table mapping "batch" onto ("dcn", "dp", "fsdp")) so the batch
    splits across slices.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    # group by slice when the platform reports one (TPU multislice).  A
    # mismatch must FAIL, not fall back: reshaping ungrouped devices puts
    # ICI axes (per-layer tp all-gathers) across the DCN boundary — a
    # silent order-of-magnitude step-time regression.
    by_slice: dict = {}
    for d in devices:
        by_slice.setdefault(getattr(d, "slice_index", 0), []).append(d)
    if len(by_slice) > 1:
        sizes = {s: len(v) for s, v in by_slice.items()}
        if len(by_slice) != n_slices or len(set(sizes.values())) != 1:
            raise ValueError(
                f"hardware reports {len(by_slice)} slice(s) of sizes "
                f"{sizes}, but n_slices={n_slices} equal slices were "
                f"requested — the dcn axis must align with physical "
                f"slice boundaries"
            )
        devices = [d for s in sorted(by_slice) for d in by_slice[s]]
    if len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices not divisible into {n_slices} slices"
        )
    per_slice = len(devices) // n_slices
    config = (config or MeshConfig()).resolve(per_slice)
    dev_array = np.asarray(devices[: n_slices * per_slice]).reshape(
        (n_slices,) + config.shape
    )
    mesh = Mesh(dev_array, (DCN_AXIS,) + AXIS_ORDER)
    set_current_mesh(mesh)
    # model-internal constrain() calls must see the dcn-aware "batch"
    # rule, or every constrained activation replicates across slices
    from ray_tpu.parallel import sharding as _sharding

    _sharding.set_active_rules(_sharding.MULTISLICE_RULES)
    return mesh
