"""Device-mesh construction for SPMD parallelism.

The TPU-native replacement for the reference's process-group bootstrap
(ray: python/ray/train/torch/config.py:112 `dist.init_process_group`,
ray: python/ray/util/collective/collective.py:120): instead of wiring a
NCCL communicator between worker processes, we build a
`jax.sharding.Mesh` over the slice's devices and let XLA compile
collectives onto ICI.

Axis convention (outer → inner, matching physical locality on a pod):

  dp    data parallelism (pure replication of params, gradient psum)
  fsdp  fully-sharded data parallelism (params sharded, all-gathered
        per layer; gradients reduce-scattered)
  ep    expert parallelism (MoE experts sharded; token dispatch is an
        all_to_all over this axis)
  sp    sequence/context parallelism (ring attention neighbors — must
        map to an ICI ring)
  tp    tensor/model parallelism (innermost: highest-bandwidth axis)

Any axis may have size 1; the mesh is always constructed with all five
named axes so sharding rules never need to special-case missing axes.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DP_AXIS = "dp"
FSDP_AXIS = "fsdp"
EP_AXIS = "ep"
SP_AXIS = "sp"
TP_AXIS = "tp"

#: Mesh axes ordered outer→inner. dp/fsdp vary slowest (their collectives
#: tolerate the most latency: once-per-step gradient reductions), tp varies
#: fastest (per-layer all-gathers/reduce-scatters want nearest neighbors).
AXIS_ORDER = (DP_AXIS, FSDP_AXIS, EP_AXIS, SP_AXIS, TP_AXIS)

#: Axes over which a gradient psum runs for data parallelism.
DATA_AXES = (DP_AXIS, FSDP_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical shape of the device mesh.

    ``-1`` for at most one axis means "absorb all remaining devices",
    mirroring the reference's ScalingConfig(num_workers=...) ergonomics
    (ray: python/ray/air/config.py:103) but in mesh terms.
    """

    dp: int = -1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        sizes = {"dp": self.dp, "fsdp": self.fsdp, "ep": self.ep,
                 "sp": self.sp, "tp": self.tp}
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {wild}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n_devices}"
            )
        return MeshConfig(**sizes)

    @property
    def shape(self) -> tuple:
        return (self.dp, self.fsdp, self.ep, self.sp, self.tp)

    def describe(self) -> str:
        return "x".join(
            f"{a}={s}" for a, s in zip(AXIS_ORDER, self.shape) if s != 1
        ) or "single-device"


#: Process-wide active mesh, set by make_mesh / set_current_mesh.  Library
#: code (ring attention, train steps) that needs the concrete mesh for
#: shard_map fetches it here rather than threading it through every call.
_CURRENT_MESH: Optional[Mesh] = None


def set_current_mesh(mesh: Optional[Mesh]) -> None:
    global _CURRENT_MESH
    _CURRENT_MESH = mesh


def current_mesh() -> Optional[Mesh]:
    return _CURRENT_MESH


def use(mesh: Mesh):
    """Context manager binding ``mesh`` for PartitionSpec resolution."""
    return jax.set_mesh(mesh)


def make_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build the 5-axis mesh over ``devices`` (default: all local devices).

    Uses `jax.experimental.mesh_utils` device ordering when available so
    the innermost axes land on physically adjacent chips (ICI neighbors);
    falls back to a plain reshape on CPU meshes where topology is flat.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    config = (config or MeshConfig()).resolve(len(devices))
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(
            config.shape, devices=devices
        )
    except ImportError:
        dev_array = np.asarray(devices).reshape(config.shape)
    except Exception as e:
        # A failed topology-aware layout on real hardware means sp/tp
        # neighbors may not be ICI-adjacent — degraded, not incorrect,
        # so warn loudly instead of failing or silently falling back.
        import warnings

        warnings.warn(
            f"mesh_utils.create_device_mesh failed ({e!r}); falling back "
            f"to flat device order — collective bandwidth may suffer"
        )
        dev_array = np.asarray(devices).reshape(config.shape)
    mesh = Mesh(dev_array, AXIS_ORDER)
    set_current_mesh(mesh)
    return mesh
