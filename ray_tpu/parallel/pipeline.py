"""Pipeline parallelism: GPipe-style microbatching inside one XLA program.

The TPU-native form of pipeline execution (SURVEY §2.4 item 8's
cross-host half): stages live on devices along a dedicated ``pp`` mesh
axis, activations move stage-to-stage with `lax.ppermute` over ICI, and
the whole schedule — including the backward pass, which jax derives
through the ppermute — is ONE compiled program.  No per-stage actors,
no host round-trips, no NCCL send/recv loops: the compiler overlaps the
permute with compute where the schedule allows.

Intra-host/actor pipelining over channels is the other half
(ray_tpu/dag compiled DAGs); this module is the in-program path that
scales across a slice.

Model contract: a STAGE function `stage_fn(stage_params, x) -> x` where
`stage_params` is one pytree slice of per-stage-stacked params
(leading dim = n_stages, like the models' scan-stacked layers).  The
classic GPipe loop runs n_micro + n_stages - 1 ticks; each device
computes its stage when a microbatch is resident and forwards the
activation to its pp-neighbor.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import PP_AXIS  # the shared 6-axis mesh's axis


def make_pp_mesh(n_stages: int, devices=None) -> Mesh:
    """A 1-axis pipeline mesh over `n_stages` devices."""
    if devices is None:
        devices = jax.devices()
    if len(devices) < n_stages:
        raise ValueError(
            f"pipeline needs {n_stages} devices, have {len(devices)}"
        )
    return Mesh(np.array(devices[:n_stages]), (PP_AXIS,))


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    n_micro: int,
):
    """Run the pipelined forward inside shard_map over the pp axis.

    stage_params: per-stage-stacked pytree with LOCAL slice (1, ...)
    per device (shard_map has already split the leading dim).
    x: (n_micro, mb, ...) microbatched input, resident on every stage
    (only stage 0 reads it).  Returns (n_micro, mb, ...) outputs valid
    on the LAST stage.
    """
    idx = lax.axis_index(PP_AXIS)
    n_stages = lax.axis_size(PP_AXIS)
    for path, leaf in jax.tree_util.tree_flatten_with_path(stage_params)[0]:
        if leaf.shape[0] != 1:
            raise ValueError(
                f"stage param {jax.tree_util.keystr(path)} has "
                f"{leaf.shape[0]} stages on one device — the stacked "
                "leading dim must equal the pp mesh size (got a local "
                f"slice of {leaf.shape[0]}; stages would be silently "
                "dropped)"
            )
    local = jax.tree.map(lambda a: a[0], stage_params)
    mb_shape = x.shape[1:]
    n_ticks = n_micro + n_stages - 1

    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        buf, outputs = carry  # buf: activation resident on this stage
        # stage 0 ingests microbatch t (if one remains); others use buf
        feed = jnp.where(
            t < n_micro,
            lax.dynamic_index_in_dim(x, jnp.minimum(t, n_micro - 1), 0,
                                     keepdims=False),
            jnp.zeros(mb_shape, x.dtype),
        )
        inp = jnp.where(idx == 0, feed, buf)
        out = stage_fn(local, inp)
        # last stage records its finished microbatch (micro index
        # t - (n_stages - 1)); branchless select keeps SPMD happy
        out_slot = t - (n_stages - 1)
        do_write = (idx == n_stages - 1) & (out_slot >= 0)
        updated = lax.dynamic_update_index_in_dim(
            outputs, out, jnp.clip(out_slot, 0, n_micro - 1), 0
        )
        outputs = jnp.where(do_write, updated, outputs)
        buf = lax.ppermute(out, PP_AXIS, fwd_perm)
        return (buf, outputs), None

    # the carry becomes device-varying over pp after the first tick;
    # mark the (replicated) zeros as varying up front so scan's carry
    # types line up under shard_map's vma typing
    buf0 = lax.pcast(
        jnp.zeros(mb_shape, x.dtype), (PP_AXIS,), to="varying"
    )
    outputs0 = lax.pcast(
        jnp.zeros((n_micro,) + mb_shape, x.dtype), (PP_AXIS,), to="varying"
    )
    (_, outputs), _ = lax.scan(
        tick, (buf0, outputs0), jnp.arange(n_ticks)
    )
    return outputs


def pipeline_train_step(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    loss_tail: Callable[[jax.Array, Any], jax.Array],
    optimizer,
    mesh: Mesh,
    *,
    n_micro: int,
):
    """Build `step(params, opt_state, x, y) -> (params, opt_state, loss)`
    with the whole pipelined fwd+bwd+update as one jitted program.

    params: per-stage-stacked pytree (n_stages, ...), sharded over pp on
    the leading dim.  loss_tail(last_stage_outputs (n_micro, mb, ...),
    y (n_micro, mb, ...)) -> scalar — evaluated on the last stage's
    results (replicated by the psum below).
    """
    n_stages = mesh.shape[PP_AXIS]

    def sharded_loss(params, x, y):
        def inner(p, xx, yy):
            outs = pipeline_apply(stage_fn, p, xx, n_micro=n_micro)
            idx = lax.axis_index(PP_AXIS)
            loss = loss_tail(outs, yy)
            # only the last stage holds real outputs; psum broadcasts its
            # loss (others contribute 0) so the value is well-defined
            # everywhere and grads flow backward through the ppermutes
            loss = jnp.where(idx == n_stages - 1, loss, 0.0)
            return lax.psum(loss, PP_AXIS)

        return jax.shard_map(
            inner,
            mesh=mesh,
            in_specs=(P(PP_AXIS), P(), P()),
            out_specs=P(),
        )(params, x, y)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(sharded_loss)(params, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step


def stage_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for per-stage-stacked params (leading dim over pp)."""
    return NamedSharding(mesh, P(PP_AXIS))


def tailed_pipeline_train_step(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    prelude: Callable[[Any, jax.Array], jax.Array],
    loss_tail: Callable[[Any, jax.Array, jax.Array], jax.Array],
    optimizer,
    mesh: Mesh,
    *,
    n_micro: int,
    _check_vma: bool = False,
):
    """Pipeline step for models with non-stage params (embeddings, final
    norm, lm head) — the shape of a real transformer, composed with the
    OTHER mesh axes: shard_map is manual over `pp` only, so dp/fsdp/tp
    shardings on the params keep working through GSPMD's auto
    propagation (jax partial-manual shard_map, `axis_names={'pp'}`).

    params pytree: {"stages": per-stage-stacked pytree (n_stages,
    layers_per_stage, ...), "tail": everything else}.
      prelude(tail, x_micro)     -> activations (n_micro, mb, ...); runs
                                    replicated on every stage (embedding
                                    lookup — cheap vs a pp-scatter)
      stage_fn(stage_slice, h)   -> h for one stage's layers
      loss_tail(tail, outs, y)   -> scalar on the last stage's outputs
    """
    n_stages = mesh.shape[PP_AXIS]

    def sharded_loss(params, x, y):
        def inner(p, xx, yy):
            from ray_tpu.parallel import sharding as sharding_mod

            with sharding_mod.no_constraints():
                h = prelude(p["tail"], xx)
                outs = pipeline_apply(
                    stage_fn, p["stages"], h, n_micro=n_micro
                )
                idx = lax.axis_index(PP_AXIS)
                loss = loss_tail(p["tail"], outs, yy)
            loss = jnp.where(idx == n_stages - 1, loss, 0.0)
            return lax.psum(loss, PP_AXIS)

        # prefix specs: stages split on the stacked leading dim over pp,
        # tail replicated across pp; all other axes stay automatic
        in_specs = (
            {
                "stages": jax.tree.map(lambda _: P(PP_AXIS), params["stages"]),
                "tail": jax.tree.map(lambda _: P(), params["tail"]),
            },
            P(),
            P(),
        )
        # check_vma=False: with manual-over-pp only, the vma type checker
        # feeds the backward pass an HLO 'copy' binop that aborts XLA's
        # CPU backend (jax 0.9, "Invalid binary instruction opcode
        # copy"); the pipeline's own pcasts already make the carry types
        # consistent.  tests/test_pipeline.py's canary runs this exact
        # path with _check_vma=True and fails LOUDLY the day a jax
        # upgrade fixes the crash, so the checker opt-out cannot
        # silently outlive the bug it works around.
        return jax.shard_map(
            inner,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=P(),
            axis_names=frozenset({PP_AXIS}),
            check_vma=_check_vma,
        )(params, x, y)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(sharded_loss)(params, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step
