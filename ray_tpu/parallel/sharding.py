"""Logical-axis sharding rules.

Model code annotates every parameter and activation with *logical* axis
names ("embed", "heads", "batch", ...); a rule table maps logical names
to mesh axes.  Switching between pure-DP, FSDP, TP, and combinations is
then a rule-table swap — no model changes.  This is the TPU-native
counterpart of the reference delegating sharding to torch FSDP/DeepSpeed
inside the user's train loop (ray: python/ray/train/torch/train_loop_utils.py:158,
SURVEY.md §2.4 item 4): here sharding is a first-class framework concept
compiled by XLA rather than a wrapper library.

A *spec* is a tuple of logical axis names (or None), one per array dim:

    ("batch", "seq", "embed")       activations
    ("embed", "mlp")                MLP kernel
    (None,)                         bias replicated everywhere
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ray_tpu.parallel.mesh import (
    DCN_AXIS,
    DP_AXIS,
    EP_AXIS,
    FSDP_AXIS,
    SP_AXIS,
    TP_AXIS,
)

LogicalSpec = Tuple[Optional[str], ...]
Rules = Tuple[Tuple[str, Union[str, Tuple[str, ...], None]], ...]

#: Default rule table, tuned for a decoder-only LM:
#:  - batch splits over both data axes,
#:  - params shard their largest dim over fsdp and their "parallel" dim
#:    (heads / mlp / vocab) over tp — the Megatron layout,
#:  - sequence dims of activations split over sp for context parallelism.
DEFAULT_RULES: Rules = (
    ("batch", (DP_AXIS, FSDP_AXIS)),
    ("seq", SP_AXIS),
    ("embed", FSDP_AXIS),
    ("heads", TP_AXIS),
    ("kv", None),
    ("mlp", TP_AXIS),
    ("vocab", TP_AXIS),
    ("layers", None),
    ("expert", EP_AXIS),
)

#: Multi-slice variant: the batch additionally splits over the dcn axis
#: (data parallelism across slices — the only collective that should
#: cross the inter-slice fabric is the once-per-step gradient psum).
MULTISLICE_RULES: Rules = (
    ("batch", (DCN_AXIS, DP_AXIS, FSDP_AXIS)),
) + tuple(r for r in DEFAULT_RULES if r[0] != "batch")

#: Process-wide ACTIVE rule table.  Model-internal constrain() calls
#: cannot thread an explicit table through every layer, so mesh
#: construction installs the right one: make_multislice_mesh swaps in
#: MULTISLICE_RULES (otherwise a "batch" constraint inside a block would
#: mean "replicated over dcn" and XLA would all-gather activations
#: across the inter-slice fabric at every layer).
_active_rules: Rules = DEFAULT_RULES


def set_active_rules(rules: Rules) -> None:
    global _active_rules
    _active_rules = rules


def active_rules() -> Rules:
    return _active_rules


def logical_to_spec(
    logical: Sequence[Optional[str]], rules: Optional[Rules] = None
) -> PartitionSpec:
    """Map a tuple of logical axis names to a PartitionSpec via ``rules``
    (default: the process-wide active table)."""
    table = dict(rules if rules is not None else _active_rules)
    used = set()
    out = []
    for name in logical:
        mesh_axes = table.get(name) if name is not None else None
        if mesh_axes is None:
            out.append(None)
            continue
        if isinstance(mesh_axes, str):
            mesh_axes = (mesh_axes,)
        # A mesh axis may only shard one dim of a given array.
        free = tuple(a for a in mesh_axes if a not in used)
        used.update(free)
        if not free:
            out.append(None)
        elif len(free) == 1:
            out.append(free[0])
        else:
            out.append(free)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def tree_shardings(mesh: Mesh, logical_tree, rules: Optional[Rules] = None):
    """Map a pytree of logical specs to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, logical_to_spec(spec, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(isinstance(e, (str, type(None))) for e in x),
    )


_constraints_disabled = False


class no_constraints:
    """Trace-time scope that turns `constrain` into identity.

    Pipeline stages trace under a partial-manual shard_map (manual over
    `pp` only); sharding constraints on the remaining auto axes are
    unreliable there — GSPMD propagates layouts from the parameter
    shardings instead.  Tracing is single-threaded per program, so a
    module flag (not a contextvar) is sufficient."""

    def __enter__(self):
        global _constraints_disabled
        self._prev = _constraints_disabled
        _constraints_disabled = True

    def __exit__(self, *exc):
        global _constraints_disabled
        _constraints_disabled = self._prev


def constrain(x, logical: Sequence[Optional[str]], rules: Optional[Rules] = None):
    """with_sharding_constraint by logical names (no-op outside a mesh).

    Only the "no mesh in scope" case is treated as identity; genuine
    spec errors (rank mismatch etc.) propagate.
    """
    if _constraints_disabled:
        return x
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or getattr(mesh, "empty", True):
        return x
    return jax.lax.with_sharding_constraint(x, logical_to_spec(logical, rules))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
