"""Logical-axis sharding rules.

Model code annotates every parameter and activation with *logical* axis
names ("embed", "heads", "batch", ...); a rule table maps logical names
to mesh axes.  Switching between pure-DP, FSDP, TP, and combinations is
then a rule-table swap — no model changes.  This is the TPU-native
counterpart of the reference delegating sharding to torch FSDP/DeepSpeed
inside the user's train loop (ray: python/ray/train/torch/train_loop_utils.py:158,
SURVEY.md §2.4 item 4): here sharding is a first-class framework concept
compiled by XLA rather than a wrapper library.

A *spec* is a tuple of logical axis names (or None), one per array dim:

    ("batch", "seq", "embed")       activations
    ("embed", "mlp")                MLP kernel
    (None,)                         bias replicated everywhere
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ray_tpu.parallel.mesh import (
    DP_AXIS,
    EP_AXIS,
    FSDP_AXIS,
    SP_AXIS,
    TP_AXIS,
)

LogicalSpec = Tuple[Optional[str], ...]
Rules = Tuple[Tuple[str, Union[str, Tuple[str, ...], None]], ...]

#: Default rule table, tuned for a decoder-only LM:
#:  - batch splits over both data axes,
#:  - params shard their largest dim over fsdp and their "parallel" dim
#:    (heads / mlp / vocab) over tp — the Megatron layout,
#:  - sequence dims of activations split over sp for context parallelism.
DEFAULT_RULES: Rules = (
    ("batch", (DP_AXIS, FSDP_AXIS)),
    ("seq", SP_AXIS),
    ("embed", FSDP_AXIS),
    ("heads", TP_AXIS),
    ("kv", None),
    ("mlp", TP_AXIS),
    ("vocab", TP_AXIS),
    ("layers", None),
    ("expert", EP_AXIS),
)


def logical_to_spec(
    logical: Sequence[Optional[str]], rules: Rules = DEFAULT_RULES
) -> PartitionSpec:
    """Map a tuple of logical axis names to a PartitionSpec via ``rules``."""
    table = dict(rules)
    used = set()
    out = []
    for name in logical:
        mesh_axes = table.get(name) if name is not None else None
        if mesh_axes is None:
            out.append(None)
            continue
        if isinstance(mesh_axes, str):
            mesh_axes = (mesh_axes,)
        # A mesh axis may only shard one dim of a given array.
        free = tuple(a for a in mesh_axes if a not in used)
        used.update(free)
        if not free:
            out.append(None)
        elif len(free) == 1:
            out.append(free[0])
        else:
            out.append(free)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def tree_shardings(mesh: Mesh, logical_tree, rules: Rules = DEFAULT_RULES):
    """Map a pytree of logical specs to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, logical_to_spec(spec, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(isinstance(e, (str, type(None))) for e in x),
    )


def constrain(x, logical: Sequence[Optional[str]], rules: Rules = DEFAULT_RULES):
    """with_sharding_constraint by logical names (no-op outside a mesh).

    Only the "no mesh in scope" case is treated as identity; genuine
    spec errors (rank mismatch etc.) propagate.
    """
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or getattr(mesh, "empty", True):
        return x
    return jax.lax.with_sharding_constraint(x, logical_to_spec(logical, rules))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
