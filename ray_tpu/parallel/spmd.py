"""Compile a sharded training step over a mesh.

This is where the reference's DDP/FSDP wrapper layer
(ray: python/ray/train/torch/train_loop_utils.py:158 `prepare_model`)
collapses to: params and optimizer state are laid out by the logical-axis
rule table, the whole step is one pjit'd program, and XLA inserts the
gradient reductions (all-reduce over dp, reduce-scatter over fsdp) and
per-layer all-gathers over ICI.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ray_tpu.parallel.mesh import DATA_AXES, SP_AXIS
from ray_tpu.parallel.sharding import (
    DEFAULT_RULES,
    Rules,
    logical_to_spec,
    tree_shardings,
)


class TrainState(NamedTuple):
    step: Any
    params: Any
    opt_state: Any


def batch_sharding(mesh: Mesh, *, shard_seq: bool = False) -> NamedSharding:
    """Input batch layout: batch dim over the data axes (plus dcn,
    outermost, on a multi-slice mesh), optionally seq over sp."""
    from ray_tpu.parallel.mesh import DCN_AXIS

    data_axes = tuple(DATA_AXES)
    if DCN_AXIS in mesh.axis_names:
        data_axes = (DCN_AXIS,) + data_axes
    if shard_seq:
        return NamedSharding(mesh, PartitionSpec(data_axes, SP_AXIS))
    return NamedSharding(mesh, PartitionSpec(data_axes))


def shard_batch(mesh: Mesh, batch, *, shard_seq: bool = False):
    """Place a host-side batch pytree onto the mesh, batch-dim sharded."""
    sh = batch_sharding(mesh, shard_seq=shard_seq)
    return jax.tree.map(lambda x: jax.device_put(x, sh), batch)


def _match_param_subtrees(
    state_shape, default_shardings, param_shardings, param_shape
):
    """Replace any opt-state subtree structurally identical to the param
    tree with the param shardings, so adam mu/nu (etc.) shard like their
    params; everything else keeps ``default_shardings`` (replicated).

    A subtree must match the param tree's structure AND its leaf shapes:
    structure alone misfires when params is a single bare array, because
    every leaf (e.g. adam's scalar step count) has the same leaf treedef.
    """
    param_struct = jax.tree.structure(param_shardings)
    param_leaf_shapes = [a.shape for a in jax.tree.leaves(param_shape)]

    def _shapes_match(node):
        leaves = jax.tree.leaves(node)
        return len(leaves) == len(param_leaf_shapes) and all(
            getattr(a, "shape", None) == s
            for a, s in zip(leaves, param_leaf_shapes)
        )

    def rec(shape_node, shard_node):
        try:
            if jax.tree.structure(shape_node) == param_struct and _shapes_match(
                shape_node
            ):
                return param_shardings
        except Exception:
            pass
        if hasattr(shape_node, "_fields"):
            return type(shape_node)(
                **{
                    f: rec(getattr(shape_node, f), getattr(shard_node, f))
                    for f in shape_node._fields
                }
            )
        if isinstance(shape_node, tuple):
            return tuple(rec(a, b) for a, b in zip(shape_node, shard_node))
        if isinstance(shape_node, list):
            return [rec(a, b) for a, b in zip(shape_node, shard_node)]
        if isinstance(shape_node, dict):
            return {k: rec(shape_node[k], shard_node[k]) for k in shape_node}
        return shard_node

    return rec(state_shape, default_shardings)


def _full_init(init_fn: Callable, optimizer: optax.GradientTransformation):
    """The one definition of how a fresh TrainState is built."""

    def go(rng):
        params = init_fn(rng)
        return TrainState(
            jnp.zeros((), jnp.int32), params, optimizer.init(params)
        )

    return go


def state_shardings(
    mesh: Mesh,
    init_fn: Callable,
    rng,
    param_logical,
    optimizer: optax.GradientTransformation,
    rules: Optional[Rules] = None,
) -> TrainState:
    """Compute the TrainState sharding tree without materializing anything."""
    param_shardings = tree_shardings(mesh, param_logical, rules)
    rep = NamedSharding(mesh, PartitionSpec())

    state_shape = jax.eval_shape(_full_init(init_fn, optimizer), rng)
    opt_shardings = jax.tree.map(lambda _: rep, state_shape.opt_state)
    opt_shardings = _match_param_subtrees(
        state_shape.opt_state, opt_shardings, param_shardings,
        state_shape.params,
    )
    return TrainState(rep, param_shardings, opt_shardings)


def sharded_init(
    mesh: Mesh,
    init_fn: Callable,
    rng,
    param_logical,
    optimizer: Optional[optax.GradientTransformation] = None,
    rules: Optional[Rules] = None,
) -> TrainState:
    """Initialize params + optimizer state directly into their shardings.

    Runs init under jit with out_shardings so each device materializes
    only its own parameter shards — a large model on 256 chips never
    exists unsharded anywhere.
    """
    optimizer = optimizer or optax.identity()
    # pre-check divisibility so a mismatch (e.g. num_experts=6 on ep=4)
    # surfaces as a clear error naming the param and axis, not a GSPMD
    # partitioning failure deep inside jit
    shapes = jax.eval_shape(init_fn, rng)

    def check(path, leaf, logical):
        # tree_map_with_path walks BOTH trees together, so a structure
        # mismatch between init_fn's output and param_logical raises a
        # tree error naming the spot instead of silently mispairing
        spec = logical_to_spec(logical, rules)
        for dim, axis in zip(leaf.shape, spec):
            if axis is None:
                continue
            axes = axis if isinstance(axis, tuple) else (axis,)
            n = 1
            for a in axes:
                n *= mesh.shape[a]
            if n > 1 and dim % n:
                name = jax.tree_util.keystr(path)
                raise ValueError(
                    f"param {name} dim of size {dim} (logical axes "
                    f"{logical}) is not divisible by mesh axis "
                    f"{axis} of size {n}; adjust the model config or "
                    "the mesh shape"
                )
        return leaf

    jax.tree_util.tree_map_with_path(check, shapes, param_logical)
    out_shardings = state_shardings(
        mesh, init_fn, rng, param_logical, optimizer, rules
    )
    with jax.set_mesh(mesh):
        return jax.jit(
            _full_init(init_fn, optimizer), out_shardings=out_shardings
        )(rng)


def compile_train_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
    *,
    donate: bool = True,
):
    """Build `step(state, batch) -> (state, metrics)`.

    Shardings are carried by the arrays themselves (see sharded_init /
    shard_batch): jit propagates them, and the gradient cross-shard
    reductions are emitted by XLA because the loss is batch-sharded
    while params are dp-replicated / fsdp-sharded.
    """

    def step(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        return (
            TrainState(state.step + 1, params, opt_state),
            {"loss": loss, "grad_norm": gnorm},
        )

    return jax.jit(step, donate_argnums=(0,) if donate else ())
