"""ICI collective primitives.

The TPU replacement for ray.util.collective's NCCL backend
(ray: python/ray/util/collective/collective_group/nccl_collective_group.py):
collectives are not runtime calls between processes but XLA ops compiled
into the program, executing over ICI links of the mesh.  These wrappers
exist so library code (ring attention, gradient sync, MoE dispatch)
names the axis it communicates over instead of hard-coding lax calls.

All of these must run inside `shard_map` / pjit-manual contexts where the
named axes of the mesh are bound.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]


def allreduce_sum(x, axis: AxisName):
    return lax.psum(x, axis)


def allreduce_mean(x, axis: AxisName):
    return lax.pmean(x, axis)


def allgather(x, axis: str, *, dim: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis, axis=dim, tiled=tiled)


def reducescatter_sum(x, axis: str, *, dim: int = 0):
    return lax.psum_scatter(x, axis, scatter_dimension=dim, tiled=True)


def all_to_all(x, axis: str, *, split_dim: int, concat_dim: int):
    return lax.all_to_all(
        x, axis, split_axis=split_dim, concat_axis=concat_dim, tiled=True
    )


def ring_permute(x, axis: str, *, shift: int = 1):
    """Send ``x`` to the neighbor ``shift`` steps ahead on the axis ring.

    The building block of ring attention and pipeline schedules; XLA
    lowers it to a ppermute over ICI neighbors.
    """
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm=perm)


def broadcast_from(x, axis: str, *, root: int = 0):
    """Replicate the value held at ``root`` to all shards on ``axis``."""
    idx = lax.axis_index(axis)
    zeroed = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(zeroed, axis)


def barrier(x, axis: AxisName):
    """Cross-shard rendezvous threaded through ``x``.

    Returns ``x`` with a data dependency on a 1-element psum over
    ``axis`` — the caller MUST use the returned value, otherwise XLA
    dead-code-eliminates the collective and no rendezvous happens
    (which is why this takes and returns a carrier instead of being a
    bare statement).
    """
    tick = lax.psum(jnp.ones((), jnp.int32), axis)
    # (tick - tick) == 0 always, but keeps the psum live in the graph.
    return jax.tree.map(lambda a: a + (tick - tick).astype(a.dtype), x)


class XlaInProgramBackend:
    """The in-program face of the shared collective-backend registry
    (``ray_tpu.util.collective.backend``, registered as ``"xla"``).

    Same op *names* as the runtime backends, different regime: these
    take jax arrays + a mesh axis name and MUST be called inside
    ``shard_map``/pjit-manual contexts — they compile into the program
    and execute over ICI, they do not move runtime tensors between
    actors.  ``init_collective_group`` refuses this backend for runtime
    groups and points here instead; library code that wants one
    namespace for both regimes dispatches on
    ``ray_tpu.util.collective.available_backends()`` kinds.
    """

    kind = "in_program"

    @staticmethod
    def allreduce(x, axis: AxisName, op: str = "sum"):
        if op == "sum":
            return allreduce_sum(x, axis)
        if op == "mean":
            return allreduce_mean(x, axis)
        if op == "max":
            return lax.pmax(x, axis)
        if op == "min":
            return lax.pmin(x, axis)
        raise ValueError(f"unsupported in-program reduce op {op!r}")

    @staticmethod
    def allgather(x, axis: str, *, dim: int = 0, tiled: bool = True):
        return allgather(x, axis, dim=dim, tiled=tiled)

    @staticmethod
    def reducescatter(x, axis: str, *, dim: int = 0):
        return reducescatter_sum(x, axis, dim=dim)

    @staticmethod
    def broadcast(x, axis: str, *, root: int = 0):
        return broadcast_from(x, axis, root=root)

    @staticmethod
    def barrier(x, axis: AxisName):
        return barrier(x, axis)

    @staticmethod
    def all_to_all(x, axis: str, *, split_dim: int, concat_dim: int):
        return all_to_all(x, axis, split_dim=split_dim,
                          concat_dim=concat_dim)

    @staticmethod
    def ring_permute(x, axis: str, *, shift: int = 1):
        return ring_permute(x, axis, shift=shift)
