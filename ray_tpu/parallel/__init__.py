"""SPMD parallelism over JAX device meshes (dp/fsdp/ep/sp/tp + pp).

See SURVEY.md §2.4: the reference delegates model sharding to external
libraries; here it is native.  Mesh construction (`mesh`), logical-axis
sharding rules (`sharding`), ICI collective wrappers (`collectives`),
and in-program GPipe pipeline parallelism (`pipeline`).
"""

from ray_tpu.parallel.mesh import (  # noqa: F401
    AXIS_ORDER,
    DATA_AXES,
    DP_AXIS,
    EP_AXIS,
    FSDP_AXIS,
    PP_AXIS,
    SP_AXIS,
    TP_AXIS,
    MeshConfig,
    make_mesh,
)
from ray_tpu.parallel.sharding import (  # noqa: F401
    DEFAULT_RULES,
    constrain,
    logical_to_spec,
    replicated,
    tree_shardings,
)
from ray_tpu.parallel import collectives  # noqa: F401
from ray_tpu.parallel import pipeline  # noqa: F401
