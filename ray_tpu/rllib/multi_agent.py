"""Multi-agent RL: env API, episode collection, and multi-policy PPO.

Role-equivalent of ray: rllib's multi-agent stack
(rllib/env/multi_agent_env.py MultiAgentEnv,
rllib/env/multi_agent_episode.py:33 MultiAgentEpisode, and the
policies= / policy_mapping_fn= config surface) reduced to the
functional-jax shapes of this stack: each policy is an independent
params pytree with its own PPOLearner, a runner actor steps ONE
multi-agent env collecting per-policy episode streams, and GAE runs per
agent stream at episode end — whole episodes per fragment, bootstrapping
0 at true termination and V(s_T) at truncation, with no cross-fragment
value stitching.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib import core
from ray_tpu.rllib.algorithm import Algorithm, probe_env_spaces
from ray_tpu.rllib.ppo import PPOConfig, PPOLearner


class MultiAgentEnv:
    """Dict-keyed env contract (ray: MultiAgentEnv).

    reset() -> (obs_dict, info_dict)
    step(action_dict) -> (obs_dict, reward_dict, terminated_dict,
                          truncated_dict, info_dict)
    terminated_dict/truncated_dict carry per-agent flags plus the
    "__all__" episode-end flag.  Only agents present in obs_dict act on
    the next step (agents may come and go mid-episode)."""

    possible_agents: List[str] = []

    def reset(self, *, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError


@dataclasses.dataclass
class MultiAgentPPOConfig(PPOConfig):
    #: policy ids; each gets independent params + learner
    policies: tuple = ("default",)
    #: agent_id -> policy id (defaults to everyone on policies[0])
    policy_mapping_fn: Optional[Callable[[str], str]] = None
    episodes_per_runner_sample: int = 4

    def multi_agent(self, *, policies, policy_mapping_fn=None):
        return dataclasses.replace(
            self,
            policies=tuple(policies),
            policy_mapping_fn=policy_mapping_fn,
        )


@ray_tpu.remote
class MultiAgentEnvRunner:
    """Steps one MultiAgentEnv, batching per-step inference per policy
    and emitting per-policy PPO-ready episode batches."""

    def __init__(self, env_fn, module_config, policies, mapping_fn,
                 seed: int, gamma: float, lambda_: float):
        import jax

        self._env = env_fn()
        self._policies = list(policies)
        self._map = mapping_fn or (lambda aid: self._policies[0])
        self._gamma = gamma
        self._lambda = lambda_
        self._params = {
            p: core.module_init(jax.random.key(seed + i), module_config)
            for i, p in enumerate(self._policies)
        }
        sample_fn, _ = core.make_sample_fns(module_config)
        self._sample = jax.jit(sample_fn)
        self._rng = jax.random.key(seed + 10_000)
        self._seed = seed
        self._episode = 0

    def set_weights(self, params_by_policy) -> bool:
        self._params.update(params_by_policy)
        return True

    def sample(self, num_episodes: int):
        import jax

        streams: Dict[str, Dict[str, list]] = {
            p: {"obs": [], "actions": [], "logp": [], "advantages": [],
                "returns": []}
            for p in self._policies
        }
        episode_returns = []
        for _ in range(num_episodes):
            self._episode += 1
            obs_d, _ = self._env.reset(seed=self._seed + self._episode)
            # per-agent episode records
            rec: Dict[str, Dict[str, list]] = {}
            # rewards credited to an agent BEFORE its first action
            # (late joiners): deferred onto its first recorded step
            pending_rew: Dict[str, float] = {}
            ep_return = 0.0
            while True:
                agents = list(obs_d)
                # one batched forward PER POLICY over its agents
                actions: Dict[str, int] = {}
                by_policy: Dict[str, list] = {}
                for aid in agents:
                    by_policy.setdefault(self._map(aid), []).append(aid)
                for pid, aids in by_policy.items():
                    batch = np.stack(
                        [np.asarray(obs_d[a], np.float32).ravel()
                         for a in aids]
                    )
                    self._rng, sub = jax.random.split(self._rng)
                    act, logp, value = self._sample(
                        self._params[pid], batch, sub
                    )
                    act = np.asarray(act)
                    logp = np.asarray(logp)
                    value = np.asarray(value)
                    for j, aid in enumerate(aids):
                        actions[aid] = int(act[j])
                        r = rec.setdefault(aid, {
                            "pid": pid, "obs": [], "actions": [],
                            "logp": [], "values": [], "rewards": [],
                        })
                        r["obs"].append(batch[j])
                        r["actions"].append(int(act[j]))
                        r["logp"].append(float(logp[j]))
                        r["values"].append(float(value[j]))
                        # placeholder keeps rewards aligned with actions
                        # even when the env omits a reward this step;
                        # deferred pre-action rewards land here
                        r["rewards"].append(pending_rew.pop(aid, 0.0))
                obs_d, rew_d, term_d, trunc_d, _ = self._env.step(actions)
                for aid, rew in rew_d.items():
                    ep_return += float(rew)
                    if aid in rec and rec[aid]["rewards"]:
                        # credited to the agent's LAST acted step — also
                        # captures late rewards for agents absent from
                        # this step's obs (e.g. terminal team rewards)
                        rec[aid]["rewards"][-1] += float(rew)
                    else:
                        # reward before the agent's first action (late
                        # joiner): defer to its first step
                        pending_rew[aid] = (
                            pending_rew.get(aid, 0.0) + float(rew)
                        )
                terminated = bool(term_d.get("__all__"))
                truncated = bool(trunc_d.get("__all__"))
                if terminated or truncated:
                    break
            episode_returns.append(ep_return)
            # Truncated (time-limit) episodes bootstrap from V(s_T); true
            # termination bootstraps 0.
            bootstrap: Dict[str, float] = {}
            if truncated and not terminated and obs_d:
                by_policy = {}
                for aid in obs_d:
                    by_policy.setdefault(self._map(aid), []).append(aid)
                for pid, aids in by_policy.items():
                    batch = np.stack(
                        [np.asarray(obs_d[a], np.float32).ravel()
                         for a in aids]
                    )
                    self._rng, sub = jax.random.split(self._rng)
                    _, _, value = self._sample(
                        self._params[pid], batch, sub
                    )
                    for j, aid in enumerate(aids):
                        bootstrap[aid] = float(np.asarray(value)[j])
            for aid, r in rec.items():
                T = len(r["actions"])
                if T == 0:
                    continue
                rewards = np.asarray(r["rewards"], np.float32)
                values = np.asarray(r["values"], np.float32)
                adv = np.zeros(T, np.float32)
                last = 0.0
                v_boot = bootstrap.get(aid, 0.0)
                for t in range(T - 1, -1, -1):
                    v_next = values[t + 1] if t + 1 < T else v_boot
                    delta = rewards[t] + self._gamma * v_next - values[t]
                    last = delta + self._gamma * self._lambda * last
                    adv[t] = last
                rets = adv + values
                s = streams[r["pid"]]
                s["obs"].extend(r["obs"])
                s["actions"].extend(r["actions"])
                s["logp"].extend(r["logp"])
                s["advantages"].extend(adv.tolist())
                s["returns"].extend(rets.tolist())
        out = {}
        for pid, s in streams.items():
            if not s["actions"]:
                continue
            adv = np.asarray(s["advantages"], np.float32)
            if adv.std() > 1e-6:
                adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            out[pid] = {
                "obs": np.stack(s["obs"]),
                "actions": np.asarray(s["actions"], np.int32),
                "logp": np.asarray(s["logp"], np.float32),
                "advantages": adv,
                "returns": np.asarray(s["returns"], np.float32),
            }
        return {"batches": out, "episode_returns": episode_returns}


class MultiAgentPPO(Algorithm):
    """One PPOLearner per policy; runners collect per-policy batches."""

    def _setup(self, config: MultiAgentPPOConfig):
        env = config.env() if callable(config.env) else config.env
        obs_d, _ = env.reset(seed=0)
        probe_obs = next(iter(obs_d.values()))
        acts = getattr(env, "num_actions", None)
        if acts is None:
            raise ValueError(
                "MultiAgentEnv must expose `num_actions` (homogeneous "
                "discrete action space)"
            )
        self.module_config = core.MLPModuleConfig(
            obs_dim=int(np.asarray(probe_obs).size),
            num_actions=int(acts),
            hidden=config.hidden,
        )
        self.learners = {
            p: PPOLearner(
                dataclasses.replace(config, seed=config.seed + i),
                self.module_config,
            )
            for i, p in enumerate(config.policies)
        }
        self.runners = [
            MultiAgentEnvRunner.options(num_cpus=0.5).remote(
                config.env, self.module_config, list(config.policies),
                config.policy_mapping_fn, config.seed + 1000 * r,
                config.gamma, config.lambda_,
            )
            for r in range(max(1, config.num_env_runners))
        ]
        self._sync()

    def _sync(self):
        w = {p: lr.params for p, lr in self.learners.items()}
        ray_tpu.get([r.set_weights.remote(w) for r in self.runners])

    def training_step(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.monotonic()
        results = ray_tpu.get([
            r.sample.remote(c.episodes_per_runner_sample)
            for r in self.runners
        ], timeout=600)
        stats: Dict[str, Any] = {}
        per_policy: Dict[str, List[dict]] = {}
        for res in results:
            self._record_returns(res["episode_returns"])
            for pid, batch in res["batches"].items():
                per_policy.setdefault(pid, []).append(batch)
        steps = 0
        for pid, batches in per_policy.items():
            merged = {
                k: np.concatenate([b[k] for b in batches])
                for k in batches[0]
            }
            steps += len(merged["actions"])
            metrics = self.learners[pid].update(merged)
            for k, v in metrics.items():
                stats[f"{pid}/{k}"] = float(v)
        self._total_steps += steps
        self._sync()
        stats["env_steps"] = steps
        stats["iter_time_s"] = time.monotonic() - t0
        return stats

    def get_state(self) -> Dict[str, Any]:
        return {"params": {p: lr.params for p, lr in self.learners.items()}}

    def set_state(self, state: Dict[str, Any]) -> None:
        for p, params in state["params"].items():
            self.learners[p].params = params
        self._sync()

    def stop(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.runners = []


MultiAgentPPOConfig.algo_class = MultiAgentPPO
