"""RLModule: the policy/value network (jax, functional).

Role-equivalent of ray: rllib/core/rl_module/rl_module.py — reduced to
the functional jax idiom: params in, (logits, value) out, so the same
module runs CPU inference in env runners and pjit'd training in the
learner.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MLPModuleConfig:
    obs_dim: int
    num_actions: int
    hidden: Tuple[int, ...] = (64, 64)


def init(rng, config: MLPModuleConfig) -> Params:
    sizes = (config.obs_dim, *config.hidden)
    keys = jax.random.split(rng, len(sizes) + 2)
    params: Params = {"layers": []}
    for i, (din, dout) in enumerate(zip(sizes[:-1], sizes[1:])):
        w = jax.random.normal(keys[i], (din, dout)) * jnp.sqrt(2.0 / din)
        params["layers"].append({"w": w, "b": jnp.zeros((dout,))})
    last = sizes[-1]
    params["pi"] = {
        "w": jax.random.normal(keys[-2], (last, config.num_actions)) * 0.01,
        "b": jnp.zeros((config.num_actions,)),
    }
    params["vf"] = {
        "w": jax.random.normal(keys[-1], (last, 1)) * 1.0,
        "b": jnp.zeros((1,)),
    }
    return params


def forward(params: Params, obs) -> Tuple[jax.Array, jax.Array]:
    """obs (B, obs_dim) → (logits (B, A), value (B,))."""
    x = obs
    for layer in params["layers"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


def _sample_fns_from_forward(fwd):
    """The single implementation of action sampling, parameterized by a
    module family's forward fn."""

    def _sample(params: Params, obs, rng):
        """Categorical sample + logp + value (env-runner inference)."""
        logits, value = fwd(params, obs)
        action = jax.random.categorical(rng, logits)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, action[:, None], axis=1)[:, 0]
        return action, logp, value

    def _sample_eps(params: Params, obs, rng, epsilon):
        """ε-greedy over the logits head read as Q-values (DQN).

        Same module, different readout: the "pi" head is the Q function
        and the value slot carries max-Q.  Returned logp is 0 —
        off-policy methods don't use it."""
        q, _ = fwd(params, obs)
        B, A = q.shape
        k_pick, k_rand = jax.random.split(rng)
        greedy = jnp.argmax(q, axis=-1)
        rand = jax.random.randint(k_rand, (B,), 0, A)
        explore = jax.random.uniform(k_pick, (B,)) < epsilon
        action = jnp.where(explore, rand, greedy)
        return action, jnp.zeros((B,)), q.max(axis=-1)

    return _sample, _sample_eps


# MLP-family globals (back-compat names)
sample_actions, sample_actions_epsilon = _sample_fns_from_forward(forward)


# ---------------------------------------------------------------------------
# Module families (catalog dispatch)
# ---------------------------------------------------------------------------

# config type -> (init_fn(rng, cfg) -> params, make_forward(cfg) -> fn)
# populated by ray_tpu.rllib.models for non-MLP families
MODULE_FAMILIES: Dict[type, Tuple[Any, Any]] = {}


def register_module_family(config_cls, init_fn, make_forward) -> None:
    """Plug a new module family (CNN, transformer, ...) into the shared
    init/forward dispatch (ray: rllib/models/catalog.py role)."""
    MODULE_FAMILIES[config_cls] = (init_fn, make_forward)


def module_init(rng, config) -> Params:
    """Family-dispatching init (falls back to the builtin MLP)."""
    fam = MODULE_FAMILIES.get(type(config))
    if fam is not None:
        return fam[0](rng, config)
    return init(rng, config)


def get_forward(config):
    """Family-dispatching forward closure; the config rides the closure
    (static under jit), never the params pytree."""
    fam = MODULE_FAMILIES.get(type(config))
    if fam is not None:
        return fam[1](config)
    return forward


def make_sample_fns(config):
    """(sample_actions, sample_actions_epsilon) for any module family —
    what EnvRunners jit instead of the MLP-only globals."""
    return _sample_fns_from_forward(get_forward(config))
