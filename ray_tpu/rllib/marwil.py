"""MARWIL: monotonic advantage re-weighted imitation learning.

Role-equivalent of ray: rllib/algorithms/marwil/ (MARWILConfig, MARWIL,
marwil_learner's loss) on the jax stack: offline episodes, per-step
discounted returns-to-go, advantages A = R - V(s), and a policy loss
that re-weights behavior cloning by exp(beta * A / c) where c^2 tracks
a moving average of E[A^2] (the paper's normalizer).  ``beta = 0``
degenerates to plain BC, exactly like the reference.  The value head
trains on A^2 in the same update.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.rllib import core
from ray_tpu.rllib.algorithm import (
    Algorithm,
    AlgorithmConfig,
    build_module_config,
    probe_env_spaces,
)
from ray_tpu.rllib.env_runner import EnvRunnerGroup
from ray_tpu.rllib.learner_group import Learner
from ray_tpu.rllib.offline import TransitionReader


@dataclasses.dataclass
class MARWILConfig(AlgorithmConfig):
    lr: float = 1e-3
    gamma: float = 0.99
    beta: float = 1.0            # 0 = plain BC
    vf_coeff: float = 1.0
    moving_average_sqd_adv_norm_update_rate: float = 1e-2
    max_advantage_weight: float = 20.0  # exp-weight clip
    train_batch_size: int = 256
    updates_per_iteration: int = 50
    hidden: tuple = (64, 64)
    input_paths: Optional[Sequence[str]] = None
    evaluation_num_steps: int = 200

    def offline_data(self, input_paths) -> "MARWILConfig":
        return dataclasses.replace(self, input_paths=input_paths)


class MARWILLearner(Learner):
    def __init__(self, config: MARWILConfig, module_config):
        import jax
        import optax

        self.config = config
        self.module_config = module_config
        self._fwd = core.get_forward(module_config)
        self.params = core.module_init(
            jax.random.key(config.seed), module_config
        )
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        # c^2: moving average of squared advantages (the normalizer);
        # rides inside the batch so the jitted loss stays pure
        self.adv_sq_ma = 1.0
        self._init_jit()

    def _loss(self, params, batch):
        import jax
        import jax.numpy as jnp

        c = self.config
        logits, value = self._fwd(params, batch["obs"])
        adv = batch["returns"] - value
        # the exp weight sees advantages as DATA (stop_gradient): the
        # policy term must not push V around, the vf term does that
        adv_data = jax.lax.stop_gradient(adv)
        norm = jnp.sqrt(batch["adv_sq_ma"] + 1e-8)
        weight = jnp.minimum(
            jnp.exp(c.beta * adv_data / norm), c.max_advantage_weight
        )
        logp = jax.nn.log_softmax(logits)
        logp_a = jnp.take_along_axis(
            logp, batch["actions"][:, None].astype(jnp.int32), axis=1
        )[:, 0]
        policy_loss = -(weight * logp_a).mean()
        vf_loss = (adv ** 2).mean()
        loss = policy_loss + c.vf_coeff * vf_loss
        return loss, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "total_loss": loss,
            "mean_advantage_sq": (adv_data ** 2).mean(),
            "mean_weight": weight.mean(),
        }

    def update(self, batch) -> Dict[str, float]:
        stats = super().update(
            dict(batch, adv_sq_ma=np.float32(self.adv_sq_ma))
        )
        # paper: c^2 <- c^2 + rate * (E[A^2] - c^2)
        rate = self.config.moving_average_sqd_adv_norm_update_rate
        self.adv_sq_ma += rate * (stats["mean_advantage_sq"] - self.adv_sq_ma)
        return stats


class MARWIL(Algorithm):
    def _setup(self, config: MARWILConfig):
        assert config.input_paths, (
            "MARWILConfig.offline_data(paths) is required"
        )
        spaces = probe_env_spaces(config.env, config.env_to_module)
        self.module_config = build_module_config(config, spaces)
        self.reader = TransitionReader(
            config.input_paths, gamma=config.gamma,
            env_to_module_fn=config.env_to_module,
        )
        self.learner = MARWILLearner(config, self.module_config)
        self.env_runner_group = EnvRunnerGroup(
            config.env,
            self.module_config,
            num_runners=max(1, config.num_env_runners),
            num_envs_per_runner=config.num_envs_per_runner,
            seed=config.seed,
            env_to_module_fn=config.env_to_module,
        )
        self._np_rng = np.random.default_rng(config.seed)

    def training_step(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.monotonic()
        losses: List[float] = []
        for _ in range(c.updates_per_iteration):
            batch = self.reader.sample(c.train_batch_size, self._np_rng)
            stats = self.learner.update(batch)
            losses.append(float(stats["total_loss"]))
        learn_time = time.monotonic() - t0
        # policy rollout via the unified metric helper — episode-bounded
        # eval is Algorithm.evaluate()
        ep_returns = self._rollout_returns(c.evaluation_num_steps)
        return {
            "total_loss": float(np.mean(losses)),
            "adv_sq_moving_avg": self.learner.adv_sq_ma,
            "num_offline_samples": len(self.reader),
            "learn_time_s": learn_time,
            "episodes_this_iter": len(ep_returns),
        }

    def get_state(self) -> Dict[str, Any]:
        return {
            "params": self.learner.params,
            "adv_sq_ma": self.learner.adv_sq_ma,
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.learner.params = state["params"]
        self.learner.adv_sq_ma = state["adv_sq_ma"]
        self.env_runner_group.sync_weights(self.learner.params)

    def stop(self) -> None:
        self.env_runner_group.stop()


MARWILConfig.algo_class = MARWIL
