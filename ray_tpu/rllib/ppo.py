"""PPO: config, jax learner, and the algorithm loop.

Role-equivalent of ray: rllib/algorithms/ppo/ppo.py (PPOConfig:67,
PPO:393, training_step:419) + core/learner/learner.py:104 — TPU-first:
the local learner's update is ONE pjit'd function (GAE-advantaged
clipped surrogate + value + entropy loss, adam, minibatch epochs via lax
loops), so on a mesh the gradient reduction compiles to ICI collectives.
With `config.learners(n)` the update runs on a LearnerGroup instead —
n learner actors doing averaged-gradient data parallelism
(learner_group.py, the reference's learner_group.py:64 analogue).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib import core
from ray_tpu.rllib.algorithm import (
    Algorithm,
    AlgorithmConfig,
    build_module_config,
    probe_env_spaces,
)
from ray_tpu.rllib.env_runner import EnvRunnerGroup
from ray_tpu.rllib.learner_group import Learner, LearnerGroup


@dataclasses.dataclass
class PPOConfig(AlgorithmConfig):
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    clip_param: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_epochs: int = 4
    minibatch_size: int = 128
    grad_clip: float = 0.5
    hidden: tuple = (64, 64)


def ppo_loss(params, batch, config: PPOConfig, forward_fn=None):
    """Clipped-surrogate + value + entropy loss on one minibatch."""
    import jax
    import jax.numpy as jnp

    c = config
    logits, values = (forward_fn or core.forward)(params, batch["obs"])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][:, None], axis=1
    )[:, 0]
    ratio = jnp.exp(logp - batch["logp"])
    adv = batch["advantages"]
    pg = -jnp.minimum(
        ratio * adv,
        jnp.clip(ratio, 1 - c.clip_param, 1 + c.clip_param) * adv,
    ).mean()
    vf = 0.5 * ((values - batch["returns"]) ** 2).mean()
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    total = pg + c.vf_coeff * vf - c.entropy_coeff * entropy
    return total, {
        "policy_loss": pg,
        "vf_loss": vf,
        "entropy": entropy,
    }


class PPOLearner(Learner):
    """Jax learner: the local whole update (epochs × minibatches) is one
    jit; compute_grads/apply_grads serve the LearnerGroup dp path."""

    def __init__(self, config: PPOConfig, module_config):
        import jax
        import optax

        self.config = config
        self.module_config = module_config
        self._fwd = core.get_forward(module_config)
        self.params = core.module_init(jax.random.key(config.seed), module_config)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip),
            optax.adam(config.lr),
        )
        self.opt_state = self.optimizer.init(self.params)
        self._update_fn = jax.jit(self._build_update())
        self._init_jit()

    def _loss(self, params, batch):
        return ppo_loss(params, batch, self.config, forward_fn=self._fwd)

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        c = self.config

        def update(params, opt_state, batch, rng):
            n = batch["obs"].shape[0]
            mb = min(c.minibatch_size, n)
            num_mb = max(1, n // mb)

            def epoch(carry, key):
                params, opt_state = carry
                perm = jax.random.permutation(key, n)

                def minibatch(carry, idx):
                    params, opt_state = carry
                    sel = jax.lax.dynamic_slice_in_dim(perm, idx * mb, mb)
                    mb_batch = {k: v[sel] for k, v in batch.items()}
                    (_, metrics), grads = jax.value_and_grad(
                        self._loss, has_aux=True
                    )(params, mb_batch)
                    updates, opt_state = self.optimizer.update(
                        grads, opt_state, params
                    )
                    params = optax.apply_updates(params, updates)
                    return (params, opt_state), metrics

                (params, opt_state), metrics = jax.lax.scan(
                    minibatch, (params, opt_state), jnp.arange(num_mb)
                )
                return (params, opt_state), metrics

            keys = jax.random.split(rng, c.num_epochs)
            (params, opt_state), metrics = jax.lax.scan(
                epoch, (params, opt_state), keys
            )
            mean_metrics = {k: v.mean() for k, v in metrics.items()}
            return params, opt_state, mean_metrics

        return update

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax

        rng = jax.random.key(int(time.time_ns()) % (1 << 31))
        self.params, self.opt_state, metrics = self._update_fn(
            self.params, self.opt_state, batch, rng
        )
        return {k: float(v) for k, v in metrics.items()}


def compute_gae(
    rewards, values, dones, last_values, gamma: float, lambda_: float
):
    """GAE over a (T, B) fragment with bootstrap values (B,)."""
    T, B = rewards.shape
    adv = np.zeros((T, B), np.float32)
    last_gae = np.zeros(B, np.float32)
    next_value = last_values
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lambda_ * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    returns = adv + values
    return adv, returns


class PPO(Algorithm):
    """(ray: Algorithm.step:818 / PPO.training_step:419 analogue.)"""

    def _setup(self, config: PPOConfig):
        spaces = probe_env_spaces(config.env, config.env_to_module)
        self.module_config = build_module_config(config, spaces)
        cfg, mc = config, self.module_config
        self.learner_group = LearnerGroup(
            lambda: PPOLearner(cfg, mc), num_learners=config.num_learners
        )
        self.env_runner_group = EnvRunnerGroup(
            config.env,
            self.module_config,
            num_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_runner,
            seed=config.seed,
            env_to_module_fn=config.env_to_module,
        )
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        self._np_rng = np.random.default_rng(config.seed)

    def training_step(self) -> Dict[str, Any]:
        """One training iteration: sample → GAE → update → sync."""
        c = self.config
        t0 = time.monotonic()
        fragments = self.env_runner_group.sample(c.rollout_fragment_length)
        sample_time = time.monotonic() - t0

        obs, acts, logps, advs, rets = [], [], [], [], []
        for frag in fragments:
            adv, ret = compute_gae(
                frag["rewards"], frag["values"], frag["dones"],
                frag["last_values"], c.gamma, c.lambda_,
            )
            T, B = frag["actions"].shape
            obs.append(frag["obs"].reshape(T * B, -1))
            acts.append(frag["actions"].reshape(-1))
            logps.append(frag["logp"].reshape(-1))
            advs.append(adv.reshape(-1))
            rets.append(ret.reshape(-1))
            self._record_returns(frag["episode_returns"])

        adv_flat = np.concatenate(advs)
        adv_flat = (adv_flat - adv_flat.mean()) / (adv_flat.std() + 1e-8)
        batch = {
            "obs": np.concatenate(obs).astype(np.float32),
            "actions": np.concatenate(acts),
            "logp": np.concatenate(logps),
            "advantages": adv_flat,
            "returns": np.concatenate(rets),
        }
        self._total_steps += len(batch["actions"])

        t1 = time.monotonic()
        metrics = self._update(batch)
        learn_time = time.monotonic() - t1
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

        return {
            "env_steps_this_iter": len(batch["actions"]),
            "time_sample_s": sample_time,
            "time_learn_s": learn_time,
            **metrics,
        }

    def _update(self, batch) -> Dict[str, float]:
        if self.learner_group.is_local:
            # fast path: the whole update is one jit on the local learner
            return self.learner_group.update(batch)
        # dp path: epochs × shuffled minibatches, each one averaged-grad
        # step across the learner replicas
        c = self.config
        n = len(batch["actions"])
        mb = min(c.minibatch_size, n)
        num_mb = max(1, n // mb)
        metrics: Dict[str, float] = {}
        for _ in range(c.num_epochs):
            perm = self._np_rng.permutation(n)
            for i in range(num_mb):
                sel = perm[i * mb:(i + 1) * mb]
                metrics = self.learner_group.update(
                    {k: v[sel] for k, v in batch.items()}
                )
        return metrics

    def get_state(self) -> Dict[str, Any]:
        state = {"params": self.learner_group.get_weights()}
        if self.learner_group.is_local:
            state["opt_state"] = self.learner_group.local.opt_state
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        self.learner_group.set_weights(state["params"])
        if self.learner_group.is_local and "opt_state" in state:
            self.learner_group.local.opt_state = state["opt_state"]
        self.env_runner_group.sync_weights(self.learner_group.get_weights())


PPOConfig.algo_class = PPO
