"""PPO: config, jax learner, and the algorithm loop.

Role-equivalent of ray: rllib/algorithms/ppo/ppo.py (PPOConfig:67,
PPO:393, training_step:419) + core/learner/learner.py:104 — TPU-first:
the learner's update is ONE pjit'd function (GAE-advantaged clipped
surrogate + value + entropy loss, adam, minibatch epochs via lax loops),
so on a mesh the gradient reduction compiles to ICI collectives instead
of torch-DDP allreduce (learner_group.py:64).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib import core
from ray_tpu.rllib.env_runner import EnvRunnerGroup


@dataclasses.dataclass
class PPOConfig:
    env: Optional[Any] = None  # gym env id or callable returning an env
    # rollouts
    num_env_runners: int = 2
    num_envs_per_runner: int = 4
    rollout_fragment_length: int = 64
    # training
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    clip_param: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_epochs: int = 4
    minibatch_size: int = 128
    grad_clip: float = 0.5
    hidden: tuple = (64, 64)
    seed: int = 0

    def environment(self, env) -> "PPOConfig":
        return dataclasses.replace(self, env=env)

    def env_runners(
        self, num_env_runners=None, num_envs_per_env_runner=None,
        rollout_fragment_length=None,
    ) -> "PPOConfig":
        out = self
        if num_env_runners is not None:
            out = dataclasses.replace(out, num_env_runners=num_env_runners)
        if num_envs_per_env_runner is not None:
            out = dataclasses.replace(
                out, num_envs_per_runner=num_envs_per_env_runner
            )
        if rollout_fragment_length is not None:
            out = dataclasses.replace(
                out, rollout_fragment_length=rollout_fragment_length
            )
        return out

    def training(self, **kw) -> "PPOConfig":
        return dataclasses.replace(self, **kw)

    def build(self) -> "PPO":
        return PPO(self)


# -- learner ---------------------------------------------------------------


class PPOLearner:
    """Jax learner: whole update (epochs × minibatches) is one jit."""

    def __init__(self, config: PPOConfig, module_config):
        import jax
        import optax

        self.config = config
        self.module_config = module_config
        self.params = core.init(jax.random.key(config.seed), module_config)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip),
            optax.adam(config.lr),
        )
        self.opt_state = self.optimizer.init(self.params)
        self._update_fn = jax.jit(self._build_update())

    def _build_update(self):
        import jax
        import jax.numpy as jnp

        c = self.config

        def loss_fn(params, batch):
            logits, values = core.forward(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1
            )[:, 0]
            ratio = jnp.exp(logp - batch["logp"])
            adv = batch["advantages"]
            pg = -jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - c.clip_param, 1 + c.clip_param) * adv,
            ).mean()
            vf = 0.5 * ((values - batch["returns"]) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg + c.vf_coeff * vf - c.entropy_coeff * entropy
            return total, {
                "policy_loss": pg,
                "vf_loss": vf,
                "entropy": entropy,
            }

        def update(params, opt_state, batch, rng):
            n = batch["obs"].shape[0]
            mb = min(c.minibatch_size, n)
            num_mb = max(1, n // mb)

            def epoch(carry, key):
                params, opt_state = carry
                perm = jax.random.permutation(key, n)

                def minibatch(carry, idx):
                    params, opt_state = carry
                    sel = jax.lax.dynamic_slice_in_dim(perm, idx * mb, mb)
                    mb_batch = {k: v[sel] for k, v in batch.items()}
                    (_, metrics), grads = jax.value_and_grad(
                        loss_fn, has_aux=True
                    )(params, mb_batch)
                    updates, opt_state = self.optimizer.update(
                        grads, opt_state, params
                    )
                    import optax

                    params = optax.apply_updates(params, updates)
                    return (params, opt_state), metrics

                (params, opt_state), metrics = jax.lax.scan(
                    minibatch, (params, opt_state), jnp.arange(num_mb)
                )
                return (params, opt_state), metrics

            keys = jax.random.split(rng, c.num_epochs)
            (params, opt_state), metrics = jax.lax.scan(
                epoch, (params, opt_state), keys
            )
            mean_metrics = {k: v.mean() for k, v in metrics.items()}
            return params, opt_state, mean_metrics

        return update

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax

        rng = jax.random.key(int(time.time_ns()) % (1 << 31))
        self.params, self.opt_state, metrics = self._update_fn(
            self.params, self.opt_state, batch, rng
        )
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.params)


def compute_gae(
    rewards, values, dones, last_values, gamma: float, lambda_: float
):
    """GAE over a (T, B) fragment with bootstrap values (B,)."""
    T, B = rewards.shape
    adv = np.zeros((T, B), np.float32)
    last_gae = np.zeros(B, np.float32)
    next_value = last_values
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lambda_ * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    returns = adv + values
    return adv, returns


# -- the algorithm ---------------------------------------------------------


class PPO:
    """(ray: Algorithm.step:818 / PPO.training_step:419 analogue.)"""

    def __init__(self, config: PPOConfig):
        import gymnasium as gym

        self.config = config
        probe = (
            config.env() if callable(config.env) else gym.make(config.env)
        )
        obs_dim = int(np.prod(probe.observation_space.shape))
        num_actions = int(probe.action_space.n)
        probe.close()
        self.module_config = core.MLPModuleConfig(
            obs_dim=obs_dim, num_actions=num_actions, hidden=config.hidden
        )
        self.learner = PPOLearner(config, self.module_config)
        self.env_runner_group = EnvRunnerGroup(
            config.env,
            self.module_config,
            num_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_runner,
            seed=config.seed,
        )
        self.env_runner_group.sync_weights(self.learner.get_weights())
        self.iteration = 0
        self._total_steps = 0
        self._recent_returns: List[float] = []

    def train(self) -> Dict[str, Any]:
        """One training iteration: sample → GAE → update → sync."""
        c = self.config
        t0 = time.monotonic()
        fragments = self.env_runner_group.sample(c.rollout_fragment_length)
        sample_time = time.monotonic() - t0

        obs, acts, logps, advs, rets = [], [], [], [], []
        for frag in fragments:
            adv, ret = compute_gae(
                frag["rewards"], frag["values"], frag["dones"],
                frag["last_values"], c.gamma, c.lambda_,
            )
            T, B = frag["actions"].shape
            obs.append(frag["obs"].reshape(T * B, -1))
            acts.append(frag["actions"].reshape(-1))
            logps.append(frag["logp"].reshape(-1))
            advs.append(adv.reshape(-1))
            rets.append(ret.reshape(-1))
            self._recent_returns.extend(frag["episode_returns"].tolist())
        self._recent_returns = self._recent_returns[-100:]

        adv_flat = np.concatenate(advs)
        adv_flat = (adv_flat - adv_flat.mean()) / (adv_flat.std() + 1e-8)
        batch = {
            "obs": np.concatenate(obs).astype(np.float32),
            "actions": np.concatenate(acts),
            "logp": np.concatenate(logps),
            "advantages": adv_flat,
            "returns": np.concatenate(rets),
        }
        self._total_steps += len(batch["actions"])

        t1 = time.monotonic()
        metrics = self.learner.update(batch)
        learn_time = time.monotonic() - t1
        self.env_runner_group.sync_weights(self.learner.get_weights())

        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (
                float(np.mean(self._recent_returns))
                if self._recent_returns
                else float("nan")
            ),
            "num_env_steps_sampled_lifetime": self._total_steps,
            "env_steps_this_iter": len(batch["actions"]),
            "time_sample_s": sample_time,
            "time_learn_s": learn_time,
            **metrics,
        }

    # -- checkpointing (ray: Algorithm.save/restore) ---------------------
    def save(self, path: str) -> str:
        import os
        import pickle

        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            pickle.dump(
                {
                    "params": self.learner.get_weights(),
                    "opt_state": self.learner.opt_state,
                    "iteration": self.iteration,
                    "total_steps": self._total_steps,
                },
                f,
            )
        return path

    def restore(self, path: str) -> None:
        import os
        import pickle

        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.learner.params = state["params"]
        self.learner.opt_state = state["opt_state"]
        self.iteration = state["iteration"]
        self._total_steps = state["total_steps"]
        self.env_runner_group.sync_weights(self.learner.get_weights())

    def stop(self):
        self.env_runner_group.stop()
