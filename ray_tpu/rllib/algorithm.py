"""Algorithm base: the shared train-loop skeleton every algo plugs into.

Role-equivalent of ray: rllib/algorithms/algorithm.py:200 (Algorithm,
train:818) + algorithm_config.py (AlgorithmConfig builder chain) — cut to
the functional-jax shape: a subclass provides `default_module_config`
(network spec from env spaces), `_setup` (learners + runners), and
`training_step` (one iteration); the base owns iteration bookkeeping,
metric aggregation, and checkpointing.
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import time
from typing import Any, Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class AlgorithmConfig:
    """Builder-style config (subclasses add their hyperparameters)."""

    env: Optional[Any] = None  # gym env id or callable returning an env
    num_env_runners: int = 2
    num_envs_per_runner: int = 4
    rollout_fragment_length: int = 64
    num_learners: int = 0  # 0 = in-process learner; >=2 = LearnerGroup dp
    seed: int = 0
    # factory returning a connectors.Pipeline — one fresh (stateful)
    # instance per EnvRunner (ray: config.env_runners(
    # env_to_module_connector=...))
    env_to_module: Optional[Any] = None
    # extra model-catalog options (conv_filters, hidden, ...); the
    # catalog picks CNN vs MLP from the (post-connector) obs shape
    model_config: Optional[dict] = None
    # -- evaluation (ray: rllib/algorithms/algorithm.py:954 evaluate() +
    # evaluation_interval / evaluation_duration on AlgorithmConfig) --
    # every N train() iterations a SEPARATE EnvRunnerGroup rolls the
    # current weights greedily; None/0 disables periodic evaluation
    # (evaluate() can still be called directly)
    evaluation_interval: Optional[int] = None
    evaluation_duration: int = 10  # episodes per evaluation
    evaluation_num_env_runners: int = 1
    evaluation_greedy: bool = True  # argmax actions (else sample policy)

    algo_class = None  # set by subclasses

    def environment(self, env):
        return dataclasses.replace(self, env=env)

    def connectors(self, env_to_module=None):
        return dataclasses.replace(self, env_to_module=env_to_module)

    def env_runners(
        self, num_env_runners=None, num_envs_per_env_runner=None,
        rollout_fragment_length=None,
    ):
        out = self
        if num_env_runners is not None:
            out = dataclasses.replace(out, num_env_runners=num_env_runners)
        if num_envs_per_env_runner is not None:
            out = dataclasses.replace(
                out, num_envs_per_runner=num_envs_per_env_runner
            )
        if rollout_fragment_length is not None:
            out = dataclasses.replace(
                out, rollout_fragment_length=rollout_fragment_length
            )
        return out

    def training(self, **kw):
        return dataclasses.replace(self, **kw)

    def learners(self, num_learners: int):
        return dataclasses.replace(self, num_learners=num_learners)

    def evaluation(
        self,
        evaluation_interval=None,
        evaluation_duration=None,
        evaluation_num_env_runners=None,
        evaluation_greedy=None,
    ):
        out = self
        if evaluation_interval is not None:
            out = dataclasses.replace(
                out, evaluation_interval=evaluation_interval
            )
        if evaluation_duration is not None:
            out = dataclasses.replace(
                out, evaluation_duration=evaluation_duration
            )
        if evaluation_num_env_runners is not None:
            out = dataclasses.replace(
                out, evaluation_num_env_runners=evaluation_num_env_runners
            )
        if evaluation_greedy is not None:
            out = dataclasses.replace(out, evaluation_greedy=evaluation_greedy)
        return out

    def build(self) -> "Algorithm":
        assert self.algo_class is not None, "config has no algo_class"
        return self.algo_class(self)


def probe_env_spaces(env, env_to_module_fn=None) -> Dict[str, int]:
    """Spin the env up once to read its spaces (ray: Algorithm._get_env_id
    + spaces inference in env_runner setup).  With an env→module
    connector pipeline, obs_dim is the module-side dim AFTER transforms
    (frame stacking widens it, flattening collapses it)."""
    import gymnasium as gym

    probe = env() if callable(env) else gym.make(env)
    obs_shape = probe.observation_space.shape
    if env_to_module_fn is not None:
        from ray_tpu.rllib.connectors import obs_shape_after

        # the pipeline's OUTPUT shape drives catalog dispatch: a
        # normalize-only pipeline keeps image rank (CNN), FlattenObs
        # collapses it (MLP)
        obs_shape = obs_shape_after(env_to_module_fn(), obs_shape)
    obs_dim = int(np.prod(obs_shape))
    spaces = {
        "obs_dim": obs_dim,
        "obs_shape": tuple(obs_shape),
        "num_actions": int(probe.action_space.n),
    }
    probe.close()
    return spaces


def build_module_config(config, spaces: Dict[str, Any]):
    """Catalog dispatch shared by every algorithm's _setup: rank-3 obs
    (no flattening connector) → CNN family, else MLP
    (ray: rllib/models/catalog.py get_model_v2 role)."""
    from ray_tpu.models.catalog import get_module_config

    model_config = dict(getattr(config, "model_config", None) or {})
    model_config.setdefault("hidden", config.hidden)
    shape = spaces["obs_shape"]
    if len(shape) not in (1, 3):
        raise ValueError(
            f"module catalog supports rank-1 (MLP) or rank-3 HWC (CNN) "
            f"observations, got shape {shape}; add a FlattenObs "
            "connector (config.connectors) for other ranks"
        )
    return get_module_config(shape, spaces["num_actions"], model_config)


class Algorithm:
    """Iteration loop + checkpoint plumbing shared by every algorithm."""

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._total_steps = 0
        self._recent_returns: List[float] = []
        self._setup(config)

    # -- subclass hooks --------------------------------------------------
    def _setup(self, config) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def get_state(self) -> Dict[str, Any]:
        raise NotImplementedError

    def set_state(self, state: Dict[str, Any]) -> None:
        raise NotImplementedError

    # -- the loop --------------------------------------------------------
    def train(self) -> Dict[str, Any]:
        t0 = time.monotonic()
        metrics = self.training_step()
        self.iteration += 1
        out = {
            "training_iteration": self.iteration,
            "episode_return_mean": (
                float(np.mean(self._recent_returns))
                if self._recent_returns
                else float("nan")
            ),
            "num_env_steps_sampled_lifetime": self._total_steps,
            "time_total_s": time.monotonic() - t0,
        }
        out.update(metrics)
        interval = getattr(self.config, "evaluation_interval", None)
        if interval and self.iteration % interval == 0:
            out["evaluation"] = self.evaluate()
        return out

    # -- evaluation (ray: Algorithm.evaluate, algorithm.py:954) ----------
    def evaluate(self) -> Dict[str, Any]:
        """Roll the CURRENT weights on a dedicated eval EnvRunnerGroup
        (greedy by default) and report unbiased episode metrics —
        training returns come from an exploring, mid-update policy and
        overstate nothing so much as they understate convergence."""
        c = self.config
        group = self._ensure_eval_group()
        group.sync_weights(self._eval_weights())
        t0 = time.monotonic()
        results = group.evaluate(
            num_episodes=c.evaluation_duration,
            greedy=getattr(c, "evaluation_greedy", True),
        )
        returns = np.concatenate([r["episode_returns"] for r in results])
        lengths = np.concatenate([r["episode_lengths"] for r in results])
        return {
            "episode_return_mean": (
                float(returns.mean()) if len(returns) else float("nan")
            ),
            "episode_return_min": (
                float(returns.min()) if len(returns) else float("nan")
            ),
            "episode_return_max": (
                float(returns.max()) if len(returns) else float("nan")
            ),
            "episode_len_mean": (
                float(lengths.mean()) if len(lengths) else float("nan")
            ),
            "num_episodes": int(len(returns)),
            "time_evaluation_s": time.monotonic() - t0,
        }

    def _ensure_eval_group(self):
        group = getattr(self, "_eval_group", None)
        if group is None:
            from ray_tpu.rllib.env_runner import EnvRunnerGroup

            c = self.config
            if getattr(self, "module_config", None) is None:
                raise RuntimeError(
                    "evaluate() needs self.module_config and config.env "
                    "(set up by single-agent algorithms)"
                )
            group = self._eval_group = EnvRunnerGroup(
                c.env,
                self.module_config,
                num_runners=max(
                    1, getattr(c, "evaluation_num_env_runners", 1)
                ),
                num_envs_per_runner=c.num_envs_per_runner,
                seed=c.seed + 777_000,  # decorrelated from training envs
                env_to_module_fn=c.env_to_module,
            )
        return group

    def _eval_weights(self):
        lg = getattr(self, "learner_group", None)
        if lg is not None:
            return lg.get_weights()
        lr = getattr(self, "learner", None)
        if lr is not None:
            return lr.params
        raise RuntimeError("no learner_group/learner to take weights from")

    def _rollout_returns(self, num_steps: int, epsilon=None) -> np.ndarray:
        """Shared step-bounded policy rollout on the TRAINING runner
        group, feeding the episode_return_mean metric — the offline
        algos' (CQL/MARWIL) only env contact during training.  Episode-
        bounded, unbiased evaluation is evaluate() on the eval group."""
        self.env_runner_group.sync_weights(self._eval_weights())
        frags = self.env_runner_group.sample(num_steps, epsilon=epsilon)
        ep_returns = (
            np.concatenate([f["episode_returns"] for f in frags])
            if frags
            else np.zeros(0)
        )
        self._record_returns(ep_returns)
        return ep_returns

    def _record_returns(self, episode_returns) -> None:
        self._recent_returns.extend(np.asarray(episode_returns).tolist())
        self._recent_returns = self._recent_returns[-100:]

    # -- checkpointing (ray: Algorithm.save/restore) ---------------------
    def save(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        state = dict(
            self.get_state(),
            iteration=self.iteration,
            total_steps=self._total_steps,
        )
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            pickle.dump(state, f)
        return path

    def restore(self, path: str) -> None:
        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.iteration = state.pop("iteration")
        self._total_steps = state.pop("total_steps")
        self.set_state(state)

    def stop(self) -> None:
        group = getattr(self, "env_runner_group", None)
        if group is not None:
            group.stop()
        eval_group = getattr(self, "_eval_group", None)
        if eval_group is not None:
            eval_group.stop()
        lg = getattr(self, "learner_group", None)
        if lg is not None:
            lg.stop()
