"""EnvRunner: vectorized gym envs stepping in an actor.

Role-equivalent of ray: rllib/env/single_agent_env_runner.py:40
(SingleAgentEnvRunner) + env_runner_group.py:66 (EnvRunnerGroup) +
rollout_ops.py:20 (synchronous_parallel_sample).  CPU actors produce
fixed-length rollout fragments; policy inference runs jax-on-CPU inside
the runner (weights synced from the learner each iteration).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu


@ray_tpu.remote
class EnvRunnerActor:
    def __init__(self, env_fn, module_config, num_envs: int, seed: int,
                 env_to_module_fn=None):
        import gymnasium as gym
        import jax

        from ray_tpu.rllib import core

        self._envs = gym.vector.SyncVectorEnv(
            [self._make_env_fn(env_fn, seed + i) for i in range(num_envs)]
        )
        self._num_envs = num_envs
        self._config = module_config
        self._params = core.module_init(jax.random.key(seed), module_config)
        self._rng = jax.random.key(seed + 10_000)
        # family-dispatching sample fns (MLP or catalog CNN)
        _sample, _sample_eps = core.make_sample_fns(module_config)
        self._forward = core.get_forward(module_config)
        self._sample_fn = jax.jit(_sample)
        # each runner owns its connector pipeline instance so stateful
        # transforms (frame stacks, running normalizers) stay runner-local
        # (ray: per-EnvRunner ConnectorV2 instances)
        self._env_to_module = (
            env_to_module_fn() if env_to_module_fn is not None else None
        )
        self._obs, _ = self._envs.reset(seed=seed)
        self._prev_done = np.zeros(num_envs, bool)
        self._proc = self._process(self._obs)
        self._sample_eps_fn = jax.jit(_sample_eps)
        # per-env running episode returns for metrics
        self._ep_return = np.zeros(num_envs, np.float64)
        self._completed: List[float] = []

    def _process(self, obs) -> np.ndarray:
        """Connector-transform a raw obs batch.

        Per-env connector state resets ONE STEP AFTER done: gymnasium
        >= 1.0 vector envs autoreset in NEXT_STEP mode, so the obs
        returned on the done step is still the OLD episode's terminal
        observation — the new episode's first obs arrives on the
        following step, and that is the one that must re-seed stacks."""
        if self._env_to_module is None:
            return obs.astype(np.float32)
        for i in np.nonzero(self._prev_done)[0]:
            self._env_to_module.reset(int(i))
        self._prev_done[:] = False
        return self._env_to_module(obs)

    @staticmethod
    def _make_env_fn(env_fn, seed):
        def make():
            env = env_fn() if callable(env_fn) else None
            if env is None:
                import gymnasium as gym

                env = gym.make(env_fn)
            return env

        return make

    def set_weights(self, params) -> bool:
        self._params = params
        return True

    def evaluate(
        self,
        num_episodes: int,
        greedy: bool = True,
        max_env_steps: int = 200_000,
    ) -> Dict[str, np.ndarray]:
        """Run until ``num_episodes`` episodes complete; greedy takes
        argmax over the module's first head (policy logits or Q-values —
        both maximize correctly), else samples the policy.  Meant for
        DEDICATED eval runners (ray: evaluation EnvRunnerGroup,
        algorithm.py:954): it advances this runner's env/connector state.
        """
        import jax

        returns: List[float] = []
        lengths: List[int] = []
        ep_len = np.zeros(self._num_envs, np.int64)
        steps = 0
        while len(returns) < num_episodes and steps < max_env_steps:
            if greedy:
                head, _ = self._forward(self._params, self._proc)
                action = np.argmax(np.asarray(head), axis=-1).astype(np.int32)
            else:
                self._rng, key = jax.random.split(self._rng)
                a, _, _ = self._sample_fn(self._params, self._proc, key)
                action = np.asarray(a)
            self._obs, reward, term, trunc, _ = self._envs.step(action)
            done = np.logical_or(term, trunc)
            self._proc = self._process(self._obs)
            self._prev_done |= done
            self._ep_return += reward
            ep_len += 1
            steps += self._num_envs
            for i in np.nonzero(done)[0]:
                returns.append(float(self._ep_return[i]))
                lengths.append(int(ep_len[i]))
                self._ep_return[i] = 0.0
                ep_len[i] = 0
        return {
            "episode_returns": np.asarray(returns, np.float64),
            "episode_lengths": np.asarray(lengths, np.int64),
        }

    def sample(
        self, num_steps: int, epsilon: Optional[float] = None
    ) -> Dict[str, np.ndarray]:
        """Collect a fragment of num_steps per env; returns flat arrays
        plus bootstrap values for GAE at the fragment boundary.

        epsilon=None samples the categorical policy (on-policy algos);
        a float switches to ε-greedy over Q-values (DQN-family)."""
        import jax

        B, T = self._num_envs, num_steps
        obs_buf = np.zeros((T, B) + self._proc.shape[1:], np.float32)
        act_buf = np.zeros((T, B), np.int32)
        rew_buf = np.zeros((T, B), np.float32)
        done_buf = np.zeros((T, B), np.float32)
        logp_buf = np.zeros((T, B), np.float32)
        val_buf = np.zeros((T, B), np.float32)

        for t in range(T):
            self._rng, key = jax.random.split(self._rng)
            if epsilon is None:
                action, logp, value = self._sample_fn(
                    self._params, self._proc, key
                )
            else:
                action, logp, value = self._sample_eps_fn(
                    self._params, self._proc, key, float(epsilon),
                )
            action = np.asarray(action)
            obs_buf[t] = self._proc
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            self._obs, reward, term, trunc, _ = self._envs.step(action)
            done = np.logical_or(term, trunc)
            self._proc = self._process(self._obs)
            # flag AFTER processing: under NEXT_STEP autoreset this obs is
            # the old episode's terminal one; the reset obs arrives next
            # step and _process will re-seed connector state then
            self._prev_done |= done
            rew_buf[t] = reward
            done_buf[t] = done
            self._ep_return += reward
            for i in np.nonzero(done)[0]:
                self._completed.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0

        # bootstrap value of the next obs (for the unfinished fragment tail)
        _, last_val = self._forward(self._params, self._proc)
        episode_returns = self._completed
        self._completed = []
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "logp": logp_buf,
            "values": val_buf,
            "last_values": np.asarray(last_val, np.float32),
            # the observation AFTER the final step: replay-buffer algos
            # need next_obs for the fragment tail (module view, i.e.
            # post-connector)
            "final_obs": np.asarray(self._proc, np.float32),
            "episode_returns": np.asarray(episode_returns, np.float64),
        }


class EnvRunnerGroup:
    """N rollout actors + synchronous parallel sampling."""

    def __init__(
        self,
        env_fn,
        module_config,
        num_runners: int = 2,
        num_envs_per_runner: int = 4,
        seed: int = 0,
        env_to_module_fn=None,
    ):
        self.runners = [
            EnvRunnerActor.options(num_cpus=1).remote(
                env_fn, module_config, num_envs_per_runner, seed + 1000 * i,
                env_to_module_fn,
            )
            for i in range(num_runners)
        ]

    def sample(
        self, num_steps: int, epsilon: Optional[float] = None
    ) -> List[Dict[str, np.ndarray]]:
        # No fixed deadline: the first sample sits behind jax init + compile
        # in the runner; a dead runner fails the get with ActorDiedError.
        return ray_tpu.get(
            [r.sample.remote(num_steps, epsilon) for r in self.runners]
        )

    def evaluate(
        self, num_episodes: int, greedy: bool = True
    ) -> List[Dict[str, np.ndarray]]:
        """Split the episode budget across runners (ceil per runner so
        the total is >= num_episodes, like evaluation_duration)."""
        n = len(self.runners)
        per = max(1, -(-num_episodes // n))
        return ray_tpu.get(
            [r.evaluate.remote(per, greedy) for r in self.runners]
        )

    def sync_weights(self, params) -> None:
        ref = ray_tpu.put(params)  # one copy in the store, N borrowers
        ray_tpu.get([r.set_weights.remote(ref) for r in self.runners])

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.runners = []
