"""EnvRunner: vectorized gym envs stepping in an actor.

Role-equivalent of ray: rllib/env/single_agent_env_runner.py:40
(SingleAgentEnvRunner) + env_runner_group.py:66 (EnvRunnerGroup) +
rollout_ops.py:20 (synchronous_parallel_sample).  CPU actors produce
fixed-length rollout fragments; policy inference runs jax-on-CPU inside
the runner (weights synced from the learner each iteration).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu


@ray_tpu.remote
class EnvRunnerActor:
    def __init__(self, env_fn, module_config, num_envs: int, seed: int,
                 env_to_module_fn=None):
        import gymnasium as gym
        import jax

        from ray_tpu.rllib import core

        self._envs = gym.vector.SyncVectorEnv(
            [self._make_env_fn(env_fn, seed + i) for i in range(num_envs)]
        )
        self._num_envs = num_envs
        self._config = module_config
        self._params = core.module_init(jax.random.key(seed), module_config)
        self._rng = jax.random.key(seed + 10_000)
        # family-dispatching sample fns (MLP or catalog CNN)
        _sample, _sample_eps = core.make_sample_fns(module_config)
        self._forward = core.get_forward(module_config)
        self._sample_fn = jax.jit(_sample)
        # each runner owns its connector pipeline instance so stateful
        # transforms (frame stacks, running normalizers) stay runner-local
        # (ray: per-EnvRunner ConnectorV2 instances)
        self._env_to_module = (
            env_to_module_fn() if env_to_module_fn is not None else None
        )
        self._obs, _ = self._envs.reset(seed=seed)
        self._prev_done = np.zeros(num_envs, bool)
        self._proc = self._process(self._obs)
        self._sample_eps_fn = jax.jit(_sample_eps)
        # per-env running episode returns for metrics
        self._ep_return = np.zeros(num_envs, np.float64)
        self._completed: List[float] = []
        # podracer-plane bookkeeping: which learner version these params
        # are, and a per-runner fragment counter (the bit-repro key)
        self._policy_version = 0
        self._frag_seq = 0

    def _process(self, obs) -> np.ndarray:
        """Connector-transform a raw obs batch.

        Per-env connector state resets ONE STEP AFTER done: gymnasium
        >= 1.0 vector envs autoreset in NEXT_STEP mode, so the obs
        returned on the done step is still the OLD episode's terminal
        observation — the new episode's first obs arrives on the
        following step, and that is the one that must re-seed stacks."""
        if self._env_to_module is None:
            return obs.astype(np.float32)
        for i in np.nonzero(self._prev_done)[0]:
            self._env_to_module.reset(int(i))
        self._prev_done[:] = False
        return self._env_to_module(obs)

    @staticmethod
    def _make_env_fn(env_fn, seed):
        def make():
            env = env_fn() if callable(env_fn) else None
            if env is None:
                import gymnasium as gym

                env = gym.make(env_fn)
            return env

        return make

    def set_weights(self, params) -> bool:
        self._params = params
        return True

    def set_weights_versioned(self, params, policy_version: int) -> int:
        """Put-path weight sync that also stamps the learner version the
        podracer plane tags fragments with."""
        self._params = params
        self._policy_version = int(policy_version)
        return self._policy_version

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self._params)

    def ping(self) -> bool:
        return True

    def evaluate(
        self,
        num_episodes: int,
        greedy: bool = True,
        max_env_steps: int = 200_000,
    ) -> Dict[str, np.ndarray]:
        """Run until ``num_episodes`` episodes complete; greedy takes
        argmax over the module's first head (policy logits or Q-values —
        both maximize correctly), else samples the policy.  Meant for
        DEDICATED eval runners (ray: evaluation EnvRunnerGroup,
        algorithm.py:954): it advances this runner's env/connector state.
        """
        import jax

        returns: List[float] = []
        lengths: List[int] = []
        ep_len = np.zeros(self._num_envs, np.int64)
        steps = 0
        while len(returns) < num_episodes and steps < max_env_steps:
            if greedy:
                head, _ = self._forward(self._params, self._proc)
                action = np.argmax(np.asarray(head), axis=-1).astype(np.int32)
            else:
                self._rng, key = jax.random.split(self._rng)
                a, _, _ = self._sample_fn(self._params, self._proc, key)
                action = np.asarray(a)
            self._obs, reward, term, trunc, _ = self._envs.step(action)
            done = np.logical_or(term, trunc)
            self._proc = self._process(self._obs)
            self._prev_done |= done
            self._ep_return += reward
            ep_len += 1
            steps += self._num_envs
            for i in np.nonzero(done)[0]:
                returns.append(float(self._ep_return[i]))
                lengths.append(int(ep_len[i]))
                self._ep_return[i] = 0.0
                ep_len[i] = 0
        return {
            "episode_returns": np.asarray(returns, np.float64),
            "episode_lengths": np.asarray(lengths, np.int64),
        }

    def sample(
        self, num_steps: int, epsilon: Optional[float] = None
    ) -> Dict[str, np.ndarray]:
        """Collect a fragment of num_steps per env; returns flat arrays
        plus bootstrap values for GAE at the fragment boundary.

        epsilon=None samples the categorical policy (on-policy algos);
        a float switches to ε-greedy over Q-values (DQN-family)."""
        import jax

        B, T = self._num_envs, num_steps
        obs_buf = np.zeros((T, B) + self._proc.shape[1:], np.float32)
        act_buf = np.zeros((T, B), np.int32)
        rew_buf = np.zeros((T, B), np.float32)
        done_buf = np.zeros((T, B), np.float32)
        logp_buf = np.zeros((T, B), np.float32)
        val_buf = np.zeros((T, B), np.float32)

        for t in range(T):
            self._rng, key = jax.random.split(self._rng)
            if epsilon is None:
                action, logp, value = self._sample_fn(
                    self._params, self._proc, key
                )
            else:
                action, logp, value = self._sample_eps_fn(
                    self._params, self._proc, key, float(epsilon),
                )
            action = np.asarray(action)
            obs_buf[t] = self._proc
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            self._obs, reward, term, trunc, _ = self._envs.step(action)
            done = np.logical_or(term, trunc)
            self._proc = self._process(self._obs)
            # flag AFTER processing: under NEXT_STEP autoreset this obs is
            # the old episode's terminal one; the reset obs arrives next
            # step and _process will re-seed connector state then
            self._prev_done |= done
            rew_buf[t] = reward
            done_buf[t] = done
            self._ep_return += reward
            for i in np.nonzero(done)[0]:
                self._completed.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0

        # bootstrap value of the next obs (for the unfinished fragment tail)
        _, last_val = self._forward(self._params, self._proc)
        episode_returns = self._completed
        self._completed = []
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "logp": logp_buf,
            "values": val_buf,
            "last_values": np.asarray(last_val, np.float32),
            # the observation AFTER the final step: replay-buffer algos
            # need next_obs for the fragment tail (module view, i.e.
            # post-connector)
            "final_obs": np.asarray(self._proc, np.float32),
            "episode_returns": np.asarray(episode_returns, np.float64),
        }

    # -- podracer plane --------------------------------------------------
    def sample_podracer(self, num_steps: int, epsilon: Optional[float] = None):
        """Free-running fragment production: sample, put the payload into
        the shm arena HERE (vectored write; inline slab when tiny), and
        return only ``(meta, ref)`` — the driver routes the few-dozen-byte
        meta and forwards the ref to the learner, whose arg-unpack
        resolves it over the direct-shm get path.  Payload bytes never
        transit the driver at any fragment size."""
        frag = self.sample(num_steps, epsilon)
        meta = {
            "runner_index": -1,  # stamped by the driver (stable across
            "seq": self._frag_seq,  # replaces; the actor can't know it)
            "policy_version": self._policy_version,
            "env_steps": int(num_steps * self._num_envs),
            "suspect": False,
            "incarnation": 0,
        }
        self._frag_seq += 1
        return meta, ray_tpu.put(frag)

    def join_weight_broadcast(
        self, group_name: str, root_rank: int = 0,
        wire_dtype: Optional[str] = None,
    ) -> int:
        """Member side of the podracer weight fan-out: one collective
        receive replaces a per-runner put.  The skeleton carries the
        policy version exactly; with a quantized ``wire_dtype`` every
        rank (root included) adopts the same decode, so the fleet ends
        bit-identical."""
        from ray_tpu.util import collective as col

        out = col.broadcast_tree(
            None, src_rank=root_rank, group_name=group_name,
            wire_dtype=wire_dtype,
        )
        self._params = out["w"]
        self._policy_version = int(out["v"])
        return self._policy_version

    def sync_weights_bcast(
        self, params, group_name: str, root_rank: int = 0,
        wire_dtype: Optional[str] = None,
    ) -> bool:
        """Collective-routed ``EnvRunnerGroup.sync_weights`` leg.  The
        root receives the params with the call (arg-unpack from one shm
        ref) and broadcasts; members pass ``params=None`` and receive.
        Every rank adopts the broadcast result, so a quantized wire still
        leaves all replicas bit-identical."""
        from ray_tpu.util import collective as col

        out = col.broadcast_tree(
            params, src_rank=root_rank, group_name=group_name,
            wire_dtype=wire_dtype,
        )
        self._params = out
        return True


class EnvRunnerGroup:
    """N rollout actors + synchronous parallel sampling.

    ``sync_weights`` routes through ``col.broadcast_tree`` over a lazily
    created persistent group (runner 0 = root) — one shm put to the root
    plus one collective instead of N per-actor puts, mirroring the
    LearnerGroup fan-out path.  ``weight_wire_dtype`` opts into the
    block-quantized wire (replicas still bit-identical: every rank,
    root included, adopts the decode).  Any collective failure trips a
    permanent fallback to the legacy put path, so weight sync never
    gets less reliable than it was.
    """

    def __init__(
        self,
        env_fn,
        module_config,
        num_runners: int = 2,
        num_envs_per_runner: int = 4,
        seed: int = 0,
        env_to_module_fn=None,
        weight_wire_dtype: Optional[str] = None,
    ):
        # spawn args kept so a dead runner can be stateless-restarted
        # (podracer replace_runner) with a decorrelated seed
        self._spawn = dict(
            env_fn=env_fn, module_config=module_config,
            num_envs_per_runner=num_envs_per_runner, seed=seed,
            env_to_module_fn=env_to_module_fn,
        )
        self.weight_wire_dtype = weight_wire_dtype
        self._sync_group: Optional[str] = None
        self._col_broken = False
        self.runners = [
            self._spawn_runner(i) for i in range(num_runners)
        ]

    def _spawn_runner(self, index: int, incarnation: int = 0):
        s = self._spawn
        # decorrelate replacement streams from every prior incarnation
        seed = s["seed"] + 1000 * index + 101 * incarnation
        return EnvRunnerActor.options(num_cpus=1).remote(
            s["env_fn"], s["module_config"], s["num_envs_per_runner"],
            seed, s["env_to_module_fn"],
        )

    def replace_runner(self, index: int, incarnation: int = 1):
        """Stateless-restart a dead runner in place (env runners carry no
        state worth migrating — the podracer failure contract)."""
        old = self.runners[index]
        try:
            ray_tpu.kill(old)
        except Exception:
            pass
        self.runners[index] = self._spawn_runner(index, incarnation)
        # the old group membership is poisoned; the podracer runner
        # re-forms its own fan-out group, ours is rebuilt on next sync
        self._drop_sync_group()
        return self.runners[index]

    def sample(
        self, num_steps: int, epsilon: Optional[float] = None
    ) -> List[Dict[str, np.ndarray]]:
        # No fixed deadline: the first sample sits behind jax init + compile
        # in the runner; a dead runner fails the get with ActorDiedError.
        return ray_tpu.get(
            [r.sample.remote(num_steps, epsilon) for r in self.runners]
        )

    def evaluate(
        self, num_episodes: int, greedy: bool = True
    ) -> List[Dict[str, np.ndarray]]:
        """Split the episode budget across runners (ceil per runner so
        the total is >= num_episodes, like evaluation_duration)."""
        n = len(self.runners)
        per = max(1, -(-num_episodes // n))
        return ray_tpu.get(
            [r.evaluate.remote(per, greedy) for r in self.runners]
        )

    def sync_weights(self, params) -> None:
        if len(self.runners) >= 2 and not self._col_broken:
            try:
                self._sync_weights_collective(params)
                return
            except Exception:
                # poisoned group / op failure: weight sync must never be
                # less reliable than the legacy path — fall back for good
                self._col_broken = True
                self._drop_sync_group()
        ref = ray_tpu.put(params)  # one copy in the store, N borrowers
        ray_tpu.get([r.set_weights.remote(ref) for r in self.runners])

    def _sync_weights_collective(self, params) -> None:
        """One put (to the root) + one broadcast_tree instead of N puts."""
        import uuid

        from ray_tpu.common.config import cfg
        from ray_tpu.util import collective as col

        if self._sync_group is None:
            name = f"env-runner-sync-{uuid.uuid4().hex[:8]}"
            col.create_collective_group(self.runners, group_name=name)
            self._sync_group = name
        ref = ray_tpu.put(params)
        refs = [
            r.sync_weights_bcast.remote(
                ref if i == 0 else None, self._sync_group, 0,
                self.weight_wire_dtype,
            )
            for i, r in enumerate(self.runners)
        ]
        ray_tpu.get(refs, timeout=cfg.collective_op_timeout_s)

    def _drop_sync_group(self):
        if self._sync_group is None:
            return
        name, self._sync_group = self._sync_group, None
        from ray_tpu.util import collective as col

        try:
            col.destroy_collective_group(name, actors=self.runners)
        except Exception:
            pass  # dead members mustn't block the rebuild

    def stop(self):
        self._drop_sync_group()
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.runners = []
