"""Connector pipelines: observation/action transforms around the module.

Role-equivalent of ray: rllib/connectors/ (ConnectorV2,
env_to_module/*.py, module_to_env/*.py) — reduced to the two pipelines
this stack actually routes through: env→module (batched observation
preprocessing inside the EnvRunner, before jax inference) and
module→env (action post-processing before `env.step`).  Connectors are
stateful objects living inside each runner, so stateful transforms
(running normalization, frame stacking) keep per-runner state exactly
like the reference's per-EnvRunner connector instances.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class Connector:
    """One transform stage.  Called with a batch (B, ...) ndarray."""

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def reset(self, env_index: Optional[int] = None) -> None:
        """Clear per-episode state (frame stacks) for one env or all."""


class Pipeline(Connector):
    """Ordered connector list (ray: ConnectorPipelineV2)."""

    def __init__(self, connectors: Optional[Sequence[Connector]] = None):
        self.connectors: List[Connector] = list(connectors or [])

    def append(self, c: Connector) -> "Pipeline":
        self.connectors.append(c)
        return self

    def prepend(self, c: Connector) -> "Pipeline":
        self.connectors.insert(0, c)
        return self

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        for c in self.connectors:
            batch = c(batch)
        return batch

    def reset(self, env_index: Optional[int] = None) -> None:
        for c in self.connectors:
            c.reset(env_index)


class FlattenObs(Connector):
    """(B, ...) → (B, prod(...)) — images/dict-leaves to MLP input
    (ray: connectors/env_to_module/flatten_observations.py)."""

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        return np.asarray(batch, np.float32).reshape(len(batch), -1)


class NormalizeObs(Connector):
    """Running mean/std normalization (ray: connectors/env_to_module/
    mean_std_filter.py MeanStdFilter; Welford's algorithm)."""

    def __init__(self, clip: float = 10.0, eps: float = 1e-8):
        self.clip = clip
        self.eps = eps
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        batch = np.asarray(batch, np.float32)
        if self._mean is None:
            self._mean = np.zeros(batch.shape[1:], np.float64)
            self._m2 = np.ones(batch.shape[1:], np.float64)
        for row in batch:  # batch sizes here are tiny (num_envs)
            self._count += 1.0
            delta = row - self._mean
            self._mean += delta / self._count
            self._m2 += delta * (row - self._mean)
        std = np.sqrt(self._m2 / max(self._count, 2.0)) + self.eps
        out = (batch - self._mean) / std
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def state(self) -> dict:
        return {"count": self._count, "mean": self._mean, "m2": self._m2}


class FrameStack(Connector):
    """Stack the last k observations per env along the feature axis
    (ray: connectors/env_to_module/frame_stacking.py)."""

    def __init__(self, k: int = 4):
        self.k = k
        self._frames: Optional[np.ndarray] = None  # (B, k, F)
        self._pending_reset: set = set()

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        batch = np.asarray(batch, np.float32).reshape(len(batch), -1)
        if self._frames is None or len(batch) != len(self._frames):
            self._frames = np.repeat(batch[:, None, :], self.k, axis=1)
            self._pending_reset.clear()
        else:
            self._frames = np.concatenate(
                [self._frames[:, 1:], batch[:, None, :]], axis=1
            )
            # envs flagged by reset(): re-seed with the NEW episode's
            # first frame repeated k times, exactly like the very first
            # call — every episode start sees the same input convention
            for i in self._pending_reset:
                self._frames[i] = batch[i]
            self._pending_reset.clear()
        return self._frames.reshape(len(batch), -1)

    def reset(self, env_index: Optional[int] = None) -> None:
        if env_index is None:
            self._frames = None
            self._pending_reset.clear()
        else:
            self._pending_reset.add(int(env_index))


class ClipActions(Connector):
    """Clip continuous actions into bounds (module→env;
    ray: connectors/module_to_env/clip_actions.py)."""

    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        return np.clip(batch, self.low, self.high)


def obs_shape_after(pipeline: Optional[Pipeline], obs_shape: tuple) -> tuple:
    """Probe the per-row obs SHAPE the module will see after env→module
    connectors (so module configs can be built — and the CNN/MLP catalog
    dispatched — before any env steps).  A normalize-only pipeline keeps
    image rank; a FlattenObs collapses it."""
    dummy = np.zeros((1,) + tuple(obs_shape), np.float32)
    if pipeline is not None:
        dummy = pipeline(dummy)
        pipeline.reset()
    return tuple(dummy.shape[1:])


def obs_dim_after(pipeline: Optional[Pipeline], obs_shape: tuple) -> int:
    return int(np.prod(obs_shape_after(pipeline, obs_shape)))
