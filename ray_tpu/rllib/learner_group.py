"""LearnerGroup: data-parallel gradient updates across learner actors.

Role-equivalent of ray: rllib/core/learner/learner_group.py:64 +
learner.py:104.  The reference shards batches to torch learners and
allreduces with DDP; here each learner actor jits grad computation, the
group tree-averages gradients (equal shards ⇒ identical numerics to a
single learner on the full batch, since the loss is a shard mean), and
every learner applies the same averaged update — so all replicas stay
bit-identical without a parameter server.

num_learners == 0 keeps the learner in-process (the common single-host
case, and what the reference calls a "local learner").
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu


class Learner:
    """Minimal learner contract: jit-compiled grads + update.

    Subclasses define `_loss(params, batch) -> (loss, metrics)` and
    construct `self.params`, `self.optimizer`, `self.opt_state`.
    """

    params: Any
    optimizer: Any
    opt_state: Any

    def _init_jit(self):
        import jax
        import optax

        def _grads(params, batch):
            (_, metrics), grads = jax.value_and_grad(
                self._loss, has_aux=True
            )(params, batch)
            return grads, metrics

        def _apply(params, opt_state, grads):
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params
            )
            return optax.apply_updates(params, updates), opt_state

        self._grads_fn = jax.jit(_grads)
        self._apply_fn = jax.jit(_apply)

    def _loss(self, params, batch):
        raise NotImplementedError

    def compute_grads(self, batch):
        grads, metrics = self._grads_fn(self.params, batch)
        return grads, {k: float(v) for k, v in metrics.items()}

    def apply_grads(self, grads):
        self.params, self.opt_state = self._apply_fn(
            self.params, self.opt_state, grads
        )

    def update(self, batch) -> Dict[str, float]:
        grads, metrics = self._grads_fn(self.params, batch)
        self.apply_grads(grads)
        return metrics

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, params):
        self.params = params


@ray_tpu.remote
class LearnerWorker:
    """One learner replica in its own process (TPU host in production)."""

    def __init__(self, factory):
        self.learner = factory()

    def compute_grads(self, batch):
        import jax

        grads, metrics = self.learner.compute_grads(batch)
        return jax.tree.map(np.asarray, grads), metrics

    def apply_grads(self, grads):
        self.learner.apply_grads(grads)
        return True

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, w):
        self.learner.set_weights(w)
        return True

    def invoke(self, method, *args, **kwargs):
        return getattr(self.learner, method)(*args, **kwargs)


def _tree_mean(trees: List[Any]):
    import jax

    return jax.tree.map(lambda *xs: sum(xs) / len(xs), *trees)


def _bcast_weights(inst, group_name: str, root: int, wire_dtype=None):
    """Runs INSIDE each LearnerWorker (via ``_apply``): one collective
    broadcast replaces the driver's N per-actor weight puts — the
    driver ships weights to rank ``root`` once (or not at all, for the
    init sync) and the group fans them out over the RPC+shm plane.

    With ``wire_dtype`` ("bf16"/"int8") the float32 leaves ride the
    block-quantized tensor path (~2x/4x fewer wire bytes); every
    replica INCLUDING the root adopts the decode of the one encoding,
    so replicas stay bit-identical to each other — the invariant the
    fp32 default guarantees exactly."""
    from ray_tpu.util import collective as col

    rank = col.get_rank(group_name)
    w = col.broadcast_tree(
        inst.learner.get_weights() if rank == root else None,
        src_rank=root,
        group_name=group_name,
        wire_dtype=wire_dtype,
    )
    if rank != root or wire_dtype is not None:
        inst.learner.set_weights(w)
    return True


class LearnerGroup:
    """N-way data-parallel sgd steps with averaged gradients.

    ``weight_wire_dtype`` ("bf16"/"int8", default None = exact fp32)
    block-quantizes the weight-sync broadcasts (init sync and
    ``set_weights``) — replicas remain bit-identical to EACH OTHER
    either way; the quantized path trades a bounded per-block error
    vs the source weights for 2x/4x fewer broadcast bytes."""

    def __init__(self, factory: Callable[[], Learner], num_learners: int = 0,
                 weight_wire_dtype: Optional[str] = None):
        self.num_learners = num_learners
        self.weight_wire_dtype = weight_wire_dtype
        if num_learners <= 1:
            self.local: Optional[Learner] = factory()
            self.workers: List[Any] = []
        else:
            from ray_tpu.util import collective as col

            self.local = None
            self.workers = [
                LearnerWorker.options(num_cpus=1).remote(factory)
                for _ in range(num_learners)
            ]
            # weight sync rides a runtime collective group over the
            # learner actors (rpc ring backend: shm handoff co-hosted,
            # oob wire cross-host) instead of per-actor object puts
            self._col_group = f"learner-group-{uuid.uuid4().hex[:8]}"
            col.create_collective_group(
                self.workers, group_name=self._col_group
            )
            # all replicas must start from identical weights: collective
            # broadcast of replica 0's init
            self._broadcast_from_rank0()

    def _broadcast_from_rank0(self):
        ray_tpu.get(
            [
                w._apply(_bcast_weights, self._col_group, 0,
                         self.weight_wire_dtype)
                for w in self.workers
            ],
            timeout=None,
        )

    @property
    def is_local(self) -> bool:
        return self.local is not None

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """One sgd step on `batch` (dp-sharded when distributed)."""
        if self.local is not None:
            m = self.local.update(batch)
            return {k: float(v) for k, v in m.items()}
        n = len(batch[next(iter(batch))])
        k = len(self.workers)
        shard = n // k
        assert shard > 0, f"batch of {n} too small for {k} learners"
        shards = [
            {key: v[i * shard:(i + 1) * shard] for key, v in batch.items()}
            for i in range(k)
        ]
        outs = ray_tpu.get(
            [
                w.compute_grads.remote(s)
                for w, s in zip(self.workers, shards)
            ],
            timeout=None,
        )
        grads = _tree_mean([g for g, _ in outs])
        ray_tpu.get(
            [w.apply_grads.remote(grads) for w in self.workers], timeout=None
        )
        metrics: Dict[str, float] = {}
        for _, m in outs:
            for key, v in m.items():
                metrics[key] = metrics.get(key, 0.0) + float(v) / len(outs)
        return metrics

    def get_weights(self):
        if self.local is not None:
            return self.local.get_weights()
        return ray_tpu.get(self.workers[0].get_weights.remote(), timeout=None)

    def foreach_learner(self, method: str, *args, **kwargs) -> List[Any]:
        """Run a learner method on every replica (e.g. DQN sync_target)."""
        if self.local is not None:
            return [getattr(self.local, method)(*args, **kwargs)]
        return ray_tpu.get(
            [w.invoke.remote(method, *args, **kwargs) for w in self.workers],
            timeout=None,
        )

    def set_weights(self, w):
        if self.local is not None:
            self.local.set_weights(w)
        else:
            # ship once to rank 0, then collective-broadcast to the rest
            ray_tpu.get(
                self.workers[0].set_weights.remote(w), timeout=None
            )
            self._broadcast_from_rank0()

    def stop(self):
        if self.workers and getattr(self, "_col_group", None):
            from ray_tpu.util import collective as col

            try:
                col.destroy_collective_group(
                    self._col_group, actors=self.workers
                )
            except Exception:
                pass  # a dead member mustn't block teardown
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
