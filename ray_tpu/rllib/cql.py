"""CQL: conservative Q-learning from offline transitions.

Role-equivalent of ray: rllib/algorithms/cql/ (CQLConfig, CQL,
cql_learner's conservative loss) in its DISCRETE form on the jax
stack: a double-DQN TD backup over the offline transition dataset plus
the conservative regularizer alpha * E[logsumexp_a Q(s,a) - Q(s,a_data)],
which pushes down out-of-distribution action values so the greedy
policy stays inside the dataset's support.  (The reference builds CQL
on SAC for continuous control; the regularizer — the algorithm's
substance — is identical.)
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.rllib import core
from ray_tpu.rllib.algorithm import (
    Algorithm,
    AlgorithmConfig,
    build_module_config,
    probe_env_spaces,
)
from ray_tpu.rllib.env_runner import EnvRunnerGroup
from ray_tpu.rllib.learner_group import Learner
from ray_tpu.rllib.offline import TransitionReader


@dataclasses.dataclass
class CQLConfig(AlgorithmConfig):
    lr: float = 3e-4
    gamma: float = 0.99
    cql_alpha: float = 1.0       # conservative-penalty weight
    double_q: bool = True
    target_update_freq: int = 100  # gradient steps between target syncs
    train_batch_size: int = 256
    updates_per_iteration: int = 100
    hidden: tuple = (64, 64)
    input_paths: Optional[Sequence[str]] = None
    evaluation_num_steps: int = 200

    def offline_data(self, input_paths) -> "CQLConfig":
        return dataclasses.replace(self, input_paths=input_paths)


class CQLLearner(Learner):
    """TD + conservative penalty; target params ride inside the batch
    (the dqn.py convention, so the jitted loss stays pure)."""

    def __init__(self, config: CQLConfig, module_config):
        import jax
        import optax

        self.config = config
        self.module_config = module_config
        self._fwd = core.get_forward(module_config)
        self.params = core.module_init(
            jax.random.key(config.seed), module_config
        )
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.grad_steps = 0
        self._init_jit()

    def _loss(self, params, batch):
        import jax
        import jax.numpy as jnp

        c = self.config
        q_all, _ = self._fwd(params, batch["obs"])
        a = batch["actions"][:, None].astype(jnp.int32)
        q_data = jnp.take_along_axis(q_all, a, axis=1)[:, 0]

        q_next_t, _ = self._fwd(batch["target_params"], batch["next_obs"])
        if c.double_q:
            q_next_online, _ = self._fwd(params, batch["next_obs"])
            best = jnp.argmax(q_next_online, axis=-1)
        else:
            best = jnp.argmax(q_next_t, axis=-1)
        q_next = jnp.take_along_axis(q_next_t, best[:, None], axis=1)[:, 0]
        target = jax.lax.stop_gradient(
            batch["rewards"] + c.gamma * (1.0 - batch["dones"]) * q_next
        )
        td = q_data - target
        td_loss = jnp.where(
            jnp.abs(td) < 1.0, 0.5 * td ** 2, jnp.abs(td) - 0.5
        ).mean()  # huber

        # the conservative term: soft-max over ALL actions minus the
        # dataset action's value — OOD actions get pushed down
        cql_term = (
            jax.scipy.special.logsumexp(q_all, axis=-1) - q_data
        ).mean()
        loss = td_loss + c.cql_alpha * cql_term
        return loss, {
            "td_loss": td_loss,
            "cql_loss": cql_term,
            "total_loss": loss,
            "q_data_mean": q_data.mean(),
        }

    def update(self, batch) -> Dict[str, float]:
        import jax

        stats = super().update(
            dict(batch, target_params=self.target_params)
        )
        self.grad_steps += 1
        if self.grad_steps % self.config.target_update_freq == 0:
            self.target_params = jax.tree.map(lambda x: x, self.params)
        return stats


class CQL(Algorithm):
    def _setup(self, config: CQLConfig):
        assert config.input_paths, "CQLConfig.offline_data(paths) is required"
        spaces = probe_env_spaces(config.env, config.env_to_module)
        self.module_config = build_module_config(config, spaces)
        self.reader = TransitionReader(
            config.input_paths, gamma=config.gamma,
            env_to_module_fn=config.env_to_module,
        )
        self.learner = CQLLearner(config, self.module_config)
        self.env_runner_group = EnvRunnerGroup(
            config.env,
            self.module_config,
            num_runners=max(1, config.num_env_runners),
            num_envs_per_runner=config.num_envs_per_runner,
            seed=config.seed,
            env_to_module_fn=config.env_to_module,
        )
        self._np_rng = np.random.default_rng(config.seed)

    def training_step(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.monotonic()
        losses: List[float] = []
        for _ in range(c.updates_per_iteration):
            batch = self.reader.sample(c.train_batch_size, self._np_rng)
            stats = self.learner.update(batch)
            losses.append(float(stats["total_loss"]))
        learn_time = time.monotonic() - t0
        # greedy rollout of the learned Q policy (epsilon 0); unified
        # metric helper — episode-bounded eval is Algorithm.evaluate()
        ep_returns = self._rollout_returns(c.evaluation_num_steps, epsilon=0.0)
        return {
            "total_loss": float(np.mean(losses)),
            "num_offline_samples": len(self.reader),
            "learn_time_s": learn_time,
            "episodes_this_iter": len(ep_returns),
        }

    def get_state(self) -> Dict[str, Any]:
        return {
            "params": self.learner.params,
            "target_params": self.learner.target_params,
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.learner.params = state["params"]
        self.learner.target_params = state["target_params"]
        self.env_runner_group.sync_weights(self.learner.params)

    def stop(self) -> None:
        self.env_runner_group.stop()


CQLConfig.algo_class = CQL
