"""DQN: double Q-learning with a replay buffer and target network.

Role-equivalent of ray: rllib/algorithms/dqn/dqn.py (DQNConfig:87,
DQN.training_step — sample → store → replay → TD update → target sync)
on the shared Algorithm / LearnerGroup / EnvRunnerGroup stack.  The
module is the same MLP as PPO with the logits head read as Q-values
(core.sample_actions_epsilon), so the two algorithms exercise one
RLModule path the way the reference's RLModule API intends.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib import core
from ray_tpu.rllib.algorithm import (
    Algorithm,
    AlgorithmConfig,
    build_module_config,
    probe_env_spaces,
)
from ray_tpu.rllib.env_runner import EnvRunnerGroup
from ray_tpu.rllib.learner_group import Learner, LearnerGroup


@dataclasses.dataclass
class DQNConfig(AlgorithmConfig):
    # training
    lr: float = 1e-3
    gamma: float = 0.99
    buffer_size: int = 50_000
    learning_starts: int = 500
    train_batch_size: int = 64
    target_update_freq: int = 200  # gradient steps between target syncs
    updates_per_env_step: float = 1.0
    double_q: bool = True
    grad_clip: float = 10.0
    hidden: tuple = (64, 64)
    # exploration: linear ε decay over decay_steps env steps
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_decay_steps: int = 5_000
    # replay algos use short fragments by default (field override, so an
    # explicit user value survives the builder chain's dataclasses.replace)
    rollout_fragment_length: int = 16


class ReplayBuffer:
    """Uniform ring buffer of transitions (numpy, host memory).

    ray: rllib/utils/replay_buffers/replay_buffer.py role; sampling is
    the learner-facing API.
    """

    def __init__(self, capacity: int, obs_dim: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros((capacity,), np.int32)
        self.rewards = np.zeros((capacity,), np.float32)
        self.dones = np.zeros((capacity,), np.float32)
        self._next = 0
        self.size = 0

    def add_batch(self, obs, actions, rewards, next_obs, dones):
        n = len(actions)
        idx = (self._next + np.arange(n)) % self.capacity
        self.obs[idx] = obs
        self.next_obs[idx] = next_obs
        self.actions[idx] = actions
        self.rewards[idx] = rewards
        self.dones[idx] = dones
        self._next = int((self._next + n) % self.capacity)
        self.size = min(self.size + n, self.capacity)

    def sample(self, rng: np.random.Generator, n: int) -> Dict[str, np.ndarray]:
        idx = rng.integers(0, self.size, size=n)
        return {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "next_obs": self.next_obs[idx],
            "dones": self.dones[idx],
        }


class DQNLearner(Learner):
    """TD(0) double-DQN update; target params ride inside the batch-free
    learner state and sync by copy every target_update_freq steps."""

    def __init__(self, config: DQNConfig, module_config):
        import jax
        import optax

        self.config = config
        self.module_config = module_config
        self._fwd = core.get_forward(module_config)
        self.params = core.module_init(jax.random.key(config.seed), module_config)
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip),
            optax.adam(config.lr),
        )
        self.opt_state = self.optimizer.init(self.params)
        self.grad_steps = 0
        self._init_jit()

    def _loss(self, params, batch):
        import jax
        import jax.numpy as jnp

        c = self.config
        q_all, _ = self._fwd(params, batch["obs"])
        q = jnp.take_along_axis(q_all, batch["actions"][:, None], axis=1)[:, 0]
        q_next_target, _ = self._fwd(batch["target_params"], batch["next_obs"])
        if c.double_q:
            q_next_online, _ = self._fwd(params, batch["next_obs"])
            best = jnp.argmax(q_next_online, axis=-1)
        else:
            best = jnp.argmax(q_next_target, axis=-1)
        q_next = jnp.take_along_axis(q_next_target, best[:, None], axis=1)[:, 0]
        target = jax.lax.stop_gradient(
            batch["rewards"] + c.gamma * (1.0 - batch["dones"]) * q_next
        )
        td = q - target
        # Huber
        loss = jnp.where(
            jnp.abs(td) < 1.0, 0.5 * td**2, jnp.abs(td) - 0.5
        ).mean()
        return loss, {"td_loss": loss, "q_mean": q.mean()}

    def update(self, batch) -> Dict[str, float]:
        batch = dict(batch, target_params=self.target_params)
        metrics = super().update(batch)
        self.grad_steps += 1
        if self.grad_steps % self.config.target_update_freq == 0:
            self.sync_target()
        return metrics

    def compute_grads(self, batch):
        return super().compute_grads(
            dict(batch, target_params=self.target_params)
        )

    def sync_target(self):
        import jax

        self.target_params = jax.tree.map(lambda x: x, self.params)


class DQN(Algorithm):
    def _setup(self, config: DQNConfig):
        spaces = probe_env_spaces(config.env, config.env_to_module)
        self.module_config = build_module_config(config, spaces)
        cfg, mc = config, self.module_config
        self.learner_group = LearnerGroup(
            lambda: DQNLearner(cfg, mc), num_learners=config.num_learners
        )
        # distributed replicas each hold target params; target syncs are
        # step-count-driven so they stay aligned — track centrally
        self._grad_steps = 0
        self.buffer = ReplayBuffer(config.buffer_size, spaces["obs_dim"])
        self.env_runner_group = EnvRunnerGroup(
            config.env,
            self.module_config,
            num_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_runner,
            seed=config.seed,
            env_to_module_fn=config.env_to_module,
        )
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        self._rng = np.random.default_rng(config.seed)

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._total_steps / max(1, c.epsilon_decay_steps))
        return c.epsilon_initial + frac * (c.epsilon_final - c.epsilon_initial)

    def training_step(self) -> Dict[str, Any]:
        c = self.config
        eps = self._epsilon()
        t0 = time.monotonic()
        fragments = self.env_runner_group.sample(
            c.rollout_fragment_length, epsilon=eps
        )
        sample_time = time.monotonic() - t0

        steps_this_iter = 0
        for frag in fragments:
            T, B = frag["actions"].shape
            obs = frag["obs"]  # (T, B, D)
            next_obs = np.concatenate(
                [obs[1:], frag["final_obs"][None]], axis=0
            )
            self.buffer.add_batch(
                obs.reshape(T * B, -1),
                frag["actions"].reshape(-1),
                frag["rewards"].reshape(-1),
                next_obs.reshape(T * B, -1),
                frag["dones"].reshape(-1),
            )
            steps_this_iter += T * B
            self._record_returns(frag["episode_returns"])
        self._total_steps += steps_this_iter

        metrics: Dict[str, float] = {}
        num_updates = 0
        t1 = time.monotonic()
        if self.buffer.size >= c.learning_starts:
            num_updates = max(1, int(steps_this_iter * c.updates_per_env_step))
            for _ in range(num_updates):
                batch = self.buffer.sample(self._rng, c.train_batch_size)
                metrics = self.learner_group.update(batch)
                self._grad_steps += 1
                if (
                    not self.learner_group.is_local
                    and self._grad_steps % c.target_update_freq == 0
                ):
                    # distributed replicas never run DQNLearner.update, so
                    # the target copy is driven centrally
                    self.learner_group.foreach_learner("sync_target")
            self.env_runner_group.sync_weights(
                self.learner_group.get_weights()
            )
        learn_time = time.monotonic() - t1
        return {
            "epsilon": eps,
            "replay_buffer_size": self.buffer.size,
            "num_grad_updates": num_updates,
            "env_steps_this_iter": steps_this_iter,
            "time_sample_s": sample_time,
            "time_learn_s": learn_time,
            **metrics,
        }

    def get_state(self) -> Dict[str, Any]:
        return {"weights": self.learner_group.get_weights()}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.learner_group.set_weights(state["weights"])
        self.env_runner_group.sync_weights(self.learner_group.get_weights())


DQNConfig.algo_class = DQN
