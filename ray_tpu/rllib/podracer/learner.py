"""Central learner actor for the podracer plane.

One process owns the training step.  Fragments arrive as ObjectRefs in
``ingest`` calls — the arg-unpack resolves them over the direct-shm get
path (zero-copy on the co-hosted node; the payload never transits the
driver).  An in-flight queue assembles fixed-size batches with
staleness bounds: a fragment whose policy lag exceeds ``max_policy_lag``
is DROPPED, at ingest or at assembly time (droppable-on-lag — queued
work can go stale while it waits and must not train).  Fragments from
SUSPECT runners are deprioritized into a second queue consumed only
when no fresh-node fragment is available.

The actor is drain-plane checkpointable (``__rt_checkpoint__`` /
``__rt_restore__`` carry params, optimizer state and the policy-version
counter; queued fragments are droppable by design, so they are NOT part
of the migrated state).
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.podracer.fragment import FragmentMeta, StalenessHistogram


@ray_tpu.remote
class PodracerLearnerActor:
    """The fleet's single policy authority.

    ``learner_factory`` builds a ``rllib.learner_group.Learner`` inside
    this process; ``batch_from_fragments`` turns a list of fragment
    dicts into one training batch of ``batch_fragments`` fragments
    stacked along the env axis.
    """

    def __init__(
        self,
        learner_factory: Callable[[], Any],
        batch_from_fragments: Callable[[List[dict]], Dict[str, np.ndarray]],
        batch_fragments: int = 2,
        max_policy_lag: int = 4,
        train: bool = True,
        max_queue_fragments: Optional[int] = None,
    ):
        self.learner = learner_factory()
        self._assemble = batch_from_fragments
        self._batch_fragments = int(batch_fragments)
        self._max_lag = int(max_policy_lag)
        self._train = bool(train)
        # backpressure cap: sampling can transiently outpace training;
        # beyond this the OLDEST queued fragment is shed (it is the one
        # closest to the staleness bound anyway)
        from ray_tpu.common.config import cfg

        self._max_queue = (
            int(max_queue_fragments)
            if max_queue_fragments is not None
            else cfg.podracer_queue_factor * self._batch_fragments
        )
        self.policy_version = 0
        self._queue: collections.deque = collections.deque()
        self._suspect_queue: collections.deque = collections.deque()
        self._hist = StalenessHistogram()
        self._trained_fragments = 0
        self._dropped_stale = 0
        self._dropped_overflow = 0
        self._env_steps_trained = 0

    # -- fragment intake -------------------------------------------------
    def ingest(self, frag: Dict[str, np.ndarray], meta: dict):
        """Accept one fragment (payload resolved by arg-unpack from its
        shm ref); train when a full batch is assembled.  Returns
        ``{"episode_returns": [...], "train": stats-or-None}`` — small
        control-plane data only."""
        m = FragmentMeta.from_dict(meta)
        returns = [float(r) for r in np.asarray(frag["episode_returns"])]
        if self.policy_version - m.policy_version > self._max_lag:
            self._dropped_stale += 1
            # "version" rides EVERY ack: the driver's fan-out trigger
            # keys off it, so a fleet whose fragments all drop stale
            # still learns it must push fresh weights (training can run
            # ahead of acked updates — drain-consumed acks don't count)
            return {
                "episode_returns": returns, "train": None,
                "version": self.policy_version,
            }
        q = self._suspect_queue if m.suspect else self._queue
        q.append((m, frag))
        while (
            len(self._queue) + len(self._suspect_queue) > self._max_queue
        ):
            # shed oldest, suspect first
            (self._suspect_queue or self._queue).popleft()
            self._dropped_overflow += 1
        stats = self._maybe_train() if self._train else None
        return {
            "episode_returns": returns, "train": stats,
            "version": self.policy_version,
        }

    def _pop_fragment(self):
        """Fresh-node fragments strictly before suspect-node ones."""
        if self._queue:
            return self._queue.popleft()
        if self._suspect_queue:
            return self._suspect_queue.popleft()
        return None

    def _maybe_train(self) -> Optional[Dict[str, float]]:
        picked = []
        while len(picked) < self._batch_fragments:
            entry = self._pop_fragment()
            if entry is None:
                break
            m, frag = entry
            if self.policy_version - m.policy_version > self._max_lag:
                # went stale while queued: droppable-on-lag
                self._dropped_stale += 1
                continue
            picked.append(entry)
        if len(picked) < self._batch_fragments:
            # not enough fresh fragments yet: put them back in order,
            # each to the queue its suspect classification belongs to
            for entry in reversed(picked):
                q = self._suspect_queue if entry[0].suspect else self._queue
                q.appendleft(entry)
            return None
        batch = self._assemble([frag for _, frag in picked])
        metrics = self.learner.update(batch)
        for m, _ in picked:
            self._hist.add(self.policy_version - m.policy_version)
        self.policy_version += 1
        self._trained_fragments += len(picked)
        steps = sum(m.env_steps for m, _ in picked)
        self._env_steps_trained += steps
        out = {k: float(v) for k, v in metrics.items()}
        out["policy_version"] = self.policy_version
        out["env_steps_trained"] = steps
        out["fragments_in_batch"] = len(picked)
        return out

    # -- weights ---------------------------------------------------------
    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, params, bump_version: bool = False) -> int:
        self.learner.set_weights(params)
        if bump_version:
            self.policy_version += 1
        return self.policy_version

    def serve_weight_broadcast(
        self, group_name: str, root_rank: int = 0,
        wire_dtype: Optional[str] = None,
    ) -> int:
        """Root side of the weight fan-out: one ``broadcast_tree`` over
        the podracer collective group replaces N per-runner puts.  The
        skeleton carries the policy version exactly (ints never ride the
        quantized tensor path); with ``wire_dtype`` the root adopts the
        decode of its own encoding, so learner and every runner end
        bit-identical — the LearnerGroup invariant."""
        from ray_tpu.util import collective as col

        tree = {"v": int(self.policy_version), "w": self.learner.get_weights()}
        out = col.broadcast_tree(
            tree, src_rank=root_rank, group_name=group_name,
            wire_dtype=wire_dtype,
        )
        if wire_dtype is not None and wire_dtype != "fp32":
            self.learner.set_weights(out["w"])
        return self.policy_version

    # -- observability ---------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        return {
            "policy_version": self.policy_version,
            "trained_fragments": self._trained_fragments,
            "dropped_stale": self._dropped_stale,
            "dropped_overflow": self._dropped_overflow,
            "env_steps_trained": self._env_steps_trained,
            "queue_depth": len(self._queue) + len(self._suspect_queue),
            "suspect_queue_depth": len(self._suspect_queue),
            "staleness_hist": self._hist.snapshot(),
            "max_trained_lag": self._hist.max_lag,
        }

    # -- drain-plane migration hooks ------------------------------------
    def __rt_checkpoint__(self) -> dict:
        import jax

        return {
            "params": jax.tree.map(np.asarray, self.learner.params),
            "opt_state": jax.tree.map(np.asarray, self.learner.opt_state),
            "policy_version": self.policy_version,
            "trained_fragments": self._trained_fragments,
            "dropped_stale": self._dropped_stale,
            "dropped_overflow": self._dropped_overflow,
            "env_steps_trained": self._env_steps_trained,
            "staleness_hist": self._hist.state(),
        }

    def __rt_restore__(self, state: dict) -> None:
        self.learner.params = state["params"]
        self.learner.opt_state = state["opt_state"]
        self.policy_version = int(state["policy_version"])
        self._trained_fragments = int(state["trained_fragments"])
        self._dropped_stale = int(state["dropped_stale"])
        self._dropped_overflow = int(state["dropped_overflow"])
        self._env_steps_trained = int(state["env_steps_trained"])
        self._hist.restore(state["staleness_hist"])
        # queued fragments are NOT migrated: they are droppable by the
        # staleness contract, and the fleet refills the queue in one
        # fragment interval
