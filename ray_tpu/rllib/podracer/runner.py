"""PodracerRunner: free-running vectorized env fleet + central learner.

The Podracer/Sebulba shape (arXiv 2104.06272) on this runtime's three
perf planes:

- **task plane** — every runner has exactly one in-flight
  ``sample_podracer`` actor call (spec-skeleton submit, per-tick frame
  coalescing); the driver relaunches it the moment a fragment lands, so
  runners never idle on the driver and there is no per-step coroutine.
- **data plane** — the fragment payload is a single shm put inside the
  runner (vectored write / inline slab); the driver sees only
  ``(meta, ref)`` and forwards the ref to the learner, whose arg-unpack
  resolves it over the direct-shm get path.  Zero payload bytes through
  the driver.
- **collective plane** — weight fan-out is one ``col.broadcast_tree``
  over a standing group (learner = rank 0, runner i = rank i+1), with
  opt-in ``wire_dtype="int8"`` (~4x fewer wire bytes).  Runners join a
  fan-out generation at their next fragment boundary, so the fleet
  keeps sampling while the push propagates.

Failure model: a dead runner is replaced (fresh actor, decorrelated
seed, collective group re-formed with the replacement under the dead
rank) without the learner ever observing the death — its in-flight
fragments are simply lost.  A SUSPECT runner keeps sampling but its
fragments are deprioritized in the learner queue.  The learner is the
only stateful member and is drain-checkpointable.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.common.config import cfg
from ray_tpu.rllib.podracer.learner import PodracerLearnerActor


@dataclasses.dataclass
class PodracerConfig:
    rollout_fragment_length: int = 32
    # fragments stacked (along the env axis) into one training batch
    batch_fragments: int = 2
    # staleness bound K: a fragment sampled > K learner updates ago
    # never trains (dropped at ingest or batch-assembly time)
    max_policy_lag: int = 4
    # learner updates between weight fan-outs (1 = push every update)
    weight_sync_period: int = 1
    # None/"fp32" = exact; "bf16"/"int8" = block-quantized fan-out
    weight_wire_dtype: Optional[str] = None
    # route fan-out through the collective plane.  False pushes the
    # learner's get_weights ref to each runner instead (no barrier, the
    # learner never blocks; per-caller ordering lands it before the
    # runner's next fragment) — wire_dtype is a collective-path feature
    collective_fanout: bool = True
    # cap on forwarded-but-unconsumed fragments before runners pause
    # (free-running sampling that the learner will only drop wastes the
    # very cores the learner needs).  None = queue_factor * batch size
    max_inflight_fragments: Optional[int] = None
    epsilon: Optional[float] = None  # e-greedy knob for DQN-family
    replace_dead_runners: bool = True
    # None = cfg.podracer_progress_timeout_s
    progress_timeout_s: Optional[float] = None


class PodracerRunner:
    """Driver-side orchestrator.  Owns the learner actor and drives the
    ``EnvRunnerGroup``'s actors as a free-running fleet."""

    def __init__(
        self,
        env_runner_group,
        learner_factory: Callable[[], Any],
        batch_from_fragments: Callable[[List[dict]], Dict[str, np.ndarray]],
        config: Optional[PodracerConfig] = None,
        *,
        train: bool = True,
        keep_fragment_refs: bool = False,
    ):
        import uuid

        self.config = config or PodracerConfig()
        self.group = env_runner_group
        self.learner = PodracerLearnerActor.options(num_cpus=1).remote(
            learner_factory,
            batch_from_fragments,
            self.config.batch_fragments,
            self.config.max_policy_lag,
            train,
        )
        self._train = train
        self._keep_refs = keep_fragment_refs
        self._inflight_cap = (
            int(self.config.max_inflight_fragments)
            if self.config.max_inflight_fragments is not None
            else cfg.podracer_queue_factor * self.config.batch_fragments
        )
        self.fragment_log: List[tuple] = []  # (idx, meta, ref) if kept
        self._incarnation = [0] * len(self.group.runners)
        # ref bookkeeping: sample meta-ref -> runner idx; ingest ref ->
        # frag ref (kept alive until the learner consumed it)
        self._sample_refs: Dict[Any, int] = {}
        self._ingest_refs: Dict[Any, Any] = {}
        self._bcast: Optional[dict] = None
        # fan-out trigger state: versions, NOT acked updates.  The
        # learner can train ahead of what the driver has acked (drain
        # consumes acks silently; stale-dropped ingests train nothing
        # but still advance nothing) — keying the push off acked update
        # counts can deadlock the fleet at lag > K with no push pending
        self._learner_version = 0
        self._pushed_version = 0
        self._last_bcast_ms: Optional[float] = None
        self._replaced_runners = 0
        self._fragments_lost = 0
        self._suspect: frozenset = frozenset()
        self._suspect_at = float("-inf")
        self._node_of: Dict[int, Optional[str]] = {}
        self._col_group: Optional[str] = None
        if self.config.collective_fanout:
            self._col_group = f"podracer-{uuid.uuid4().hex[:8]}"
            self._create_group()
        # initial weight push: every runner starts bit-identical to the
        # learner (put path — runners are idle, no fragment boundary to
        # piggyback a collective join on yet)
        self._put_sync_all()
        self._refresh_node_map()

    # -- group / fleet plumbing -----------------------------------------
    def _members(self):
        return [self.learner] + list(self.group.runners)

    def _create_group(self):
        from ray_tpu.util import collective as col

        col.create_collective_group(
            self._members(), group_name=self._col_group
        )

    def _put_sync_all(self, indices: Optional[List[int]] = None):
        """Fallback/initial weight sync: one put, N borrowers."""
        w, v = ray_tpu.get(
            [self.learner.get_weights.remote(),
             self.learner.stats.remote()],
        )
        ref = ray_tpu.put(w)
        runners = self.group.runners
        idxs = range(len(runners)) if indices is None else indices
        ray_tpu.get([
            runners[i].set_weights_versioned.remote(
                ref, v["policy_version"]
            )
            for i in idxs
        ])
        self._learner_version = max(
            self._learner_version, int(v["policy_version"])
        )
        if indices is None:
            self._pushed_version = self._learner_version

    def _refresh_node_map(self):
        """actor -> node mapping for the suspect-deprioritization path."""
        from ray_tpu.core.runtime import get_runtime

        try:
            rt = get_runtime()
            rows = rt._run(rt.gcs.call("list_actors", {}), timeout=10.0)
            by_id = {r["actor_id"]: r.get("node_id") for r in rows}
            for i, r in enumerate(self.group.runners):
                self._node_of[i] = by_id.get(r._actor_id.hex())
        except Exception:
            pass  # placement metadata is advisory

    def _suspect_nodes(self) -> frozenset:
        now = time.monotonic()
        if now - self._suspect_at >= cfg.collective_suspect_refresh_s:
            from ray_tpu.core.runtime import get_runtime

            try:
                rt = get_runtime()
                rows = rt._run(rt.gcs.call("node_health", {}), timeout=5.0)
                self._suspect = frozenset(
                    nid for nid, r in rows.items() if r.get("suspect")
                )
            except Exception:
                pass  # keep the stale view; health is advisory here
            self._suspect_at = now
        return self._suspect

    # -- sampling --------------------------------------------------------
    def _launch_sample(self, idx: int):
        c = self.config
        ref = self.group.runners[idx].sample_podracer.remote(
            c.rollout_fragment_length, c.epsilon
        )
        self._sample_refs[ref] = idx

    def _launch_all_idle(self):
        busy = set(self._sample_refs.values())
        if self._bcast is not None:
            busy |= self._bcast["pending"]
        for i in range(len(self.group.runners)):
            if i not in busy:
                self._launch_sample(i)

    # -- weight fan-out --------------------------------------------------
    def _initiate_broadcast(self):
        """Start a fan-out generation: the learner (root) enters the
        broadcast now; each runner joins at its next fragment boundary.
        The fleet never stops sampling."""
        c = self.config
        root_ref = self.learner.serve_weight_broadcast.remote(
            self._col_group, 0, c.weight_wire_dtype
        )
        self._bcast = {
            "root_ref": root_ref,
            "member_refs": {},     # ref -> runner idx
            "pending": set(),      # runner idx joined, ref in flight
            "waiting": set(range(len(self.group.runners))),
            "t0": time.monotonic(),
            "failed": False,
        }
        # the root serves its version AT EXECUTION (>= this), so this
        # marker is conservative — never claims a push it didn't make
        self._pushed_version = self._learner_version
        # a parked (backpressured) runner has no in-flight fragment and
        # so no upcoming boundary — it is AT one; join it immediately or
        # the generation never completes
        sampling = set(self._sample_refs.values())
        for idx in list(self._bcast["waiting"]):
            if idx not in sampling:
                self._join_broadcast(idx)

    def _join_broadcast(self, idx: int):
        b = self._bcast
        c = self.config
        ref = self.group.runners[idx].join_weight_broadcast.remote(
            self._col_group, 0, c.weight_wire_dtype
        )
        b["member_refs"][ref] = idx
        b["waiting"].discard(idx)
        b["pending"].add(idx)

    def _broadcast_refs(self):
        b = self._bcast
        if b is None:
            return []
        refs = list(b["member_refs"])
        if b["root_ref"] is not None:
            refs.append(b["root_ref"])
        return refs

    def _finish_broadcast_ref(self, ref) -> bool:
        """Returns True when the generation completed (or aborted)."""
        b = self._bcast
        try:
            ray_tpu.get(ref, timeout=1.0)
        except Exception:
            b["failed"] = True
        if ref in b["member_refs"]:
            idx = b["member_refs"].pop(ref)
            b["pending"].discard(idx)
            if not b["failed"] and not self._backpressured():
                self._launch_sample(idx)
        else:
            b["root_ref"] = None
        if b["failed"]:
            self._abort_broadcast()
            return True
        if b["root_ref"] is None and not b["waiting"] and not b["pending"]:
            self._last_bcast_ms = (time.monotonic() - b["t0"]) * 1e3
            self._bcast = None
            if not self._backpressured():
                self._launch_all_idle()
            return True
        return False

    def _abort_broadcast(self):
        """A member died (or an op failed) mid-generation: settle the
        outstanding refs, re-form the group, and restore fleet-wide
        weight consistency over the put path.  The learner actor itself
        is untouched — no learner-step failure."""
        b, self._bcast = self._bcast, None
        for ref in list(b["member_refs"]) + (
            [b["root_ref"]] if b["root_ref"] is not None else []
        ):
            try:
                ray_tpu.get(ref, timeout=60.0)
            except Exception:
                pass
        self._repair_fleet()
        self._put_sync_all()
        self._launch_all_idle()

    # -- failure handling ------------------------------------------------
    def _repair_fleet(self):
        """Replace dead runners and re-form the collective group with
        replacements joining under the dead ranks."""
        from ray_tpu.core.errors import RayTpuError  # noqa: F401

        dead = []
        for i, r in enumerate(self.group.runners):
            try:
                ray_tpu.get(r.ping.remote(), timeout=60.0)
            except Exception:
                dead.append(i)
        if not dead:
            return
        if not self.config.replace_dead_runners:
            raise RuntimeError(f"env runners {dead} died")
        replaced_ranks = []
        for i in dead:
            self._incarnation[i] += 1
            self.group.replace_runner(i, incarnation=self._incarnation[i])
            self._replaced_runners += 1
            replaced_ranks.append(i + 1)  # learner holds rank 0
            # drop any bookkeeping that still points at the old handle
            self._sample_refs = {
                ref: idx for ref, idx in self._sample_refs.items()
                if idx != i
            }
        if self._col_group is not None:
            from ray_tpu.util import collective as col

            members = self._members()
            ranks = [
                r if r in replaced_ranks else None
                for r in range(len(members))
            ]
            try:
                col.reform_collective_group(
                    len(members), group_name=self._col_group,
                    actors=members, ranks=ranks,
                )
            except Exception:
                # poisoned beyond reform: rebuild from scratch
                try:
                    col.destroy_collective_group(
                        self._col_group, actors=members
                    )
                except Exception:
                    pass
                self._create_group()
        self._put_sync_all(indices=dead)
        self._refresh_node_map()

    def _on_dead_sample(self, idx: int):
        self._fragments_lost += 1
        if self._bcast is not None and (
            idx in self._bcast["waiting"] or idx in self._bcast["pending"]
        ):
            # the generation can never complete; abort repairs the fleet
            self._abort_broadcast()
            return
        self._repair_fleet()
        self._launch_all_idle()

    # -- the loop --------------------------------------------------------
    def run(
        self,
        *,
        min_updates: int = 1,
        min_fragments: int = 0,
        max_seconds: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Pump the fleet until ``min_updates`` learner updates (or, with
        training off, ``min_fragments`` fragments) completed.  Returns
        aggregated control-plane stats; payload bytes never surface
        here."""
        c = self.config
        progress_s = (
            c.progress_timeout_s
            if c.progress_timeout_s is not None
            else cfg.podracer_progress_timeout_s
        )
        deadline = time.monotonic() + (
            max_seconds if max_seconds is not None else progress_s
        )
        out: Dict[str, Any] = {
            "updates": 0, "fragments": 0, "env_steps_sampled": 0,
            "episode_returns": [],
        }
        last_train: Dict[str, Any] = {}
        self._launch_all_idle()
        while (
            out["updates"] < min_updates
            if self._train
            else out["fragments"] < min_fragments
        ):
            # control-plane refs (broadcast legs, ingest acks) come FIRST:
            # on a loaded host a short-fragment fleet keeps a sample ref
            # ready at every wait, and a samples-first ordering starves
            # the learner acks the loop needs to count updates at all
            refs = (
                self._broadcast_refs()
                + list(self._ingest_refs)
                + list(self._sample_refs)
            )
            budget = deadline - time.monotonic()
            if budget <= 0:
                raise TimeoutError(
                    f"podracer made no sufficient progress in "
                    f"{progress_s}s ({out})"
                )
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=budget)
            if not ready:
                continue
            # drain EVERY ready ref before re-waiting; handlers mutate
            # the bookkeeping (abort settles broadcast legs, repair drops
            # sample refs), so each ref re-checks membership here
            for ref in ready:
                if ref in self._sample_refs:
                    self._on_sample_ready(ref, out)
                elif ref in self._ingest_refs:
                    self._on_ingest_ready(ref, out, last_train)
                elif self._bcast is not None and (
                    ref in self._bcast["member_refs"]
                    or ref == self._bcast["root_ref"]
                ):
                    self._finish_broadcast_ref(ref)
        out.update(last_train)
        out["replaced_runners"] = self._replaced_runners
        out["fragments_lost"] = self._fragments_lost
        if self._last_bcast_ms is not None:
            out["weight_broadcast_ms"] = self._last_bcast_ms
        return out

    def _on_sample_ready(self, ref, out):
        idx = self._sample_refs.pop(ref)
        try:
            meta, frag_ref = ray_tpu.get(ref, timeout=60.0)
        except Exception:
            # runner died mid-fragment: replace it, learner unaffected
            self._on_dead_sample(idx)
            return
        node = self._node_of.get(idx)
        meta["suspect"] = bool(node and node in self._suspect_nodes())
        meta["runner_index"] = idx
        meta["incarnation"] = self._incarnation[idx]
        ingest_ref = self.learner.ingest.remote(frag_ref, meta)
        # frag_ref stays pinned until the learner consumed it
        self._ingest_refs[ingest_ref] = frag_ref
        if self._keep_refs:
            self.fragment_log.append((idx, dict(meta), frag_ref))
        out["fragments"] += 1
        out["env_steps_sampled"] += int(meta["env_steps"])
        # a pending fan-out generation is joined BEFORE the next sample
        # (per-caller ordering makes the relaunch run under new weights)
        if self._bcast is not None and idx in self._bcast["waiting"]:
            self._join_broadcast(idx)
        elif not self._backpressured():
            self._launch_sample(idx)
        # else: runner parks idle; an ingest completion relaunches it

    def _backpressured(self) -> bool:
        return len(self._ingest_refs) >= self._inflight_cap

    def _on_ingest_ready(self, ref, out, last_train):
        self._ingest_refs.pop(ref)
        try:
            res = ray_tpu.get(ref, timeout=60.0)
        except Exception:
            # the fragment ref failed to resolve (its runner died after
            # handoff): the fragment is lost, the learner is fine
            self._fragments_lost += 1
            return
        finally:
            # a consumed fragment frees queue room: wake parked runners
            if not self._backpressured():
                self._launch_all_idle()
        out["episode_returns"].extend(res["episode_returns"])
        self._learner_version = max(
            self._learner_version, int(res.get("version", 0))
        )
        stats = res["train"]
        if stats is not None:
            out["updates"] += 1
            last_train.update(stats)
        if (
            self._learner_version - self._pushed_version
            >= self.config.weight_sync_period
        ):
            if self._col_group is not None:
                if self._bcast is None:
                    self._initiate_broadcast()
            else:
                self._put_fanout(self._learner_version)

    def _put_fanout(self, version: int):
        """Barrier-free fan-out: each runner resolves the learner's
        ``get_weights`` ref over direct shm — the learner never blocks
        and per-caller ordering lands the push before the runner's next
        relaunch.  The trade vs the collective path: N unicast pulls
        (no tree, no wire quantization), zero generation latency."""
        wref = self.learner.get_weights.remote()
        for r in self.group.runners:
            # dropped ref is safe: a set_weights failure surfaces
            # through that runner's next tracked sample ref
            # rtlint: disable-next=RT105
            r.set_weights_versioned.remote(wref, int(version))
        self._pushed_version = int(version)

    # -- control-plane helpers ------------------------------------------
    def broadcast_weights(
        self, wire_dtype: Optional[str] = None
    ) -> float:
        """Synchronous fan-out (fleet must be idle — no in-flight
        samples); returns elapsed ms.  The bench's fp32-vs-int8 A/B
        row."""
        assert not self._sample_refs and self._bcast is None
        t0 = time.monotonic()
        refs = [
            self.learner.serve_weight_broadcast.remote(
                self._col_group, 0, wire_dtype
            )
        ] + [
            r.join_weight_broadcast.remote(self._col_group, 0, wire_dtype)
            for r in self.group.runners
        ]
        ray_tpu.get(refs, timeout=cfg.collective_op_timeout_s)
        return (time.monotonic() - t0) * 1e3

    def drain_in_flight(self, timeout: float = 120.0):
        """Let in-flight work land without relaunching (pause the
        fleet); used between interleaved bench windows."""
        deadline = time.monotonic() + timeout
        while self._sample_refs or self._ingest_refs or self._bcast:
            refs = (
                self._broadcast_refs() + list(self._ingest_refs)
                + list(self._sample_refs)
            )
            ready, _ = ray_tpu.wait(
                refs, num_returns=1,
                timeout=max(0.1, deadline - time.monotonic()),
            )
            if not ready:
                raise TimeoutError("podracer drain timed out")
            ref = ready[0]
            if ref in self._sample_refs:
                idx = self._sample_refs.pop(ref)
                try:
                    meta, frag_ref = ray_tpu.get(ref, timeout=60.0)
                except Exception:
                    self._fragments_lost += 1
                    continue
                if self._bcast is not None and idx in self._bcast["waiting"]:
                    self._join_broadcast(idx)
            elif ref in self._ingest_refs:
                self._ingest_refs.pop(ref)
                try:
                    ray_tpu.get(ref, timeout=60.0)
                except Exception:
                    self._fragments_lost += 1
            elif self._bcast is not None:
                b = self._bcast
                try:
                    ray_tpu.get(ref, timeout=60.0)
                except Exception:
                    b["failed"] = True
                if ref in b["member_refs"]:
                    b["pending"].discard(b["member_refs"].pop(ref))
                else:
                    b["root_ref"] = None
                if b["failed"]:
                    self._abort_broadcast()
                    # abort relaunches; cancel those for the drain
                    self._sample_refs.clear()
                elif (
                    b["root_ref"] is None and not b["waiting"]
                    and not b["pending"]
                ):
                    self._bcast = None

    def get_weights(self):
        return ray_tpu.get(
            self.learner.get_weights.remote(), timeout=120.0
        )

    def learner_stats(self) -> Dict[str, Any]:
        return ray_tpu.get(self.learner.stats.remote(), timeout=120.0)

    def stop(self):
        self._sample_refs.clear()
        self._ingest_refs.clear()
        self._bcast = None
        if self._col_group is not None:
            from ray_tpu.util import collective as col

            try:
                col.destroy_collective_group(
                    self._col_group, actors=self._members()
                )
            except Exception:
                pass  # a dead member mustn't block teardown
            self._col_group = None
        try:
            ray_tpu.kill(self.learner)
        except Exception:
            pass
