"""Podracer throughput plane (arXiv 2104.06272, Sebulba shape).

Free-running vectorized env actors feed a central learner actor through
shm-ref'd rollout fragments; weights fan back out over one block-
quantizable ``broadcast_tree``.  First end-to-end composition of the
batched task plane, data-plane v2 and Collectives v2 — and the
regression net for all three (``env_steps_per_s`` in bench.py).
"""

from ray_tpu.rllib.podracer.fragment import FragmentMeta, StalenessHistogram
from ray_tpu.rllib.podracer.learner import PodracerLearnerActor
from ray_tpu.rllib.podracer.runner import PodracerConfig, PodracerRunner

__all__ = [
    "FragmentMeta",
    "StalenessHistogram",
    "PodracerLearnerActor",
    "PodracerConfig",
    "PodracerRunner",
]
