"""Rollout-fragment metadata + staleness accounting for the podracer plane.

A fragment's PAYLOAD (the (T, B) arrays from ``EnvRunnerActor.sample``)
never rides these types — it lives in the shm arena and moves by
ObjectRef.  ``FragmentMeta`` is the few-dozen-byte control record the
driver routes: who sampled it, under which policy version, how many env
steps it carries, and whether its runner's node was SUSPECT when it
landed (the health plane's deprioritization input).
"""

from __future__ import annotations

import dataclasses
from typing import Dict


@dataclasses.dataclass
class FragmentMeta:
    """Control-plane record for one rollout fragment."""

    runner_index: int       # position in the fleet (stable across replaces)
    seq: int                # per-runner fragment counter (bit-repro key)
    policy_version: int     # learner version of the weights that sampled it
    env_steps: int          # T * num_envs
    suspect: bool = False   # runner's node SUSPECT at arrival (deprioritize)
    incarnation: int = 0    # bumps when the runner is replaced after a death

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "FragmentMeta":
        return cls(**d)


class StalenessHistogram:
    """Counts of policy lag (learner version − fragment version) over the
    fragments that actually TRAINED — the published observability row for
    the staleness bound (lag ≤ K is enforced upstream; this shows the
    realized distribution inside the bound)."""

    def __init__(self):
        self._counts: Dict[int, int] = {}

    def add(self, lag: int) -> None:
        lag = int(lag)
        self._counts[lag] = self._counts.get(lag, 0) + 1

    @property
    def max_lag(self) -> int:
        return max(self._counts) if self._counts else 0

    @property
    def total(self) -> int:
        return sum(self._counts.values())

    def snapshot(self) -> Dict[int, int]:
        return dict(sorted(self._counts.items()))

    def state(self) -> Dict[int, int]:
        return dict(self._counts)

    def restore(self, state: Dict[int, int]) -> None:
        self._counts = {int(k): int(v) for k, v in state.items()}

    def __repr__(self):
        return f"StalenessHistogram({self.snapshot()})"
