"""APPO: asynchronous PPO — IMPALA's decoupled actor-learner pipeline
with the PPO clipped-surrogate objective on V-trace-corrected targets.

Role-equivalent of ray: rllib/algorithms/appo/appo.py (APPOConfig,
APPO — "IMPALA + surrogate loss + target-network smoothing"): runners
sample continuously under slightly-stale policies, V-trace corrects the
off-policyness, and the importance ratio is clipped PPO-style so one
very-stale fragment cannot blow up the update.  The optional target
network (use_kl_loss analogue collapsed: the clip does the trust-region
work) smooths tgt_logp drift between weight syncs.

APPO inherits IMPALA's ``throughput_mode="podracer"`` wholesale — the
podracer plane builds its central learner from ``learner_cls``, so the
clipped-surrogate learner rides the free-running fleet unchanged
(``tests/test_zz_podracer.py::TestImpalaPodracerMode``).  The ratio
clip matters MORE there: fragments arrive at up to ``max_policy_lag``
versions stale by design.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, IMPALALearner, vtrace


@dataclasses.dataclass
class APPOConfig(IMPALAConfig):
    clip_param: float = 0.2
    lr: float = 3e-4
    entropy_coeff: float = 0.005


class APPOLearner(IMPALALearner):
    def _loss(self, params, batch):
        import jax
        import jax.numpy as jnp

        c = self.config
        T, B = batch["actions"].shape
        obs_flat = batch["obs"].reshape(T * B, -1)
        logits, values = self._fwd(params, obs_flat)
        logits = logits.reshape(T, B, -1)
        values = values.reshape(T, B)
        _, last_values = self._fwd(params, batch["last_obs"])
        logp_all = jax.nn.log_softmax(logits)
        tgt_logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1
        )[..., 0]
        vs, pg_adv = vtrace(
            batch["logp"], jax.lax.stop_gradient(tgt_logp),
            batch["rewards"], jax.lax.stop_gradient(values),
            batch["dones"], jax.lax.stop_gradient(last_values),
            c.gamma, c.vtrace_rho_clip, c.vtrace_c_clip,
        )
        adv = jax.lax.stop_gradient(pg_adv)
        # PPO surrogate on the behavior ratio (the APPO difference from
        # IMPALA's plain ρ-weighted policy gradient)
        ratio = jnp.exp(tgt_logp - batch["logp"])
        pg = -jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - c.clip_param, 1 + c.clip_param) * adv,
        ).mean()
        vf = 0.5 * ((values - jax.lax.stop_gradient(vs)) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = pg + c.vf_coeff * vf - c.entropy_coeff * entropy
        return total, {
            "policy_loss": pg,
            "vf_loss": vf,
            "entropy": entropy,
            "mean_ratio": ratio.mean(),
        }


class APPO(IMPALA):
    """Same async pipeline as IMPALA; only the learner's loss differs."""

    learner_cls = APPOLearner


APPOConfig.algo_class = APPO
