"""SAC (discrete): twin soft-Q critics, categorical policy, learned
temperature.

Role-equivalent of ray: rllib/algorithms/sac/sac.py (SACConfig, SAC) in
its discrete-action form (Christodoulou 2019, arXiv:1910.07207), on this
stack's replay-based shapes (shared with DQN): sample → store → replay
→ one jit'd soft actor-critic update → polyak target sync.

Discrete SAC computes exact expectations over actions (no
reparameterization): soft state value
V(s) = Σ_a π(a|s)[min(Q1t, Q2t)(s, a) − α log π(a|s)], critic targets
y = r + γ(1−d)V(s'), actor loss E_s Σ_a π(a|s)[α log π(a|s) − minQ(s,a)],
and α is trained toward a target entropy.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict

import numpy as np

from ray_tpu.rllib import core
from ray_tpu.rllib.algorithm import (
    Algorithm,
    AlgorithmConfig,
    build_module_config,
    probe_env_spaces,
)
from ray_tpu.rllib.dqn import ReplayBuffer
from ray_tpu.rllib.env_runner import EnvRunnerGroup


@dataclasses.dataclass
class SACConfig(AlgorithmConfig):
    lr: float = 3e-4
    alpha_lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.01             # polyak factor for target critics
    buffer_size: int = 50_000
    learning_starts: int = 500
    train_batch_size: int = 128
    updates_per_env_step: float = 1.0
    target_entropy_scale: float = 0.5  # H_target = scale * log(|A|)
    initial_alpha: float = 1.0
    grad_clip: float = 10.0
    hidden: tuple = (64, 64)
    rollout_fragment_length: int = 16


class SACLearner:
    """params = {"pi", "q1", "q2", "q1_t", "q2_t", "log_alpha"} — three
    independent MLP modules (the value heads of the Q nets are unused)."""

    def __init__(self, config: SACConfig, module_config):
        import jax
        import jax.numpy as jnp
        import optax

        self.config = config
        self.module_config = module_config
        self._fwd = core.get_forward(module_config)
        ks = jax.random.split(jax.random.key(config.seed), 3)
        pi = core.module_init(ks[0], module_config)
        q1 = core.module_init(ks[1], module_config)
        q2 = core.module_init(ks[2], module_config)
        self.params = {
            "pi": pi, "q1": q1, "q2": q2,
            "q1_t": jax.tree.map(jnp.copy, q1),
            "q2_t": jax.tree.map(jnp.copy, q2),
            "log_alpha": jnp.log(jnp.float32(config.initial_alpha)),
        }
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip),
            optax.adam(config.lr),
        )
        trainable = {k: self.params[k] for k in ("pi", "q1", "q2")}
        self.opt_state = self.optimizer.init(trainable)
        self.alpha_opt = optax.adam(config.alpha_lr)
        self.alpha_opt_state = self.alpha_opt.init(self.params["log_alpha"])
        self.target_entropy = config.target_entropy_scale * float(
            np.log(module_config.num_actions)
        )
        self._update = jax.jit(self._build_update())

    def _q(self, qparams, obs):
        return self._fwd(qparams, obs)[0]  # logits head read as Q values

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        c = self.config

        def losses(trainable, frozen, batch):
            pi, q1, q2 = trainable["pi"], trainable["q1"], trainable["q2"]
            q1_t, q2_t = frozen["q1_t"], frozen["q2_t"]
            alpha = jnp.exp(frozen["log_alpha"])
            obs, nobs = batch["obs"], batch["next_obs"]
            B = obs.shape[0]
            a = batch["actions"]

            # critic targets from the CURRENT policy at s'
            nlogits, _ = self._fwd(pi, nobs)
            nlogp = jax.nn.log_softmax(nlogits)
            nprobs = jnp.exp(nlogp)
            minq_t = jnp.minimum(self._q(q1_t, nobs), self._q(q2_t, nobs))
            v_next = (nprobs * (minq_t - alpha * nlogp)).sum(-1)
            y = jax.lax.stop_gradient(
                batch["rewards"]
                + c.gamma * (1.0 - batch["dones"]) * v_next
            )
            q1_sa = jnp.take_along_axis(
                self._q(q1, obs), a[:, None], axis=1
            )[:, 0]
            q2_sa = jnp.take_along_axis(
                self._q(q2, obs), a[:, None], axis=1
            )[:, 0]
            critic = 0.5 * (
                ((q1_sa - y) ** 2).mean() + ((q2_sa - y) ** 2).mean()
            )

            # actor: expected soft value under π at s (critics frozen)
            logits, _ = self._fwd(pi, obs)
            logp = jax.nn.log_softmax(logits)
            probs = jnp.exp(logp)
            minq = jax.lax.stop_gradient(
                jnp.minimum(self._q(q1, obs), self._q(q2, obs))
            )
            actor = (probs * (alpha * logp - minq)).sum(-1).mean()
            entropy = -(probs * logp).sum(-1).mean()
            return critic + actor, {
                "critic_loss": critic,
                "actor_loss": actor,
                "entropy": entropy,
                "alpha": alpha,
            }

        def update(params, opt_state, alpha_opt_state, batch):
            trainable = {k: params[k] for k in ("pi", "q1", "q2")}
            frozen = {k: params[k] for k in ("q1_t", "q2_t", "log_alpha")}
            (_, metrics), grads = jax.value_and_grad(
                losses, has_aux=True
            )(trainable, frozen, batch)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, trainable
            )
            trainable = optax.apply_updates(trainable, updates)

            # temperature toward the target entropy: α grows while the
            # policy is below target entropy, shrinks above it
            def alpha_loss(log_alpha):
                return log_alpha * jax.lax.stop_gradient(
                    metrics["entropy"] - self.target_entropy
                )

            agrad = jax.grad(alpha_loss)(params["log_alpha"])
            aupd, alpha_opt_state = self.alpha_opt.update(
                agrad, alpha_opt_state
            )
            log_alpha = optax.apply_updates(params["log_alpha"], aupd)

            # polyak critic-target sync
            tau = c.tau
            new = dict(trainable)
            new["q1_t"] = jax.tree.map(
                lambda t, s: (1 - tau) * t + tau * s,
                params["q1_t"], trainable["q1"],
            )
            new["q2_t"] = jax.tree.map(
                lambda t, s: (1 - tau) * t + tau * s,
                params["q2_t"], trainable["q2"],
            )
            new["log_alpha"] = log_alpha
            return new, opt_state, alpha_opt_state, metrics

        return update

    def update(self, batch) -> Dict[str, Any]:
        (self.params, self.opt_state, self.alpha_opt_state,
         metrics) = self._update(
            self.params, self.opt_state, self.alpha_opt_state, batch
        )
        return metrics


class SAC(Algorithm):
    def _setup(self, config: SACConfig):
        spaces = probe_env_spaces(config.env, config.env_to_module)
        self.module_config = build_module_config(config, spaces)
        self.learner = SACLearner(config, self.module_config)
        self.buffer = ReplayBuffer(config.buffer_size, spaces["obs_dim"])
        self._rng = np.random.default_rng(config.seed)
        self.env_runner_group = EnvRunnerGroup(
            config.env,
            self.module_config,
            num_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_runner,
            seed=config.seed,
            env_to_module_fn=config.env_to_module,
        )
        self._sync()

    def _sync(self):
        # runners sample from the categorical policy head
        self.env_runner_group.sync_weights(self.learner.params["pi"])

    def training_step(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.monotonic()
        # on-policy categorical sampling (no epsilon): SAC's exploration
        # is the policy's own entropy, held up by the temperature
        frags = self.env_runner_group.sample(c.rollout_fragment_length)
        env_steps = 0
        for frag in frags:
            T, B = frag["actions"].shape
            obs = frag["obs"]
            next_obs = np.concatenate(
                [obs[1:], frag["final_obs"][None]], axis=0
            )
            self.buffer.add_batch(
                obs.reshape(T * B, -1),
                frag["actions"].reshape(-1),
                frag["rewards"].reshape(-1),
                next_obs.reshape(T * B, -1),
                frag["dones"].reshape(-1),
            )
            env_steps += T * B
            self._record_returns(frag["episode_returns"])
        self._total_steps += env_steps
        stats: Dict[str, Any] = {"env_steps": env_steps}
        if self.buffer.size >= c.learning_starts:
            n_updates = max(1, int(env_steps * c.updates_per_env_step))
            metrics: Dict[str, Any] = {}
            for _ in range(n_updates):
                batch = self.buffer.sample(self._rng, c.train_batch_size)
                metrics = self.learner.update(batch)
            stats.update({k: float(v) for k, v in metrics.items()})
            stats["updates"] = n_updates
            self._sync()
        stats["iter_time_s"] = time.monotonic() - t0
        return stats

    def get_state(self) -> Dict[str, Any]:
        return {"params": self.learner.params}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.learner.params = state["params"]
        self._sync()

    def stop(self) -> None:
        self.env_runner_group.stop()


SACConfig.algo_class = SAC
