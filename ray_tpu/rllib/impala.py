"""IMPALA: asynchronous actor-learner with V-trace off-policy correction.

Role-equivalent of ray: rllib/algorithms/impala/ (IMPALAConfig, IMPALA,
vtrace) on this stack's shapes: EnvRunner actors sample continuously
and NEVER gang-block the learner — the algorithm keeps one in-flight
sample per runner, updates on whichever fragment lands first (V-trace
correcting for the policy lag), syncs fresh weights to that runner
only, and immediately relaunches it.  The update is one jit'd function
(V-trace targets + policy gradient + value + entropy loss), so on a
mesh the gradient reduction compiles to ICI collectives like PPO's.

V-trace (Espeholt et al. 2018, arXiv:1802.01561): with behavior logp μ
(recorded by the runner at sample time) and target logp π (current
learner policy), truncated importance weights ρ=min(ρ̄, π/μ),
c=min(c̄, π/μ) give corrected value targets

    v_s = V_s + δ_s + γ c_s (v_{s+1} − V_{s+1}),
    δ_s = ρ_s (r_s + γ V_{s+1} − V_s)

computed as a reverse lax.scan over the fragment.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib import core
from ray_tpu.rllib.algorithm import (
    Algorithm,
    AlgorithmConfig,
    build_module_config,
    probe_env_spaces,
)
from ray_tpu.rllib.env_runner import EnvRunnerGroup
from ray_tpu.rllib.learner_group import Learner


@dataclasses.dataclass
class IMPALAConfig(AlgorithmConfig):
    lr: float = 5e-4
    gamma: float = 0.99
    vtrace_rho_clip: float = 1.0
    vtrace_c_clip: float = 1.0
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    grad_clip: float = 40.0
    hidden: tuple = (64, 64)
    # how many fragments to consume per training_step call
    updates_per_iteration: int = 4
    # "podracer" routes the loop onto the podracer throughput plane
    # (free-running fleet + central learner actor + collective weight
    # fan-out); None keeps the legacy in-driver loop bit-for-bit
    throughput_mode: Optional[str] = None
    podracer_batch_fragments: int = 2
    podracer_max_policy_lag: int = 4
    podracer_weight_sync_period: int = 1
    # None = exact fp32 fan-out; "int8" = block-quantized (~1/4 wire)
    podracer_weight_wire_dtype: Optional[str] = None


def vtrace(behavior_logp, target_logp, rewards, values, dones, last_values,
           gamma: float, rho_clip: float, c_clip: float):
    """V-trace targets + pg advantages over a (T, B) fragment (jax).

    Returns (vs (T, B), pg_adv (T, B)) — both stop-gradient-safe (pure
    functions of inputs; callers stop-grad as needed)."""
    import jax.numpy as jnp
    from jax import lax

    rho = jnp.minimum(jnp.exp(target_logp - behavior_logp), rho_clip)
    c = jnp.minimum(jnp.exp(target_logp - behavior_logp), c_clip)
    nonterminal = 1.0 - dones
    # V_{s+1}: shift values down; bootstrap last_values at the fragment end
    values_next = jnp.concatenate(
        [values[1:], last_values[None, :]], axis=0
    )
    delta = rho * (rewards + gamma * values_next * nonterminal - values)

    def backward(carry, xs):
        acc = carry  # v_{s+1} − V_{s+1}
        d, cs, nt = xs
        acc = d + gamma * cs * nt * acc
        return acc, acc

    _, vs_minus_v = lax.scan(
        backward,
        jnp.zeros_like(last_values),
        (delta, c, nonterminal),
        reverse=True,
    )
    vs = values + vs_minus_v
    vs_next = jnp.concatenate([vs[1:], last_values[None, :]], axis=0)
    pg_adv = rho * (rewards + gamma * vs_next * nonterminal - values)
    return vs, pg_adv


class IMPALALearner(Learner):
    def __init__(self, config: IMPALAConfig, module_config):
        import jax
        import optax

        self.config = config
        self.module_config = module_config
        self._fwd = core.get_forward(module_config)
        self.params = core.module_init(
            jax.random.key(config.seed), module_config
        )
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip),
            optax.adam(config.lr),
        )
        self.opt_state = self.optimizer.init(self.params)
        self._init_jit()

    def _loss(self, params, batch):
        """batch: obs (T,B,F), actions (T,B), logp (T,B, behavior),
        rewards, dones (T,B), last_obs (B,F)."""
        import jax
        import jax.numpy as jnp

        c = self.config
        T, B = batch["actions"].shape
        obs_flat = batch["obs"].reshape(T * B, -1)
        logits, values = self._fwd(params, obs_flat)
        logits = logits.reshape(T, B, -1)
        values = values.reshape(T, B)
        _, last_values = self._fwd(params, batch["last_obs"])
        logp_all = jax.nn.log_softmax(logits)
        tgt_logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1
        )[..., 0]
        vs, pg_adv = vtrace(
            batch["logp"], jax.lax.stop_gradient(tgt_logp),
            batch["rewards"], jax.lax.stop_gradient(values),
            batch["dones"], jax.lax.stop_gradient(last_values),
            c.gamma, c.vtrace_rho_clip, c.vtrace_c_clip,
        )
        pg = -(tgt_logp * jax.lax.stop_gradient(pg_adv)).mean()
        vf = 0.5 * ((values - jax.lax.stop_gradient(vs)) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = pg + c.vf_coeff * vf - c.entropy_coeff * entropy
        return total, {
            "policy_loss": pg,
            "vf_loss": vf,
            "entropy": entropy,
            "mean_rho": jnp.exp(
                jax.lax.stop_gradient(tgt_logp) - batch["logp"]
            ).mean(),
        }


def impala_batch_from_fragments(frags) -> Dict[str, np.ndarray]:
    """Stack rollout fragments along the env (B) axis into one V-trace
    batch — the podracer learner's batch assembler.  Fragments share T
    (one ``rollout_fragment_length``); B may differ per runner."""
    obs = np.concatenate([f["obs"] for f in frags], axis=1)
    last_obs = np.concatenate(
        [f["final_obs"].reshape(f["obs"].shape[1], -1) for f in frags],
        axis=0,
    )
    return {
        "obs": obs.astype(np.float32),
        "actions": np.concatenate([f["actions"] for f in frags], axis=1),
        "logp": np.concatenate([f["logp"] for f in frags], axis=1),
        "rewards": np.concatenate([f["rewards"] for f in frags], axis=1),
        "dones": np.concatenate([f["dones"] for f in frags], axis=1),
        "last_obs": last_obs,
    }


class IMPALA(Algorithm):
    """Async decoupled actor-learner (ray: impala.py training_step's
    aggregated async sampling, minus the GPU aggregation actors the
    single-learner case doesn't need).

    ``throughput_mode="podracer"`` swaps the in-driver update loop for
    the podracer plane: the learner moves into a dedicated actor fed by
    a free-running fleet over shm fragment refs, with staleness-bounded
    batching (V-trace is exactly the correction that makes the extra
    policy lag sound) and collective weight fan-out."""

    learner_cls = IMPALALearner  # overridden by APPO

    def _setup(self, config: IMPALAConfig):
        import ray_tpu

        self._podracer = None
        if getattr(config, "throughput_mode", None) == "podracer":
            self._setup_podracer(config)
            return
        spaces = probe_env_spaces(config.env, config.env_to_module)
        self.module_config = build_module_config(config, spaces)
        self.learner = self.learner_cls(config, self.module_config)
        self.env_runner_group = EnvRunnerGroup(
            config.env,
            self.module_config,
            num_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_runner,
            seed=config.seed,
            env_to_module_fn=config.env_to_module,
        )
        self.env_runner_group.sync_weights(self.learner.params)
        # one standing sample per runner — the async pipeline
        self._inflight = {
            r.sample.remote(config.rollout_fragment_length): r
            for r in self.env_runner_group.runners
        }
        self._ray = ray_tpu

    def _setup_podracer(self, config: IMPALAConfig):
        import functools

        import ray_tpu
        from ray_tpu.rllib.podracer import PodracerConfig, PodracerRunner

        spaces = probe_env_spaces(config.env, config.env_to_module)
        self.module_config = build_module_config(config, spaces)
        self.env_runner_group = EnvRunnerGroup(
            config.env,
            self.module_config,
            num_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_runner,
            seed=config.seed,
            env_to_module_fn=config.env_to_module,
        )
        # the learner lives in the podracer actor, not this process
        self.learner = None
        self._podracer = PodracerRunner(
            self.env_runner_group,
            functools.partial(self.learner_cls, config, self.module_config),
            impala_batch_from_fragments,
            PodracerConfig(
                rollout_fragment_length=config.rollout_fragment_length,
                batch_fragments=config.podracer_batch_fragments,
                max_policy_lag=config.podracer_max_policy_lag,
                weight_sync_period=config.podracer_weight_sync_period,
                weight_wire_dtype=config.podracer_weight_wire_dtype,
            ),
        )
        self._inflight = {}
        self._ray = ray_tpu

    def _eval_weights(self):
        if self._podracer is not None:
            return self._podracer.get_weights()
        return super()._eval_weights()

    def _podracer_training_step(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.monotonic()
        out = self._podracer.run(min_updates=c.updates_per_iteration)
        self._record_returns(np.asarray(out.pop("episode_returns")))
        self._total_steps += int(out["env_steps_sampled"])
        out["iter_time_s"] = time.monotonic() - t0
        return out

    def training_step(self) -> Dict[str, Any]:
        if self._podracer is not None:
            return self._podracer_training_step()
        c = self.config
        stats_acc: Dict[str, float] = {}
        t0 = time.monotonic()
        consumed = 0
        while consumed < c.updates_per_iteration:
            ready, _ = self._ray.wait(
                list(self._inflight), num_returns=1, timeout=300.0
            )
            if not ready:
                raise TimeoutError("no IMPALA fragment arrived in 300s")
            ref = ready[0]
            runner = self._inflight.pop(ref)
            frag = self._ray.get(ref)
            self._record_returns(frag["episode_returns"])
            T, B = frag["actions"].shape
            batch = {
                "obs": frag["obs"].astype(np.float32),
                "actions": frag["actions"],
                "logp": frag["logp"],
                "rewards": frag["rewards"],
                "dones": frag["dones"],
                "last_obs": frag["final_obs"].reshape(B, -1),
            }
            stats = self.learner.update(batch)
            for k, v in stats.items():
                stats_acc[k] = float(v)
            consumed += 1
            self._total_steps += T * B
            # fresh weights to THIS runner only; relaunch immediately —
            # other runners keep sampling under their slightly-stale
            # policies (that lag is exactly what V-trace corrects)
            # dropped ref is safe: per-caller actor-call ordering runs
            # set_weights BEFORE the sample.remote below on the same
            # runner, and a set_weights failure surfaces through that
            # tracked sample ref (rtflow RT202 audit: the sample refs
            # stored in self._inflight are all drained by the
            # wait/pop/get loop above and cleared in stop())
            # rtlint: disable-next=RT105
            runner.set_weights.remote(self._ray.put(self.learner.params))
            self._inflight[
                runner.sample.remote(c.rollout_fragment_length)
            ] = runner
        stats_acc["fragments_consumed"] = consumed
        stats_acc["iter_time_s"] = time.monotonic() - t0
        return stats_acc

    def get_state(self) -> Dict[str, Any]:
        if self._podracer is not None:
            return {"params": self._podracer.get_weights()}
        return {"params": self.learner.params}

    def set_state(self, state: Dict[str, Any]) -> None:
        if self._podracer is not None:
            self._ray.get(
                self._podracer.learner.set_weights.remote(state["params"]),
                timeout=120.0,
            )
            self._podracer._put_sync_all()
            return
        self.learner.params = state["params"]
        self.env_runner_group.sync_weights(self.learner.params)

    def stop(self) -> None:
        self._inflight.clear()
        if self._podracer is not None:
            self._podracer.stop()
            self._podracer = None
        self.env_runner_group.stop()


IMPALAConfig.algo_class = IMPALA
