"""Offline RL: episode recording, dataset reading, and behavior cloning.

Role-equivalent of ray: rllib/offline/ (JsonWriter/JsonReader,
offline_data.py OfflineData) + rllib/algorithms/bc/ (BCConfig, BC).
Episodes are JSONL — one episode per line with obs/actions/rewards
lists — readable without this framework, like the reference's JSON
sample format.  BC trains the shared MLP RLModule with cross-entropy on
expert actions (the reference's BC loss, rllib/algorithms/bc/bc_learner
minus the torch), then evaluates by rolling the learned policy in a
live EnvRunnerGroup — exercising the offline→online loop end to end.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from ray_tpu.rllib import core
from ray_tpu.rllib.algorithm import (
    Algorithm,
    AlgorithmConfig,
    build_module_config,
    probe_env_spaces,
)
from ray_tpu.rllib.env_runner import EnvRunnerGroup
from ray_tpu.rllib.learner_group import Learner

# ---------------------------------------------------------------------------
# Recording + reading
# ---------------------------------------------------------------------------


def record_episodes(
    env_fn,
    policy_fn: Callable[[np.ndarray], int],
    num_episodes: int,
    path: str,
    seed: int = 0,
    max_steps: int = 1000,
) -> Dict[str, float]:
    """Roll `policy_fn` in the env and append one JSONL line per episode
    (ray: rllib/offline/json_writer.py role).  Returns summary stats."""
    import gymnasium as gym

    env = env_fn() if callable(env_fn) else gym.make(env_fn)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    returns = []
    with open(path, "a") as f:
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=seed + ep)
            traj = {"obs": [], "actions": [], "rewards": []}
            for _ in range(max_steps):
                a = int(policy_fn(np.asarray(obs, np.float32)))
                traj["obs"].append(np.asarray(obs, np.float32).tolist())
                traj["actions"].append(a)
                obs, r, term, trunc, _ = env.step(a)
                traj["rewards"].append(float(r))
                if term or trunc:
                    break
            returns.append(sum(traj["rewards"]))
            f.write(json.dumps(traj) + "\n")
    env.close()
    return {
        "episodes": num_episodes,
        "mean_return": float(np.mean(returns)),
    }


def _iter_episodes(paths, env_to_module_fn=None):
    """Yield (obs_array, actions, rewards) per JSONL episode, replaying
    a FRESH connector pipeline per episode when given — exactly the
    transform an online EnvRunner would apply, so offline learners see
    the same input distribution the learned policy will see live.
    Shared by both readers (episode-shaped and transition-shaped)."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    for p in paths:
        with open(str(p)) as f:
            for line in f:
                if not line.strip():
                    continue
                ep = json.loads(line)
                ep_obs = np.asarray(ep["obs"], np.float32)
                if env_to_module_fn is not None:
                    pipeline = env_to_module_fn()
                    ep_obs = np.concatenate(
                        [pipeline(step[None, ...]) for step in ep_obs]
                    )
                yield ep_obs, ep["actions"], ep.get("rewards", [])


class JsonEpisodeReader:
    """Read JSONL episode files into flat (obs, action) arrays
    (ray: rllib/offline/json_reader.py JsonReader)."""

    def __init__(self, paths: Sequence[str], env_to_module_fn=None):
        if isinstance(paths, (str, os.PathLike)):
            paths = [paths]
        self.paths = [str(p) for p in paths]
        obs, acts = [], []
        self.num_episodes = 0
        self.mean_return = 0.0
        total_ret = 0.0
        for ep_obs, actions, rewards in _iter_episodes(
            self.paths, env_to_module_fn
        ):
            obs.append(ep_obs)
            acts.extend(actions)
            total_ret += sum(rewards)
            self.num_episodes += 1
        if not obs:
            raise ValueError(f"no episodes found in {self.paths}")
        self.obs = np.concatenate(obs).astype(np.float32)
        self.actions = np.asarray(acts, np.int32)
        self.mean_return = total_ret / max(self.num_episodes, 1)

    def __len__(self) -> int:
        return len(self.actions)

    def iter_batches(self, batch_size: int, rng: np.random.Generator,
                     ) -> Iterator[Dict[str, np.ndarray]]:
        idx = rng.permutation(len(self.actions))
        for i in range(0, len(idx) - batch_size + 1, batch_size):
            sel = idx[i:i + batch_size]
            yield {"obs": self.obs[sel], "actions": self.actions[sel]}


class TransitionReader:
    """Read JSONL episodes into flat (s, a, r, s', done, return-to-go)
    transition arrays — the sample shape value-based offline learners
    (CQL) and advantage-weighted ones (MARWIL) train on (ray:
    rllib/offline/json_reader.py transition batches role).

    Episodes may record one trailing terminal obs (len(obs) ==
    len(actions)+1); it becomes the last step's ``next_obs``.  Without
    it, the last ``next_obs`` repeats its own obs with done=1 — the done
    mask kills the bootstrap, so the value never matters.  Zero-step
    episodes are skipped.  ``returns`` are discounted returns-to-go.
    """

    def __init__(self, paths: Sequence[str], gamma: float = 0.99,
                 env_to_module_fn=None):
        obs_l, act_l, rew_l, nxt_l, done_l, ret_l = [], [], [], [], [], []
        self.num_episodes = 0
        for o, actions, rewards in _iter_episodes(paths, env_to_module_fn):
            r = np.asarray(rewards, np.float32)
            T = len(r)
            if len(actions) != T:
                raise ValueError(
                    f"episode shape mismatch: {len(actions)} actions, "
                    f"{T} rewards (expected equal)"
                )
            if T == 0:
                continue  # zero-step episode: no transitions to learn from
            if len(o) == len(actions) + 1:
                # terminal-obs format: the trailing obs is the real s_T —
                # use it for next_obs instead of repeating s_{T-1}
                nxt = o[1:]
                o = o[: len(actions)]
            elif len(o) == len(actions):
                nxt = np.concatenate([o[1:], o[-1:]])
            else:
                raise ValueError(
                    f"episode shape mismatch: {len(o)} obs, "
                    f"{len(actions)} actions (expected equal, or one "
                    "trailing terminal obs)"
                )
            ret = np.zeros(T, np.float32)
            acc = 0.0
            for t in range(T - 1, -1, -1):
                acc = r[t] + gamma * acc
                ret[t] = acc
            done = np.zeros(T, np.float32)
            done[-1] = 1.0
            obs_l.append(o)
            nxt_l.append(nxt)
            act_l.extend(actions)
            rew_l.append(r)
            done_l.append(done)
            ret_l.append(ret)
            self.num_episodes += 1
        if not obs_l:
            raise ValueError(f"no episodes found in {paths!r}")
        self.obs = np.concatenate(obs_l)
        self.actions = np.asarray(act_l, np.int32)
        self.rewards = np.concatenate(rew_l)
        self.next_obs = np.concatenate(nxt_l)
        self.dones = np.concatenate(done_l)
        self.returns = np.concatenate(ret_l)

    def __len__(self) -> int:
        return len(self.actions)

    def sample(self, batch_size: int, rng: np.random.Generator,
               ) -> Dict[str, np.ndarray]:
        sel = rng.integers(0, len(self.actions), size=batch_size)
        return {
            "obs": self.obs[sel],
            "actions": self.actions[sel],
            "rewards": self.rewards[sel],
            "next_obs": self.next_obs[sel],
            "dones": self.dones[sel],
            "returns": self.returns[sel],
        }


# ---------------------------------------------------------------------------
# Behavior cloning
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class BCConfig(AlgorithmConfig):
    lr: float = 1e-3
    train_batch_size: int = 256
    updates_per_iteration: int = 50
    hidden: tuple = (64, 64)
    input_paths: Optional[Sequence[str]] = None
    # rollout evaluation of the cloned policy each iteration
    evaluation_num_steps: int = 200

    def offline_data(self, input_paths) -> "BCConfig":
        return dataclasses.replace(self, input_paths=input_paths)


class BCLearner(Learner):
    def __init__(self, config: BCConfig, module_config):
        import jax
        import optax

        self.config = config
        self.module_config = module_config
        self._fwd = core.get_forward(module_config)
        self.params = core.module_init(jax.random.key(config.seed), module_config)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._init_jit()

    def _loss(self, params, batch):
        import jax
        import jax.numpy as jnp

        logits, _ = self._fwd(params, batch["obs"])
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(
            logp, batch["actions"][:, None].astype(jnp.int32), axis=1
        )[:, 0]
        return nll.mean(), {"bc_loss": nll.mean()}


class BC(Algorithm):
    def _setup(self, config: BCConfig):
        assert config.input_paths, "BCConfig.offline_data(paths) is required"
        spaces = probe_env_spaces(config.env, config.env_to_module)
        self.module_config = build_module_config(config, spaces)
        self.reader = JsonEpisodeReader(
            config.input_paths, env_to_module_fn=config.env_to_module
        )
        if len(self.reader) < config.train_batch_size:
            raise ValueError(
                f"offline dataset has {len(self.reader)} samples, fewer "
                f"than train_batch_size={config.train_batch_size}; record "
                "more episodes or lower train_batch_size"
            )
        self.learner = BCLearner(config, self.module_config)
        self.env_runner_group = EnvRunnerGroup(
            config.env,
            self.module_config,
            num_runners=max(1, config.num_env_runners),
            num_envs_per_runner=config.num_envs_per_runner,
            seed=config.seed,
            env_to_module_fn=config.env_to_module,
        )
        self._np_rng = np.random.default_rng(config.seed)

    def training_step(self) -> Dict[str, Any]:
        c = self.config
        t0 = time.monotonic()
        losses: List[float] = []
        batches = self.reader.iter_batches(c.train_batch_size, self._np_rng)
        for _ in range(c.updates_per_iteration):
            try:
                batch = next(batches)
            except StopIteration:
                batches = self.reader.iter_batches(
                    c.train_batch_size, self._np_rng
                )
                batch = next(batches)
            stats = self.learner.update(batch)
            losses.append(float(stats["bc_loss"]))
        learn_time = time.monotonic() - t0
        # evaluation rollout with the cloned weights
        self.env_runner_group.sync_weights(self.learner.params)
        frags = self.env_runner_group.sample(c.evaluation_num_steps)
        ep_returns = np.concatenate(
            [f["episode_returns"] for f in frags]
        ) if frags else np.zeros(0)
        self._record_returns(ep_returns)
        return {
            "bc_loss": float(np.mean(losses)),
            "num_offline_samples": len(self.reader),
            "dataset_mean_return": self.reader.mean_return,
            "learn_time_s": learn_time,
            "episodes_this_iter": len(ep_returns),
        }

    def get_state(self) -> Dict[str, Any]:
        return {"params": self.learner.params}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.learner.params = state["params"]
        self.env_runner_group.sync_weights(self.learner.params)

    def stop(self) -> None:
        self.env_runner_group.stop()


BCConfig.algo_class = BC
