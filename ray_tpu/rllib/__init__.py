"""ray_tpu.rllib: reinforcement learning on actor rollouts + jax learners.

Role-equivalent of ray: rllib/ — EnvRunner actors sample vectorized gym
envs; the learner's whole PPO update is one jit'd jax function.
"""

from ray_tpu.rllib.core import MLPModuleConfig  # noqa: F401
from ray_tpu.rllib.env_runner import EnvRunnerGroup  # noqa: F401
from ray_tpu.rllib.ppo import PPO, PPOConfig, PPOLearner, compute_gae  # noqa: F401
