"""ray_tpu.rllib: reinforcement learning on actor rollouts + jax learners.

Role-equivalent of ray: rllib/ — EnvRunner actors sample vectorized gym
envs; learners are jit'd jax functions, either in-process (whole update
one jit) or as a data-parallel LearnerGroup of actors.  Algorithms (PPO,
DQN) share the Algorithm/AlgorithmConfig skeleton.
"""

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig  # noqa: F401
from ray_tpu.rllib.core import MLPModuleConfig  # noqa: F401
from ray_tpu.rllib.dqn import DQN, DQNConfig, DQNLearner, ReplayBuffer  # noqa: F401
from ray_tpu.rllib.env_runner import EnvRunnerGroup  # noqa: F401
from ray_tpu.rllib.learner_group import Learner, LearnerGroup  # noqa: F401
from ray_tpu.rllib.impala import (  # noqa: F401
    IMPALA,
    IMPALAConfig,
    IMPALALearner,
    vtrace,
)
from ray_tpu.rllib.appo import APPO, APPOConfig  # noqa: F401
from ray_tpu.rllib.multi_agent import (  # noqa: F401
    MultiAgentEnv,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rllib.sac import SAC, SACConfig  # noqa: F401
from ray_tpu.rllib.offline import (  # noqa: F401
    BC,
    BCConfig,
    JsonEpisodeReader,
    TransitionReader,
    record_episodes,
)
from ray_tpu.rllib.cql import CQL, CQLConfig  # noqa: F401
from ray_tpu.rllib.marwil import MARWIL, MARWILConfig  # noqa: F401
from ray_tpu.rllib.ppo import PPO, PPOConfig, PPOLearner, compute_gae  # noqa: F401
from ray_tpu.rllib import connectors  # noqa: F401
from ray_tpu.rllib import podracer  # noqa: F401

# NOTE: the model catalog (CNN family) lives in ray_tpu.models.catalog —
# imported there, not here, to keep rllib importable from the catalog
# module itself (registration into core.MODULE_FAMILIES happens on
# catalog import, including implicitly when a CNNModuleConfig unpickles
# inside a worker).
