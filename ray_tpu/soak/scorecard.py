"""The availability scorecard: goodput, shed, p99-vs-SLO, and
per-incident blackout attribution.

``compute_scorecard`` is a PURE function of its inputs — the request
latency stream, the unified storm log, and the health-plane samples —
so the scorecard of a deterministic (sim-harness) run is byte-stable:
``Scorecard.to_json()`` canonicalizes (sorted keys, floats rounded) and
two runs from the same scenario seed produce identical bytes.  For a
live run the same code path renders measured numbers; what stays
reproducible there is the storm timeline and the attribution
STRUCTURE.

Blackout attribution (the method, also in docs/architecture.md):

1. Bin the request stream into ``bucket_s`` windows; per bucket count
   in-SLO completions, sheds, errors.
2. A bucket is a DIP when it contains errors, or when its in-SLO
   completion count falls below half the run's median bucket (the
   robust baseline — the storm occupies a minority of buckets by
   construction, so the median is a clean-weather number).
3. Window-join each dip bucket against the storm log's process-level
   events (preemption notices, partitions, node kills): an event
   explains a dip if the dip starts inside
   [event_ts, event_ts + attribution_window_s (+ partition duration)].
   The LATEST explaining event wins — blame the nearest cause.
4. Dip buckets attributed to the same event group into one
   ``Incident`` carrying blackout seconds, lost in-SLO completions vs
   the median baseline, shed/error counts, the health plane's evidence
   over the window (max phi, suspect nodes, incarnation bumps), and
   the site-fault firings that landed inside it.
5. Dips no event explains land in ``unattributed_dips`` — the
   acceptance gate asserts this list is EMPTY: every availability dip
   must trace to a storm event, or the soak found a bug.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ray_tpu.soak.load import RequestRecord
from ray_tpu.soak.scenario import SoakScenario

__all__ = ["Incident", "Scorecard", "compute_scorecard"]

#: storm-log (source, event) pairs that can own an incident
_INCIDENT_EVENTS = {
    ("chaos", "node_preempt"),
    ("chaos", "node_kill"),
    ("chaos", "partition"),
    ("chaos", "cut"),
    ("chaos", "spot_preempt"),
    ("chaos", "gcs_kill"),
    ("link", "cut"),
}


@dataclass
class Incident:
    """One storm event and the availability damage attributed to it."""

    event: str
    event_ts: float
    detail: dict
    start_s: float
    end_s: float
    blackout_s: float
    ok_lost: float
    shed: int
    errors: int
    max_phi: Optional[float] = None
    suspect_nodes: List[str] = field(default_factory=list)
    incarnation_bumps: int = 0
    fault_firings: List[dict] = field(default_factory=list)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclass
class Scorecard:
    scenario: str
    seed: int
    duration_s: float
    offered: int
    completed_ok: int
    in_slo: int
    goodput_rps: float
    #: in-SLO completions / offered — what SLOSpec.goodput_floor gates
    goodput_frac: float
    shed: int
    shed_rate: float
    errors: int
    error_rate: float
    p50_ms: float
    p99_ms: float
    slo_p99_ms: float
    #: fraction of buckets that were NOT dips
    availability: float
    slo_pass: bool
    slo_failures: List[str]
    incidents: List[Incident]
    unattributed_dips: List[dict]

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return _round_floats(d)

    def to_json(self) -> str:
        """Canonical rendering — the bit-reproducibility surface."""
        return json.dumps(self.to_dict(), sort_keys=True)

    def to_rows(self) -> List[dict]:
        """bench.py ``soak_availability`` row family."""
        rows = [{
            "metric": "soak_availability",
            "value": round(self.availability, 4),
            "unit": "frac",
            "goodput_rps": round(self.goodput_rps, 2),
            "goodput_frac": round(self.goodput_frac, 4),
            "shed_rate": round(self.shed_rate, 4),
            "error_rate": round(self.error_rate, 4),
            "p99_ms": round(self.p99_ms, 1),
            "slo_p99_ms": self.slo_p99_ms,
            "slo_pass": self.slo_pass,
            "incidents": len(self.incidents),
            "unattributed_dips": len(self.unattributed_dips),
            "scenario": self.scenario,
            "seed": self.seed,
        }]
        for inc in self.incidents:
            rows.append({
                "metric": "soak_incident",
                "value": round(inc.blackout_s, 2),
                "unit": "s blackout",
                "event": inc.event,
                "at_s": round(inc.event_ts, 2),
                "ok_lost": round(inc.ok_lost, 1),
                "shed": inc.shed,
                "errors": inc.errors,
                "max_phi": (
                    round(inc.max_phi, 2)
                    if inc.max_phi is not None else None
                ),
                "suspects": len(inc.suspect_nodes),
            })
        return rows


def _round_floats(x, ndigits: int = 6):
    if isinstance(x, float):
        return round(x, ndigits)
    if isinstance(x, dict):
        return {k: _round_floats(v, ndigits) for k, v in x.items()}
    if isinstance(x, list):
        return [_round_floats(v, ndigits) for v in x]
    return x


def _pct(sorted_vals: List[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(p / 100.0 * len(sorted_vals)))]


def compute_scorecard(
    scenario: SoakScenario,
    records: Sequence[RequestRecord],
    storm_log: Sequence[dict] = (),
    health_samples: Sequence[dict] = (),
    t0: float = 0.0,
) -> Scorecard:
    """Render the scorecard.  ``records`` carry offsets from the load
    window start; ``storm_log``/``health_samples`` timestamps are
    normalized by subtracting ``t0`` (pass the monotonic load-start of
    a live run; sim harnesses emit offsets directly and pass 0).

    ``health_samples`` rows: ``{"t_s", "node", "phi", "suspect",
    "incarnation", "alive"}`` — the ``rpc_node_health`` reply flattened
    per node per poll."""
    slo_ms = scenario.workload.slo_ms
    bucket_s = scenario.bucket_s
    n_buckets = max(1, int(round(scenario.duration_s / bucket_s)))

    ok_lat = sorted(r.latency_ms for r in records if r.status == "ok")
    offered = len(records)
    completed_ok = len(ok_lat)
    in_slo_total = sum(1 for v in ok_lat if v <= slo_ms)
    shed = sum(1 for r in records if r.status == "shed")
    errors = sum(1 for r in records if r.status == "error")

    # -- bucketize ------------------------------------------------------
    b_in_slo = [0] * n_buckets
    b_shed = [0] * n_buckets
    b_err = [0] * n_buckets
    b_total = [0] * n_buckets
    for r in records:
        i = min(n_buckets - 1, max(0, int(r.t_s / bucket_s)))
        b_total[i] += 1
        if r.status == "ok" and r.latency_ms <= slo_ms:
            b_in_slo[i] += 1
        elif r.status == "shed":
            b_shed[i] += 1
        elif r.status == "error":
            b_err[i] += 1
    median_ok = sorted(b_in_slo)[n_buckets // 2]

    def is_dip(i: int) -> bool:
        if b_err[i] > 0:
            return True
        # dip = the bucket SERVED under half of what arrived in it —
        # judged against the bucket's own offered count, not the run
        # median, so an open-loop Poisson lull (few arrivals, all
        # served) never reads as a blackout.  Requests are bucketed by
        # ARRIVAL time, so a stall shows up here as arrivals whose
        # latency blew the SLO.  Low-count guard: < 4 arrivals carries
        # no signal either way.
        return b_total[i] >= 4 and b_in_slo[i] < 0.5 * b_total[i]

    dips = [i for i in range(n_buckets) if is_dip(i)]

    # -- storm events that can own an incident --------------------------
    events = []
    for e in storm_log:
        if (e.get("source"), e.get("event")) in _INCIDENT_EVENTS:
            ev = dict(e)
            ev["t_s"] = float(e.get("ts", 0.0)) - t0
            events.append(ev)
    # "latest explaining event wins" below — at equal timestamps the
    # process-level chaos event must outrank its own low-level link
    # rows, so sort link entries first
    events.sort(key=lambda e: (e["t_s"], 0 if e["source"] == "link" else 1))

    def explains(ev: dict, dip_start: float) -> bool:
        window = scenario.attribution_window_s
        window += float(ev.get("detail", {}).get("duration_s") or 0.0)
        # the bucket containing the event counts too, hence - bucket_s
        return ev["t_s"] - bucket_s <= dip_start <= ev["t_s"] + window

    # -- attribute dips -------------------------------------------------
    by_event: Dict[int, List[int]] = {}
    unattributed: List[dict] = []
    for i in dips:
        dip_start = i * bucket_s
        owner = None
        for k, ev in enumerate(events):
            if explains(ev, dip_start):
                owner = k  # latest explaining event wins (sorted asc)
        if owner is None:
            unattributed.append({
                "bucket_s": dip_start,
                "in_slo": b_in_slo[i],
                "shed": b_shed[i],
                "errors": b_err[i],
            })
        else:
            by_event.setdefault(owner, []).append(i)

    incidents: List[Incident] = []
    for k in sorted(by_event):
        ev, idxs = events[k], by_event[k]
        start = min(idxs) * bucket_s
        end = (max(idxs) + 1) * bucket_s
        h = [s for s in health_samples
             if start <= float(s.get("t_s", 0.0)) - t0 <= end]
        phis = [s["phi"] for s in h if s.get("phi") is not None]
        suspects = sorted({s["node"] for s in h if s.get("suspect")})
        bumps = 0
        first_inc: Dict[str, int] = {}
        for s in h:
            node, inc = s.get("node"), s.get("incarnation")
            if node is None or inc is None:
                continue
            if node in first_inc and inc > first_inc[node]:
                bumps += 1
            first_inc.setdefault(node, inc)
        firings = [
            {"site": e.get("detail", {}).get("site"),
             "t_s": round(float(e.get("ts", 0.0)) - t0, 3)}
            for e in storm_log
            if e.get("source") == "fault"
            and start <= float(e.get("ts", 0.0)) - t0 <= end
        ]
        incidents.append(Incident(
            event=ev["event"],
            event_ts=round(ev["t_s"], 3),
            detail=dict(ev.get("detail", {})),
            start_s=start,
            end_s=end,
            blackout_s=len(idxs) * bucket_s,
            ok_lost=sum(max(0.0, median_ok - b_in_slo[i])
                        for i in idxs),
            shed=sum(b_shed[i] for i in idxs),
            errors=sum(b_err[i] for i in idxs),
            max_phi=max(phis) if phis else None,
            suspect_nodes=suspects,
            incarnation_bumps=bumps,
            fault_firings=firings,
        ))

    # -- SLO verdict ----------------------------------------------------
    goodput_frac = in_slo_total / offered if offered else 0.0
    shed_rate = shed / offered if offered else 0.0
    error_rate = errors / offered if offered else 0.0
    p99 = _pct(ok_lat, 99)
    failures = []
    if goodput_frac < scenario.slo.goodput_floor:
        failures.append(
            f"goodput {goodput_frac:.3f} < floor "
            f"{scenario.slo.goodput_floor}"
        )
    if shed_rate > scenario.slo.shed_ceiling:
        failures.append(
            f"shed {shed_rate:.3f} > ceiling {scenario.slo.shed_ceiling}"
        )
    if error_rate > scenario.slo.max_error_rate:
        failures.append(
            f"errors {error_rate:.3f} > max {scenario.slo.max_error_rate}"
        )
    if p99 > scenario.slo.p99_ms:
        failures.append(f"p99 {p99:.1f}ms > {scenario.slo.p99_ms}ms")

    return Scorecard(
        scenario=scenario.name,
        seed=scenario.seed,
        duration_s=scenario.duration_s,
        offered=offered,
        completed_ok=completed_ok,
        in_slo=in_slo_total,
        goodput_rps=in_slo_total / scenario.duration_s,
        goodput_frac=goodput_frac,
        shed=shed,
        shed_rate=shed_rate,
        errors=errors,
        error_rate=error_rate,
        p50_ms=_pct(ok_lat, 50),
        p99_ms=p99,
        slo_p99_ms=scenario.slo.p99_ms,
        availability=(n_buckets - len(dips)) / n_buckets,
        slo_pass=not failures,
        slo_failures=failures,
        incidents=incidents,
        unattributed_dips=unattributed,
    )
