"""The declarative soak scenario: workload + SLOs + seeded storm.

A ``SoakScenario`` is the whole experiment in one JSON-serializable
value: the serve workload to sustain (service time, offered rate,
queueing/autoscaling policy), the SLOs the scorecard enforces, the
storm to deliver while the workload runs (counts and shapes of
preemptions / partitions / node kills, expanded into a concrete
timeline by ``storm.build_storm`` as a pure function of the seed), and
the nth-hit fault plans armed at t=0 (``RT_FAULTS`` inheritance pushes
them into every cluster subprocess).

Everything nondeterministic derives from ``seed`` — arrivals, storm
timing, victim choice, fault-plan firing.  Same scenario JSON ⇒ same
storm timeline ⇒ (in sim mode) the same scorecard byte-for-byte.
``from_dict`` is strict like ``FaultPlan.from_dict``: a typo'd field
silently disarming half the storm makes the soak lie, so it raises.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import List, Tuple

from ray_tpu.common.faults import FaultPlan

__all__ = [
    "SLOSpec",
    "SoakScenario",
    "StormEvent",
    "StormSpec",
    "WorkloadSpec",
    "acceptance_scenario",
]


def _strict_fields(cls, d: dict) -> dict:
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(d) - names
    if unknown:
        raise ValueError(
            f"{cls.__name__} has no field(s) {sorted(unknown)}; "
            f"valid fields: {sorted(names)}"
        )
    return {k: d[k] for k in names if k in d}


@dataclass(frozen=True)
class WorkloadSpec:
    """The sustained serve workload (the PR 6 serve_rps shape scaled
    up): a fixed-service-time deployment under SLO-aware traffic
    management with queue-driven replica autoscaling live."""

    service_ms: float = 100.0
    max_ongoing: int = 4
    #: open-loop offered rate; capacity per replica is
    #: max_ongoing * 1000 / service_ms
    offered_rps: float = 30.0
    arrival_process: str = "poisson"
    slo_ms: float = 750.0
    max_queue_depth: int = 32
    min_replicas: int = 1
    max_replicas: int = 4
    target_queue_depth_per_replica: float = 4.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "WorkloadSpec":
        return cls(**_strict_fields(cls, d))


@dataclass(frozen=True)
class SLOSpec:
    """What the scorecard enforces.  ``goodput_floor`` is the fraction
    of OFFERED requests that must complete inside the per-request
    ``WorkloadSpec.slo_ms`` budget over the whole run — the one number
    that speaks to availability under storm."""

    p99_ms: float = 750.0
    goodput_floor: float = 0.6
    shed_ceiling: float = 0.35
    max_error_rate: float = 0.05

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "SLOSpec":
        return cls(**_strict_fields(cls, d))


@dataclass(frozen=True)
class StormSpec:
    """Storm composition knobs; ``storm.build_storm`` expands them into
    a concrete ``StormEvent`` timeline from the scenario seed.  Events
    land inside [start_frac, end_frac] of the run so the scorecard sees
    a clean head and tail to baseline against."""

    preempts: int = 1
    preempt_deadline_s: float = 4.0
    partitions: int = 1
    partition_duration_s: float = 2.0
    node_kills: int = 0
    start_frac: float = 0.2
    end_frac: float = 0.8
    #: minimum spacing between consecutive storm events — overlapping
    #: recoveries are a (harder) scenario of their own; 0 allows pileup
    min_gap_s: float = 2.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "StormSpec":
        return cls(**_strict_fields(cls, d))


@dataclass(frozen=True)
class StormEvent:
    """One concrete timeline entry: at ``t_s`` (offset from load
    start), apply ``kind`` with ``args``.

    Kinds: ``preempt`` (spot notice → drain → kill; args victim,
    deadline_s), ``partition`` (directional-pair cut victim<->gcs;
    args victim, duration_s — heal is the auto-heal deadline),
    ``kill`` (hard node kill, no notice; args victim).  ``victim`` is a
    stable worker INDEX into the scenario's initial worker list —
    resolved to a live node id by whichever harness (sim or cluster)
    executes the timeline.
    """

    t_s: float
    kind: str
    args: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"t_s": self.t_s, "kind": self.kind,
                "args": dict(self.args)}

    @classmethod
    def from_dict(cls, d: dict) -> "StormEvent":
        out = _strict_fields(cls, d)
        out["args"] = dict(out.get("args") or {})
        return cls(**out)


@dataclass(frozen=True)
class SoakScenario:
    name: str = "soak"
    seed: int = 0
    duration_s: float = 30.0
    initial_workers: int = 2
    workload: WorkloadSpec = field(default_factory=WorkloadSpec)
    slo: SLOSpec = field(default_factory=SLOSpec)
    storm: StormSpec = field(default_factory=StormSpec)
    #: nth-hit / seeded-probability site faults armed for the WHOLE run
    #: in EVERY cluster process (rpc.send.frame, raylet.lease.grant,
    #: store.put, ... — the PR 7 registry)
    fault_plans: Tuple[FaultPlan, ...] = ()
    #: scorecard binning + attribution knobs
    bucket_s: float = 1.0
    attribution_window_s: float = 6.0

    def capacity_rps(self) -> float:
        """Saturation rate of ONE replica (arithmetic, not a mood)."""
        w = self.workload
        return w.max_ongoing * 1000.0 / w.service_ms

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "duration_s": self.duration_s,
            "initial_workers": self.initial_workers,
            "workload": self.workload.to_dict(),
            "slo": self.slo.to_dict(),
            "storm": self.storm.to_dict(),
            "fault_plans": [p.to_dict() for p in self.fault_plans],
            "bucket_s": self.bucket_s,
            "attribution_window_s": self.attribution_window_s,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict) -> "SoakScenario":
        out = _strict_fields(cls, d)
        if "workload" in out:
            out["workload"] = WorkloadSpec.from_dict(out["workload"])
        if "slo" in out:
            out["slo"] = SLOSpec.from_dict(out["slo"])
        if "storm" in out:
            out["storm"] = StormSpec.from_dict(out["storm"])
        out["fault_plans"] = tuple(
            FaultPlan.from_dict(p) for p in out.get("fault_plans", ())
        )
        return cls(**out)

    @classmethod
    def from_json(cls, text: str) -> "SoakScenario":
        return cls.from_dict(json.loads(text))


def acceptance_scenario(seed: int = 7,
                        duration_s: float = 30.0) -> SoakScenario:
    """The ISSUE-18 acceptance shape: ≥3 fault planes active at once —
    a preemption notice (drain plane), a directional partition + heal
    (health plane), and nth-hit injected rpc + lease faults (chaos
    plane) — under queue-driven autoscaling, all derived from one
    seed."""
    return SoakScenario(
        name="acceptance",
        seed=seed,
        duration_s=duration_s,
        initial_workers=2,
        # min_replicas=2 spreads the serving set across both workers so
        # the storm's victims are never spectators; 50 rps against
        # 2 × 40 rps capacity keeps both replicas earning
        workload=WorkloadSpec(
            service_ms=100.0, max_ongoing=4, offered_rps=50.0,
            slo_ms=750.0, max_queue_depth=32,
            min_replicas=2, max_replicas=4,
        ),
        slo=SLOSpec(p99_ms=750.0, goodput_floor=0.6,
                    shed_ceiling=0.35, max_error_rate=0.05),
        storm=StormSpec(preempts=1, partitions=1,
                        partition_duration_s=2.0, node_kills=0),
        fault_plans=(
            FaultPlan(site="rpc.send.frame", action="drop",
                      nth=40, count=3, seed=seed),
            FaultPlan(site="raylet.lease.grant", action="kill",
                      nth=5, count=1, seed=seed + 1),
            FaultPlan(site="store.put", action="error",
                      nth=30, count=1, seed=seed + 2),
        ),
    )
