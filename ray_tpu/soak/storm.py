"""Storm timeline: seeded composition of every fault plane at once.

``build_storm`` expands a scenario's ``StormSpec`` into a concrete,
sorted ``StormEvent`` timeline as a PURE function of the scenario seed
— no wall clock, no OS entropy (RT116 polices this file).  The same
scenario therefore storms identically in the deterministic sim harness
and against a live cluster; what differs between the two is only how
an event is APPLIED.

``StormDriver`` is the live half: it walks the timeline against a
``cluster_utils.Cluster`` through the PR 7 ``ChaosController`` —
preemption notices ride the PR 9 drain protocol (notice → drain →
kill), partitions ride the PR 10 directional link-cut registry with
auto-heal, node kills are the hard path — so every applied event lands
in the controller's replayable log and the unified ``storm_log()``.
The nth-hit site faults (rpc/lease/store) are NOT timeline events:
they are armed at t=0 via ``RT_FAULTS`` inheritance and fire on their
own hit schedules; their firings surface in ``storm_log()`` through
``faults.trace()``.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional, Sequence

from ray_tpu.soak.scenario import SoakScenario, StormEvent

__all__ = ["StormDriver", "build_storm"]


def build_storm(scenario: SoakScenario) -> List[StormEvent]:
    """The concrete timeline: event times uniform inside
    [start_frac, end_frac] of the run, sorted, then pushed apart to
    ``min_gap_s`` (overlapping recoveries are a separate, harder
    scenario — the gap keeps one incident's blackout attributable to
    one event); kinds shuffled; victims drawn per event.  Everything
    from ``random.Random(f"{seed}:storm")``."""
    spec = scenario.storm
    rng = random.Random(f"{scenario.seed}:storm")
    kinds: List[str] = (
        ["preempt"] * spec.preempts
        + ["partition"] * spec.partitions
        + ["kill"] * spec.node_kills
    )
    if not kinds:
        return []
    rng.shuffle(kinds)
    lo = scenario.duration_s * spec.start_frac
    hi = scenario.duration_s * spec.end_frac
    times = sorted(rng.uniform(lo, hi) for _ in kinds)
    for i in range(1, len(times)):
        if times[i] - times[i - 1] < spec.min_gap_s:
            times[i] = times[i - 1] + spec.min_gap_s
    events: List[StormEvent] = []
    for t, kind in zip(times, kinds):
        victim = rng.randrange(max(1, scenario.initial_workers))
        if kind == "preempt":
            args = {"victim": victim,
                    "deadline_s": spec.preempt_deadline_s}
        elif kind == "partition":
            args = {"victim": victim,
                    "duration_s": spec.partition_duration_s}
        else:
            args = {"victim": victim}
        events.append(StormEvent(t_s=round(t, 3), kind=kind, args=args))
    return events


class StormDriver:
    """Executes a timeline against a live cluster in a worker thread.

    Victim indices resolve against the INITIAL worker roster (the
    non-head nodes present when the driver starts); if the indexed node
    has since died, the next live worker substitutes — a real storm
    hits whoever is there, and the substitution is recorded so the log
    still explains what ran.  ``ChaosController.preempt_node`` blocks
    through the drain, so a long drain pushes later events back — the
    recorded ``ts`` of each applied event, not the planned ``t_s``, is
    what the scorecard joins against.
    """

    def __init__(self, controller, events: Sequence[StormEvent],
                 workers: Optional[list] = None):
        self.controller = controller
        self.events = list(events)
        cluster = controller.cluster
        self.workers = list(
            workers if workers is not None
            else [n for n in cluster._nodes if n is not cluster.head_node]
        )
        self._thread: Optional[threading.Thread] = None
        self.applied: List[dict] = []

    # -- victim resolution ----------------------------------------------
    def _resolve(self, idx: int):
        live = [n for n in self.controller.cluster._nodes
                if n is not self.controller.cluster.head_node]
        if not live:
            return None, False
        if idx < len(self.workers) and self.workers[idx] in live:
            return self.workers[idx], False
        # indexed worker already dead: the storm hits whoever is there
        return live[idx % len(live)], True

    def _apply(self, ev: StormEvent) -> None:
        node, substituted = self._resolve(int(ev.args.get("victim", 0)))
        if node is None:
            self.controller.record_external(
                "storm_skip", kind=ev.kind, planned_t_s=ev.t_s,
                reason="no live workers",
            )
            return
        detail = {"planned_t_s": ev.t_s, "substituted": substituted}
        if ev.kind == "preempt":
            self.controller.preempt_node(
                node, deadline_s=float(ev.args.get("deadline_s", 4.0))
            )
        elif ev.kind == "partition":
            self.controller.partition(
                node, "gcs",
                duration_s=float(ev.args.get("duration_s", 2.0)),
            )
        elif ev.kind == "kill":
            self.controller.kill_node(node)
        else:
            self.controller.record_external(
                "storm_skip", kind=ev.kind, planned_t_s=ev.t_s,
                reason="unknown kind",
            )
            return
        self.applied.append({"kind": ev.kind, "node_id": node.node_id,
                             **detail})

    def _run(self, t0: float) -> None:
        for ev in self.events:
            delay = t0 + ev.t_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            try:
                self._apply(ev)
            except Exception as e:  # a dead victim must not end the storm
                self.controller.record_external(
                    "storm_error", kind=ev.kind, planned_t_s=ev.t_s,
                    error=repr(e),
                )

    def start(self, t0: Optional[float] = None) -> None:
        """Begin delivering events relative to ``t0`` (defaults to
        now — pass the load window's start so event offsets line up
        with request offsets)."""
        t0 = time.monotonic() if t0 is None else t0
        self._thread = threading.Thread(
            target=self._run, args=(t0,), name="soak-storm", daemon=True
        )
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
