"""Spot-fleet mode: deliberately provision preemptible capacity and
price the churn.

The spot bet: preemptible nodes cost a fraction of on-demand but the
provider revokes them with a short notice.  This module supplies both
halves of evaluating that bet:

- ``SpotFleet`` — the LIVE revocation process.  A seeded arrival
  process picks a preemptible provider node and delivers the full GCE
  preemption sequence through production machinery: GCS
  ``drain_node(reason="preemption")`` (PR 9 drain plane evacuates
  leases/actors/sole-copy objects), poll ``get_drain_status`` to
  settle, then provider ``terminate_node`` — while the autoscaler's
  min_workers floor launches the replacement (draining nodes are
  excluded from its counts, so replacement provisioning OVERLAPS the
  drain).  Every revocation lands in the unified storm log via
  ``ChaosController.record_external``.

- ``run_spot_economics`` — the DETERMINISTIC ledger.  Two ``soak.sim``
  runs from the same scenario seed: an on-demand fleet (scenario storm
  only) and a spot fleet (same storm PLUS the seeded revocation
  process, nodes replaced at provisioning latency), each accruing
  node-seconds.  The verdict is throughput-per-cost: in-SLO
  completions per node-second-dollar, spot vs on-demand, plus the
  goodput each fleet kept.  Byte-stable like every sim scorecard.
"""

from __future__ import annotations

import dataclasses
import json
import random
from dataclasses import dataclass
from typing import Dict, List, Optional

from ray_tpu.soak.scenario import SoakScenario
from ray_tpu.soak.sim import SimParams, run_sim

__all__ = [
    "SpotFleet",
    "SpotFleetConfig",
    "economics_rows",
    "economics_to_json",
    "run_spot_economics",
    "spot_preempt_times",
]

_SETTLED = ("drained", "failed", "dead", "none", "unknown")


@dataclass(frozen=True)
class SpotFleetConfig:
    """Economics + churn knobs.  Prices are relative $/node-second
    (only the RATIO matters); the default 0.35 is the classic ~65%
    spot discount."""

    spot_price: float = 0.35
    ondemand_price: float = 1.0
    #: mean revocations per minute across the fleet (seeded Poisson)
    preempts_per_min: float = 4.0
    preempt_deadline_s: float = 3.0
    #: revocations only land inside this window of the run (the head
    #: and tail stay clean so the scorecard has a baseline)
    start_frac: float = 0.15
    end_frac: float = 0.9

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def spot_preempt_times(scenario: SoakScenario,
                       cfg: SpotFleetConfig) -> List[dict]:
    """The seeded revocation schedule: Poisson arrivals inside the
    config window, victims drawn per event — all from
    ``random.Random(f"{seed}:spot")`` (RT116 discipline: replayable or
    it didn't happen)."""
    rng = random.Random(f"{scenario.seed}:spot")
    rate_s = cfg.preempts_per_min / 60.0
    lo = scenario.duration_s * cfg.start_frac
    hi = scenario.duration_s * cfg.end_frac
    out: List[dict] = []
    t = lo + rng.expovariate(rate_s) if rate_s > 0 else hi
    while t < hi:
        out.append({
            "t_s": round(t, 3),
            "victim": rng.randrange(max(1, scenario.initial_workers)),
            "deadline_s": cfg.preempt_deadline_s,
        })
        t += rng.expovariate(rate_s)
    return out


def run_spot_economics(
    scenario: SoakScenario,
    cfg: SpotFleetConfig = SpotFleetConfig(),
    params: SimParams = SimParams(),
) -> dict:
    """Same seed, two fleets, one ledger.  Returns a dict whose
    ``json.dumps(..., sort_keys=True)`` is byte-stable across runs."""
    ondemand = run_sim(scenario, params=params, replace_nodes=True)
    spot = run_sim(
        scenario, params=params, replace_nodes=True,
        preempt_extra=spot_preempt_times(scenario, cfg),
    )

    def ledger(res, price: float) -> dict:
        cost = res.node_seconds * price
        in_slo = res.scorecard.in_slo
        return {
            "in_slo": in_slo,
            "goodput_frac": round(res.scorecard.goodput_frac, 6),
            "availability": round(res.scorecard.availability, 6),
            "node_seconds": round(res.node_seconds, 3),
            "cost": round(cost, 6),
            "throughput_per_cost": round(in_slo / cost, 6) if cost else 0.0,
            "incidents": len(res.scorecard.incidents),
        }

    od = ledger(ondemand, cfg.ondemand_price)
    sp = ledger(spot, cfg.spot_price)
    advantage = (
        sp["throughput_per_cost"] / od["throughput_per_cost"]
        if od["throughput_per_cost"] else 0.0
    )
    return {
        "scenario": scenario.name,
        "seed": scenario.seed,
        "config": cfg.to_dict(),
        "ondemand": od,
        "spot": sp,
        #: >1 means the discount beat the churn
        "spot_advantage": round(advantage, 4),
        "spot_goodput_retained": round(
            sp["goodput_frac"] / od["goodput_frac"], 4
        ) if od["goodput_frac"] else 0.0,
    }


def economics_to_json(econ: dict) -> str:
    return json.dumps(econ, sort_keys=True)


def economics_rows(econ: dict) -> List[dict]:
    """bench.py ``soak_spot_economics`` row."""
    return [{
        "metric": "soak_spot_economics",
        "value": econ["spot_advantage"],
        "unit": "x throughput/cost vs on-demand",
        "spot_tpc": econ["spot"]["throughput_per_cost"],
        "ondemand_tpc": econ["ondemand"]["throughput_per_cost"],
        "spot_goodput": econ["spot"]["goodput_frac"],
        "ondemand_goodput": econ["ondemand"]["goodput_frac"],
        "goodput_retained": econ["spot_goodput_retained"],
        "preempts_per_min": econ["config"]["preempts_per_min"],
        "price_ratio": round(
            econ["config"]["spot_price"]
            / econ["config"]["ondemand_price"], 3
        ),
        "seed": econ["seed"],
    }]


class SpotFleet:
    """Live seeded revocation process against an autoscaler provider.

    The caller owns the reconcile cadence (tests step
    ``Autoscaler.reconcile()`` themselves); the fleet owns WHEN and WHO:
    ``preempt_due(now_s)`` delivers every revocation whose scheduled
    offset has passed, each one drain-protocol-first.  Victims are
    drawn seeded among nodes of PREEMPTIBLE types only — on-demand
    nodes in a mixed fleet are never revoked.
    """

    def __init__(self, gcs, provider, preemptible_types,
                 seed: int = 0, deadline_s: float = 3.0,
                 controller=None):
        self.gcs = gcs
        self.provider = provider
        self.preemptible_types = set(preemptible_types)
        self.rng = random.Random(f"{seed}:spot")
        self.deadline_s = deadline_s
        self.controller = controller
        self.preempted: List[str] = []

    def _record(self, event: str, **detail) -> None:
        if self.controller is not None:
            self.controller.record_external(event, **detail)

    def _pick(self):
        cands = sorted(
            (pn for pn in self.provider.non_terminated_nodes()
             if pn.node_type in self.preemptible_types
             and pn.provider_id not in self.preempted),
            key=lambda pn: pn.provider_id,
        )
        if not cands:
            return None
        return cands[self.rng.randrange(len(cands))]

    async def preempt_one(self) -> Optional[str]:
        """One full revocation: notice → drain → settle → terminate.
        Returns the provider id of the victim (None if the fleet has no
        revocable node right now)."""
        import asyncio
        import time

        pn = self._pick()
        if pn is None:
            self._record("spot_preempt_skip", reason="no preemptible node")
            return None
        self.preempted.append(pn.provider_id)
        nids = pn.meta.get("node_ids") or [pn.node_id_hex]
        self._record("spot_preempt", provider_id=pn.provider_id,
                     node_ids=nids, node_type=pn.node_type,
                     deadline_s=self.deadline_s)
        for nid in nids:
            try:
                await self.gcs.call(
                    "drain_node",
                    {"node_id": nid, "reason": "preemption",
                     "deadline_s": self.deadline_s},
                )
            except Exception:
                pass  # node may already be gone; the kill below settles it
        deadline = time.monotonic() + self.deadline_s + 2.0
        while time.monotonic() < deadline:
            try:
                states = [
                    (await self.gcs.call(
                        "get_drain_status", {"node_id": nid}
                    ) or {}).get("state")
                    for nid in nids
                ]
                if all(s in _SETTLED for s in states):
                    break
            except Exception:
                pass
            await asyncio.sleep(0.1)
        await asyncio.to_thread(self.provider.terminate_node, pn)
        self._record("spot_kill", provider_id=pn.provider_id,
                     node_ids=nids)
        return pn.provider_id
