"""Live soak: the scenario against a real cluster.

``run_live`` assumes an initialized runtime (``ray_tpu.init`` +
``serve.start()`` already done by the caller — same contract as the
serve tests) and drives the FULL production path: aiohttp proxy →
admission → RequestScheduler → autoscaled replicas, while the storm
thread delivers the scenario's seeded timeline through
``ChaosController`` (drain-protocol preemptions, directional
partitions with auto-heal, hard kills) and the armed ``RT_FAULTS``
plans fire on their nth hits in every process.  A health-sampler
thread polls the ``node_health`` rpc through the storm so the
scorecard's incident join has phi/suspect/incarnation evidence.

Wall-clock latencies are measured, so a live scorecard's NUMBERS are
not byte-stable — the storm timeline, the unified log schema, and the
attribution structure are what reproduce (the deterministic twin lives
in ``soak.sim``).
"""

from __future__ import annotations

import asyncio
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

from ray_tpu.common.faults import ChaosController
from ray_tpu.soak import load as soak_load
from ray_tpu.soak.scenario import SoakScenario
from ray_tpu.soak.scorecard import Scorecard, compute_scorecard
from ray_tpu.soak.storm import StormDriver, build_storm

__all__ = ["LiveSoakResult", "HealthSampler", "run_live"]


@dataclass
class LiveSoakResult:
    scorecard: Scorecard
    records: List[soak_load.RequestRecord]
    storm_log: List[dict]
    health_samples: List[dict]
    applied_events: List[dict] = field(default_factory=list)
    t0: float = 0.0


class HealthSampler:
    """Polls the GCS ``node_health`` rpc on a thread; flattens each
    reply into per-node rows the scorecard window-joins."""

    def __init__(self, interval_s: float = 0.5):
        self.interval_s = interval_s
        self.samples: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _poll_once(self) -> None:
        from ray_tpu.core.runtime import get_runtime

        try:
            rt = get_runtime()
            rows = rt._run(rt.gcs.call("node_health", {}), timeout=2.0)
        except Exception:
            return  # GCS briefly unreachable mid-storm: skip the beat
        now = time.monotonic()
        for nid, r in rows.items():
            self.samples.append({
                "t_s": now,
                "node": nid,
                "phi": r.get("phi"),
                "suspect": bool(r.get("suspect")),
                "incarnation": r.get("incarnation"),
                "alive": bool(r.get("alive")),
            })

    def _run(self) -> None:
        while not self._stop.is_set():
            self._poll_once()
            self._stop.wait(self.interval_s)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="soak-health", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def deploy_workload(scenario: SoakScenario, name: str = "soak",
                    route: str = "/soak", port: int = 18765,
                    actor_options: Optional[dict] = None) -> str:
    """Deploy the scenario's workload (fixed-service-time deployment
    under the scenario's traffic + autoscaling policy) and return the
    proxy URL.  ``actor_options`` pins replica placement (tests use a
    custom resource to put replicas on the storm's victim nodes)."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import api as serve_api

    w = scenario.workload
    service_s = w.service_ms / 1000.0

    @serve.deployment(
        ray_actor_options=actor_options or {},
        max_ongoing_requests=w.max_ongoing,
        traffic_config={
            "slo_ms": w.slo_ms,
            "max_queue_depth": w.max_queue_depth,
            "shed_retry_after_s": 0.5,
            "target_queue_depth_per_replica":
                w.target_queue_depth_per_replica,
            "stats_push_interval_s": 0.2,
            "drain_timeout_s": 10.0,
        },
        autoscaling_config={
            "min_replicas": w.min_replicas,
            "max_replicas": w.max_replicas,
            "target_ongoing_requests": float(w.max_ongoing),
            "upscale_delay_s": w.upscale_delay_s,
            "downscale_delay_s": w.downscale_delay_s,
        },
    )
    class Fixed:
        async def __call__(self):
            await asyncio.sleep(service_s)
            return "ok"

    serve.run(Fixed.bind(), name=name, route_prefix=route)
    proxy = serve_api._get_or_create_proxy(port)
    actual = ray_tpu.get(proxy.start.remote(), timeout=60)
    return f"http://127.0.0.1:{actual}{route}"


def run_live(
    scenario: SoakScenario,
    cluster,
    url: Optional[str] = None,
    port: int = 18765,
    actor_options: Optional[dict] = None,
) -> LiveSoakResult:
    """Run the scenario against ``cluster`` (a ``cluster_utils.Cluster``
    with the runtime already initialized against it).  Deploys the
    workload unless ``url`` points at one already deployed.

    NOTE on fault plans: nth-hit site faults must be armed BEFORE the
    cluster spawns (``faults.plans_to_json`` → ``RT_FAULTS`` env) for
    subprocesses to inherit them; plans installed after spawn only
    cover the driver process.  The runner does not arm them itself —
    arming is a spawn-time decision the caller owns.
    """
    if url is None:
        url = deploy_workload(scenario, name=scenario.name, port=port,
                              actor_options=actor_options)

    controller = ChaosController(cluster, seed=scenario.seed)
    driver = StormDriver(controller, build_storm(scenario))
    sampler = HealthSampler()

    offsets = soak_load.arrival_offsets(
        scenario.workload.offered_rps,
        scenario.duration_s,
        seed=f"{scenario.seed}:arrivals",
        process=scenario.workload.arrival_process,
    )

    t0_box = {"t0": 0.0}

    def _go():
        t0_box["t0"] = time.monotonic()
        driver.start(t0_box["t0"])

    sampler.start()
    try:
        records = asyncio.run(soak_load.drive_http(
            url, offsets, on_start=_go,
            request_timeout_s=max(5.0, scenario.workload.slo_ms / 250.0),
        ))
        driver.join(timeout=scenario.duration_s + 30.0)
    finally:
        sampler.stop()

    storm_log = controller.storm_log()
    card = compute_scorecard(
        scenario, records, storm_log, sampler.samples, t0=t0_box["t0"]
    )
    return LiveSoakResult(
        scorecard=card,
        records=records,
        storm_log=storm_log,
        health_samples=sampler.samples,
        applied_events=driver.applied,
        t0=t0_box["t0"],
    )
