"""Deterministic soak harness: the bit-reproducible half of the plane.

A live soak measures wall-clock latencies — real, but never
byte-stable.  This harness runs the SAME scenario through a
discrete-time model of the serve fleet instead, and it deliberately
reuses every piece of production policy code that is pure enough to
run under simulated time:

- the storm timeline comes from ``storm.build_storm`` (identical to
  the live run's),
- site faults are evaluated by a REAL ``faults.FaultController`` —
  the sim calls ``hit()`` at the same named sites (``rpc.send.frame``
  per dispatch, ``raylet.lease.grant`` per replica launch,
  ``store.put`` per result) so nth-hit windows and seeded-p draws
  exercise the actual selection code,
- arrivals come from ``load.arrival_offsets`` (the shared open-loop
  Poisson model),
- the scorecard is ``scorecard.compute_scorecard`` verbatim.

What IS modeled: replica occupancy/queueing (max_ongoing slots, fixed
service time, bounded queue with admission + deadline expiry — the PR 6
queue model), queue-driven replica autoscaling with launch latency,
and each fault plane's availability signature with constants taken
from the measured PR 9/10 benches (drain blackout ~ms, phi suspect
detection ~0.6 s, partition rpc timeouts).  The sim is single-threaded
and consumes no wall clock or OS entropy, so the whole run — request
stream, storm log, health samples, scorecard — is a pure function of
the scenario: ``run_sim(s).scorecard.to_json()`` is byte-identical
across runs and hosts.  That is the regression net: a cross-feature
policy change that shifts availability math shows up as a scorecard
diff, pinned by seed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.common.faults import FaultController
from ray_tpu.soak.load import RequestRecord, arrival_offsets
from ray_tpu.soak.scenario import SoakScenario
from ray_tpu.soak.scorecard import Scorecard, compute_scorecard
from ray_tpu.soak.storm import build_storm

__all__ = ["SimParams", "SimResult", "run_sim"]


@dataclass(frozen=True)
class SimParams:
    """Availability constants of the modeled planes — each one anchored
    to a measured number from BENCH.md rather than invented."""

    dt_s: float = 0.01
    #: phi-accrual suspect detection after a partition's silence starts
    #: (failure_detection bench: ~0.6 s at 100 ms beats)
    partition_detect_s: float = 0.6
    #: a dispatch into the undetected-partition window times out
    partition_error_s: float = 1.0
    #: router re-admits a healed node's replicas after this long
    partition_rejoin_s: float = 0.3
    #: graceful-drain migration blackout (preemption_recovery bench:
    #: ~2 ms object/actor — modeled as one dispatch tick)
    preempt_migrate_s: float = 0.3
    #: hook-less restart after a HARD kill (fault_recovery bench:
    #: ~450 ms lease+spawn)
    kill_restart_s: float = 1.0
    kill_error_s: float = 0.5
    #: replica launch latency for autoscale scale-up
    replica_launch_s: float = 0.5
    #: fresh NODE provisioning latency (spot-fleet replacement)
    node_launch_s: float = 2.0
    #: retry penalty a fired rpc drop/reset costs one request
    rpc_retry_s: float = 0.2
    store_retry_s: float = 0.1
    lease_fault_delay_s: float = 1.0
    autoscale_tick_s: float = 0.25
    health_sample_s: float = 0.5
    #: keep simulating (no new arrivals) this long past duration so
    #: in-flight work lands in the record stream
    tail_s: float = 5.0


@dataclass
class SimResult:
    scorecard: Scorecard
    records: List[RequestRecord]
    storm_log: List[dict]
    health_samples: List[dict]
    #: node-seconds by price actually accrued (spot economics input)
    node_seconds: float = 0.0
    replica_launches: int = 0
    min_up_nodes: int = 0


class _Node:
    __slots__ = ("idx", "up", "draining_since", "down_at",
                 "partition_t", "heal_t", "incarnation", "launched_at")

    def __init__(self, idx: int, t: float = 0.0):
        self.idx = idx
        self.up = True
        self.draining_since: Optional[float] = None
        self.down_at: Optional[float] = None
        self.partition_t: Optional[float] = None
        self.heal_t: Optional[float] = None
        self.incarnation = 1
        self.launched_at = t


class _Replica:
    __slots__ = ("node", "busy", "ready_at")

    def __init__(self, node: int, ready_at: float = 0.0):
        self.node = node
        self.busy = 0
        self.ready_at = ready_at


def run_sim(
    scenario: SoakScenario,
    params: SimParams = SimParams(),
    replace_nodes: bool = False,
    preempt_extra: Optional[List[dict]] = None,
) -> SimResult:
    """One deterministic soak.  ``replace_nodes`` models a provider +
    min_workers floor behind the fleet (spot mode): a downed node is
    re-provisioned after ``node_launch_s`` with a bumped incarnation.
    ``preempt_extra`` injects additional ``{"t_s", "victim",
    "deadline_s"}`` preemptions (the spot-fleet arrival process) on top
    of the scenario storm."""
    w = scenario.workload
    p = params
    ctl = FaultController(list(scenario.fault_plans))

    nodes = [_Node(i) for i in range(max(1, scenario.initial_workers))]
    replicas = [
        _Replica(i % len(nodes)) for i in range(w.min_replicas)
    ]
    storm_log: List[dict] = []
    health: List[dict] = []
    records: List[RequestRecord] = []

    def log(source: str, event: str, t: float, **detail):
        storm_log.append({"ts": t, "source": source, "event": event,
                          "detail": detail})

    def hit(site: str, ctx: str, t: float) -> Optional[str]:
        plan = ctl.hit(site, ctx)
        if plan is None:
            return None
        log("fault", plan.action, t, site=site, ctx=ctx)
        return plan.action

    # -- storm timeline (shared with the live driver) -------------------
    events = [
        {"t_s": ev.t_s, "kind": ev.kind, "args": dict(ev.args)}
        for ev in build_storm(scenario)
    ]
    for ex in (preempt_extra or []):
        events.append({
            "t_s": float(ex["t_s"]), "kind": "preempt",
            "args": {"victim": int(ex["victim"]),
                     "deadline_s": float(ex.get("deadline_s", 4.0)),
                     "spot": True},
        })
    events.sort(key=lambda e: e["t_s"])

    arrivals = arrival_offsets(
        w.offered_rps, scenario.duration_s,
        seed=f"{scenario.seed}:arrivals", process=w.arrival_process,
    )

    # queue entries: (arrival_t, deadline_t); in-flight:
    # (complete_at, arrival_t, replica_idx, fails: bool)
    queue: List[tuple] = []
    inflight: List[list] = []
    pending_replicas: List[float] = []  # ready_at times of launches
    pending_nodes: List[tuple] = []  # (ready_at, reuse_idx)
    over_since: Optional[float] = None
    idle_since: Optional[float] = None
    next_autoscale = 0.0
    next_health = 0.0
    node_seconds = 0.0
    replica_launches = 0
    min_up = len(nodes)
    ai = 0  # next arrival index
    ei = 0  # next storm event index

    def live_node(n: _Node, t: float) -> bool:
        return n.up

    def routable(n: _Node, t: float) -> bool:
        """Router willingly dispatches here: up, not mid-partition
        (once DETECTED), not healing, not mid-drain-migration."""
        if not n.up:
            return False
        if n.draining_since is not None:
            return False
        if n.partition_t is not None:
            det = n.partition_t + p.partition_detect_s
            if t >= det and (n.heal_t is None
                             or t < n.heal_t + p.partition_rejoin_s):
                return False
        return True

    def blind_partitioned(n: _Node, t: float) -> bool:
        """Partition started but phi hasn't crossed suspect yet — the
        router still dispatches here, and those requests time out."""
        return (
            n.up and n.partition_t is not None
            and n.partition_t <= t < n.partition_t + p.partition_detect_s
        )

    def place_replicas(victim_idx: int, t: float, delay: float):
        """Re-place the victim node's replicas on routable survivors
        (fewest-first); with no survivor they park and re-place when a
        node returns."""
        targets = [n for n in nodes if n.up and n.idx != victim_idx
                   and n.draining_since is None]
        for r in replicas:
            if r.node == victim_idx:
                if targets:
                    tgt = min(
                        targets,
                        key=lambda n: sum(1 for x in replicas
                                          if x.node == n.idx),
                    )
                    r.node = tgt.idx
                r.busy = 0
                r.ready_at = max(r.ready_at, t + delay)

    def apply_event(ev: dict, t: float):
        nonlocal replica_launches
        kind = ev["kind"]
        up_nodes = [n for n in nodes if n.up]
        if not up_nodes:
            log("chaos", "storm_skip", t, kind=kind,
                reason="no live nodes")
            return
        victim = up_nodes[int(ev["args"].get("victim", 0)) % len(up_nodes)]
        nid = f"sim-{victim.idx}"
        if kind == "preempt":
            deadline = float(ev["args"].get("deadline_s", 4.0))
            victim.draining_since = t
            victim.down_at = t + deadline
            place_replicas(victim.idx, t, p.preempt_migrate_s)
            # the lease for each migrated replica rides the lease site
            for _ in [r for r in replicas if r.node != victim.idx]:
                if hit("raylet.lease.grant", "soak.migrate", t) == "kill":
                    pass  # grant retried: modeled inside migrate delay
            log("chaos", "node_preempt", t, node_id=nid,
                deadline_s=deadline,
                spot=bool(ev["args"].get("spot")))
            if replace_nodes:
                pending_nodes.append((t + deadline + p.node_launch_s,
                                      victim.idx))
        elif kind == "partition":
            d = float(ev["args"].get("duration_s", 2.0))
            victim.partition_t = t
            victim.heal_t = t + d
            log("chaos", "partition", t, a=nid, b="gcs", duration_s=d)
            log("link", "cut", t, src=nid, dst="gcs", duration_s=d)
            log("link", "cut", t, src="gcs", dst=nid, duration_s=d)
        elif kind == "kill":
            victim.up = False
            victim.down_at = t
            for f in inflight:
                r = replicas[f[2]]
                if r.node == victim.idx:
                    f[3] = True  # fails at its (shortened) deadline
                    f[0] = min(f[0], t + p.kill_error_s)
            place_replicas(victim.idx, t, p.kill_restart_s)
            log("chaos", "node_kill", t, node_id=nid, graceful=False)
            if replace_nodes:
                pending_nodes.append((t + p.node_launch_s, victim.idx))

    t = 0.0
    end = scenario.duration_s + p.tail_s
    while t < end:
        # 1. storm
        while ei < len(events) and events[ei]["t_s"] <= t:
            apply_event(events[ei], t)
            ei += 1
        # node lifecycle: drain completion, heal, replacement
        for n in nodes:
            if n.up and n.down_at is not None and t >= n.down_at:
                n.up = False
                if n.draining_since is not None:
                    log("chaos", "node_kill", t,
                        node_id=f"sim-{n.idx}", graceful=True)
                n.draining_since = None
            if (n.partition_t is not None and n.heal_t is not None
                    and t >= n.heal_t + p.partition_rejoin_s):
                log("link", "auto_heal", n.heal_t,
                    src=f"sim-{n.idx}", dst="gcs")
                n.partition_t = n.heal_t = None
        for ready_at, idx in list(pending_nodes):
            if t >= ready_at:
                pending_nodes.remove((ready_at, idx))
                n = nodes[idx]
                n.up = True
                n.down_at = None
                n.incarnation += 1
                n.launched_at = t
                log("chaos", "node_launch", t, node_id=f"sim-{idx}",
                    incarnation=n.incarnation)
        min_up = min(min_up, sum(1 for n in nodes if n.up))
        node_seconds += sum(1 for n in nodes if n.up) * p.dt_s

        # 2. autoscale (queue-depth driven, PR 6 controller shape)
        if t >= next_autoscale:
            next_autoscale = t + p.autoscale_tick_s
            n_rep = len(replicas) + len(pending_replicas)
            depth_per = len(queue) / max(1, n_rep)
            if depth_per > w.target_queue_depth_per_replica:
                idle_since = None
                if over_since is None:
                    over_since = t
                elif (t - over_since >= w.upscale_delay_s
                      and n_rep < w.max_replicas):
                    launch = p.replica_launch_s
                    if hit("raylet.lease.grant", "soak.scale_up",
                           t) is not None:
                        launch += p.lease_fault_delay_s
                    pending_replicas.append(t + launch)
                    replica_launches += 1
                    over_since = t
            else:
                over_since = None
                busy = sum(r.busy for r in replicas)
                if len(queue) == 0 and busy <= 1:
                    if idle_since is None:
                        idle_since = t
                    elif (t - idle_since >= w.downscale_delay_s
                          and len(replicas) > w.min_replicas):
                        idle = [r for r in replicas if r.busy == 0]
                        if idle:
                            replicas.remove(idle[-1])
                            idle_since = t
                else:
                    idle_since = None
        for ready in list(pending_replicas):
            if t >= ready:
                pending_replicas.remove(ready)
                targets = [n for n in nodes if routable(n, t)]
                if targets:
                    tgt = min(targets, key=lambda n: sum(
                        1 for x in replicas if x.node == n.idx))
                    replicas.append(_Replica(tgt.idx, ready_at=t))
                else:  # nowhere to land yet: retry next tick
                    pending_replicas.append(t + p.dt_s)

        # 3. completions
        for f in list(inflight):
            if f[0] <= t:
                inflight.remove(f)
                complete_at, arrival, ridx, fails = f
                if ridx < len(replicas):
                    replicas[ridx].busy = max(
                        0, replicas[ridx].busy - 1)
                lat_ms = (complete_at - arrival) * 1000.0
                if fails:
                    records.append(RequestRecord(arrival, lat_ms,
                                                 "error"))
                else:
                    if hit("store.put", "soak.result",
                           complete_at) is not None:
                        lat_ms += p.store_retry_s * 1000.0
                    records.append(RequestRecord(arrival, lat_ms, "ok"))

        # 4. admission of arrivals due by now
        while ai < len(arrivals) and arrivals[ai] <= t:
            a = arrivals[ai]
            ai += 1
            if len(queue) >= w.max_queue_depth:
                records.append(RequestRecord(a, 1.0, "shed"))
                continue
            # predicted-delay trip (admission.py shape)
            cap = max(1, len(replicas)) * w.max_ongoing
            predicted_ms = (len(queue) / cap) * w.service_ms
            if predicted_ms > w.slo_ms:
                records.append(RequestRecord(a, 1.0, "shed"))
                continue
            queue.append((a, a + w.slo_ms / 1000.0))

        # 5. deadline expiry sweep (EDF shed of lapsed queue entries)
        for q in list(queue):
            if q[1] <= t:
                queue.remove(q)
                records.append(RequestRecord(
                    q[0], (t - q[0]) * 1000.0, "shed"))

        # 6. dispatch — least-busy among replicas the router BELIEVES
        # healthy: a blind-partitioned node is an equal candidate until
        # phi crosses suspect (the router can't route around silence it
        # hasn't detected), and dispatches there time out
        while queue:
            ridx = None
            for i, r in enumerate(replicas):
                if r.busy >= w.max_ongoing or r.ready_at > t:
                    continue
                n = nodes[r.node]
                if not (routable(n, t) or blind_partitioned(n, t)):
                    continue
                if ridx is None or r.busy < replicas[ridx].busy:
                    ridx = i
            if ridx is None:
                break
            arrival, _deadline = queue.pop(0)
            r = replicas[ridx]
            slot = None if blind_partitioned(nodes[r.node], t) else ridx
            r.busy += 1
            penalty = 0.0
            act = hit("rpc.send.frame", "soak.dispatch", t)
            if act in ("drop", "reset", "delay"):
                penalty += p.rpc_retry_s
            elif act == "error":
                inflight.append([t + 0.05, arrival, ridx, True])
                continue
            if slot is None:  # dispatched into the undetected partition
                inflight.append(
                    [t + p.partition_error_s, arrival, ridx, True])
            else:
                inflight.append(
                    [t + penalty + w.service_ms / 1000.0,
                     arrival, ridx, False])

        # 7. health samples
        if t >= next_health:
            next_health = t + p.health_sample_s
            for n in nodes:
                phi = 0.02
                suspect = False
                if n.partition_t is not None and t >= n.partition_t:
                    silent = t - n.partition_t
                    if n.heal_t is not None and t > n.heal_t:
                        silent = 0.0
                    phi = 0.02 + 3.0 * silent / p.partition_detect_s
                    suspect = phi >= 3.0
                health.append({
                    "t_s": round(t, 3), "node": f"sim-{n.idx}",
                    "phi": round(phi, 3), "suspect": suspect,
                    "incarnation": n.incarnation, "alive": n.up,
                })

        if (ai >= len(arrivals) and not inflight and not queue
                and t >= scenario.duration_s):
            break
        t = round(t + p.dt_s, 6)

    records.sort(key=lambda r: (r.t_s, r.status, r.latency_ms))
    storm_log.sort(key=lambda e: e["ts"])
    card = compute_scorecard(scenario, records, storm_log, health, t0=0.0)
    return SimResult(
        scorecard=card,
        records=records,
        storm_log=storm_log,
        health_samples=health,
        node_seconds=round(node_seconds, 6),
        replica_launches=replica_launches,
        min_up_nodes=min_up,
    )
