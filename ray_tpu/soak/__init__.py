"""ray_tpu.soak — the million-user-day soak plane.

A soak is the integration test the unit suite cannot be: one sustained
serve workload, every fault plane firing at once from a single seed,
and an availability scorecard that must EXPLAIN every dip it shows.

Layout (one concern per module):

- ``scenario``  declarative ``SoakScenario``: workload + SLOs + storm
  composition + armed fault plans, strict JSON round-trip.
- ``storm``     ``build_storm`` (pure seeded timeline) and the live
  ``StormDriver`` (timeline → ChaosController).
- ``load``      the shared open-loop arrival model + HTTP driver +
  ``RequestRecord`` stream (bench.py serve_rps consumes this too).
- ``scorecard`` goodput / shed / p99-vs-SLO / per-incident blackout
  attribution; canonical ``to_json`` is the reproducibility surface.
- ``sim``       deterministic twin: the scenario through a modeled
  fleet with REAL FaultController + storm + scorecard code —
  byte-identical scorecards from the same seed.
- ``runner``    live mode against a real cluster (proxy → admission →
  scheduler → autoscaled replicas, storm thread, health sampler).
- ``spot``      spot-fleet mode: live seeded revocation process and
  the deterministic throughput-per-cost ledger vs on-demand.
"""

from ray_tpu.soak.load import (
    RequestRecord,
    arrival_offsets,
    drive_http,
    summarize,
)
from ray_tpu.soak.scenario import (
    SLOSpec,
    SoakScenario,
    StormEvent,
    StormSpec,
    WorkloadSpec,
    acceptance_scenario,
)
from ray_tpu.soak.scorecard import Incident, Scorecard, compute_scorecard
from ray_tpu.soak.sim import SimParams, SimResult, run_sim
from ray_tpu.soak.spot import (
    SpotFleet,
    SpotFleetConfig,
    economics_rows,
    run_spot_economics,
    spot_preempt_times,
)
from ray_tpu.soak.storm import StormDriver, build_storm

__all__ = [
    "Incident",
    "RequestRecord",
    "SLOSpec",
    "Scorecard",
    "SimParams",
    "SimResult",
    "SoakScenario",
    "SpotFleet",
    "SpotFleetConfig",
    "StormDriver",
    "StormEvent",
    "StormSpec",
    "WorkloadSpec",
    "acceptance_scenario",
    "arrival_offsets",
    "build_storm",
    "compute_scorecard",
    "drive_http",
    "economics_rows",
    "run_sim",
    "run_spot_economics",
    "spot_preempt_times",
    "summarize",
]
