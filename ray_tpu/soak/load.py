"""Open-loop load generation: the ONE traffic model bench and soak share.

Open-loop means arrivals follow a fixed schedule computed up front —
the client never waits for a response before sending the next request.
A closed-loop client self-throttles at saturation (each in-flight
request blocks the next), which HIDES overload: the serve_rps bench and
the soak plane both exist to measure behavior PAST saturation, so both
must drive the same open-loop schedule.  Extracted from bench.py's
serve_rps inline loop so the bench row and the soak scorecard measure
with identical arrival semantics.

Arrival processes:

- ``poisson`` — exponential inter-arrivals from a ``random.Random``
  seeded by the scenario (memoryless: bursts and gaps occur naturally,
  the realistic open-internet shape).  Everything derives from the
  seed — same seed, same schedule, bit-for-bit (RT116 enforces this
  discipline package-wide).
- ``uniform`` — fixed 1/rate spacing (the legacy serve_rps schedule;
  kept for A/B against old records).

Per-request outcomes are normalized to ``RequestRecord`` — the
request-latency stream the scorecard window-joins against storm
events.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import List, Optional, Sequence

__all__ = [
    "RequestRecord",
    "arrival_offsets",
    "drive_http",
    "summarize",
]


@dataclass(frozen=True)
class RequestRecord:
    """One request in the latency stream.

    ``t_s``        arrival offset from the load window's start (s).
    ``latency_ms`` admission→completion latency; for sheds, the time to
                   the 503 (cheap); for errors, time to the failure.
    ``status``     "ok" | "shed" | "error".
    """

    t_s: float
    latency_ms: float
    status: str


def arrival_offsets(
    rate_rps: float,
    duration_s: float,
    seed: Optional[int] = None,
    process: str = "poisson",
) -> List[float]:
    """The open-loop schedule: sorted arrival offsets in [0, duration).

    ``poisson`` draws exponential inter-arrivals from
    ``random.Random(seed)`` — the seed is REQUIRED for poisson (a
    schedule that can't be replayed can't feed a reproducible
    scorecard).  ``uniform`` ignores the seed.
    """
    if process == "uniform":
        n = int(rate_rps * duration_s)
        return [i / rate_rps for i in range(n)]
    if process != "poisson":
        raise ValueError(f"unknown arrival process {process!r}")
    if seed is None:
        raise ValueError("poisson arrivals require a seed")
    rng = random.Random(seed)
    out: List[float] = []
    t = rng.expovariate(rate_rps)
    while t < duration_s:
        out.append(t)
        t += rng.expovariate(rate_rps)
    return out


async def drive_http(
    url: str,
    offsets: Sequence[float],
    warmup: int = 10,
    ready_timeout_s: float = 30.0,
    request_timeout_s: float = 30.0,
    on_start=None,
) -> List[RequestRecord]:
    """Fire the schedule at ``url`` (GET) and collect the latency
    stream.  200 → ok, 503 → shed, anything else (or a transport
    error) → error.  Waits for a first 200 (route/replica readiness)
    and runs ``warmup`` unrecorded requests before the clock starts.
    ``on_start`` (if given) is called exactly when the schedule clock
    starts — the soak runner uses it to launch the storm on the same
    t0 so event offsets and request offsets share one timeline.
    """
    import asyncio
    import time

    import aiohttp

    records: List[RequestRecord] = []
    timeout = aiohttp.ClientTimeout(total=request_timeout_s)

    async with aiohttp.ClientSession(timeout=timeout) as sess:

        async def one(t_arrive: float, record: bool = True):
            t0 = time.perf_counter()
            try:
                async with sess.get(url) as r:
                    await r.read()
                    status = (
                        "ok" if r.status == 200
                        else "shed" if r.status == 503
                        else "error"
                    )
            except Exception:
                status = "error"
            if record:
                records.append(RequestRecord(
                    t_s=t_arrive,
                    latency_ms=(time.perf_counter() - t0) * 1000.0,
                    status=status,
                ))

        # readiness: first 200 within the window, then warmup
        deadline = time.monotonic() + ready_timeout_s
        while time.monotonic() < deadline:
            try:
                async with sess.get(url) as r:
                    await r.read()
                    if r.status == 200:
                        break
            except Exception:
                pass
            await asyncio.sleep(0.3)
        for _ in range(warmup):
            await one(0.0, record=False)

        if on_start is not None:
            on_start()
        t_start = time.perf_counter()
        tasks = []
        for off in offsets:
            delay = t_start + off - time.perf_counter()
            if delay > 0:
                await asyncio.sleep(delay)
            tasks.append(asyncio.ensure_future(one(off)))
        await asyncio.gather(*tasks)
    return records


def summarize(records: Sequence[RequestRecord],
              elapsed_s: Optional[float] = None) -> dict:
    """The serve_rps row shape: admitted rate + latency percentiles of
    the OK stream, shed rate over everything offered."""
    ok = sorted(r.latency_ms for r in records if r.status == "ok")
    n = len(records)
    if elapsed_s is None:
        elapsed_s = max((r.t_s for r in records), default=0.0) or 1.0

    def pct(p: float) -> float:
        if not ok:
            return 0.0
        return ok[min(len(ok) - 1, int(p / 100.0 * len(ok)))]

    return {
        "offered": n,
        "admitted_rps": round(len(ok) / max(elapsed_s, 1e-9), 1),
        "p50_ms": round(pct(50), 1),
        "p99_ms": round(pct(99), 1),
        "shed_rate": round(
            sum(1 for r in records if r.status == "shed") / max(1, n), 3
        ),
        "errors": sum(1 for r in records if r.status == "error"),
    }
