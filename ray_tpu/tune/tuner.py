"""Tuner: the user-facing experiment API.

Role-equivalent of ray: python/ray/tune/tuner.py:44 (Tuner) +
result_grid.py (ResultGrid).  `Tuner(fn_or_trainer, param_space=...,
tune_config=...).fit()` resolves the search space into trials, runs them
through the TuneController, and returns a ResultGrid.

A JaxTrainer can be passed as the trainable (reference: Train delegates
its run loop to Tune, base_trainer.py:567-612; here the layering is
inverted — each trial drives a whole SPMD gang via trainer.fit()).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, List, Optional, Union

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.trainer import JaxTrainer, Result
from ray_tpu.tune.schedulers import FIFOScheduler
from ray_tpu.tune.search import generate_variants
from ray_tpu.tune.tune_controller import ERROR, Trial, TuneController


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 0  # 0 = unlimited
    scheduler: Any = None
    search_alg: Any = None  # a tune.search.Searcher (e.g. TPESearcher)
    seed: Optional[int] = None


class ResultGrid:
    def __init__(
        self,
        results: List[Result],
        trials: List[Trial],
        default_metric: Optional[str] = None,
        default_mode: str = "max",
    ):
        self._results = results
        self._trials = trials
        self._default_metric = default_metric
        self._default_mode = default_mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    @property
    def errors(self) -> List[BaseException]:
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> Result:
        metric = metric or self._default_metric
        mode = mode or self._default_mode
        if metric is None:
            raise ValueError(
                "no metric: pass metric= here or set TuneConfig.metric"
            )
        candidates = [
            r
            for r in self._results
            if r.error is None and metric in (r.metrics or {})
        ]
        if not candidates:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(candidates, key=key) if mode == "max" else min(
            candidates, key=key
        )

    def get_dataframe(self) -> List[Dict[str, Any]]:
        return [dict(r.metrics, _trial=i) for i, r in enumerate(self._results)]


def with_resources(
    trainable: Callable, resources: Dict[str, float]
) -> Callable:
    """Attach a per-trial resource demand (ray: tune.with_resources)."""
    trainable.__tune_resources__ = dict(resources)
    return trainable


class Tuner:
    def __init__(
        self,
        trainable: Union[Callable[[Dict[str, Any]], Any], JaxTrainer],
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config=None,  # train.RunConfig
    ):
        from ray_tpu.train.config import RunConfig

        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()

    def _resolve_trainable(self) -> Callable[[Dict[str, Any]], Any]:
        if isinstance(self.trainable, JaxTrainer):
            trainer = self.trainable

            def run_trainer_trial(config: Dict[str, Any]):
                from ray_tpu.train import session as train_session
                from ray_tpu.train.trainer import JaxTrainer as _JT

                merged = dict(trainer._config)
                merged.update(config.get("train_loop_config", config))
                sess = train_session.get_session()
                trial_trainer = _JT(
                    trainer._train_fn,
                    train_loop_config=merged,
                    scaling_config=trainer.scaling_config,
                    run_config=dataclasses.replace(
                        trainer.run_config,
                        name=sess.context.experiment_name
                        + "/"
                        + os.path.basename(sess.context.trial_dir),
                    ),
                    backend_config=trainer.backend_config,
                )
                r = trial_trainer.fit()
                if r.error is not None:
                    raise r.error
                sess.report(r.metrics, checkpoint=r.checkpoint)
                return r.metrics

            return run_trainer_trial
        return self.trainable

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        if tc.search_alg is not None:
            # set_search_properties role: a searcher constructed without a
            # space/metric inherits the Tuner's (explicit settings win)
            if not getattr(tc.search_alg, "space", None) and self.param_space:
                if hasattr(tc.search_alg, "set_space"):
                    tc.search_alg.set_space(self.param_space)
                else:
                    tc.search_alg.space = self.param_space
            if getattr(tc.search_alg, "metric", None) is None:
                tc.search_alg.metric = tc.metric
            if getattr(tc.search_alg, "mode", None) is None:
                tc.search_alg.mode = tc.mode
            configs = []  # trials come from the searcher, one at a time
        else:
            configs = generate_variants(
                self.param_space, num_samples=tc.num_samples, seed=tc.seed
            )
        name = self.run_config.name or "tune_run"
        exp_dir = os.path.join(self.run_config.resolved_storage_path(), name)
        scheduler = tc.scheduler or FIFOScheduler()
        # reference pattern: metric/mode set on TuneConfig propagate into a
        # scheduler constructed without them (set_search_properties); an
        # explicit scheduler setting always wins
        if getattr(scheduler, "metric", "") is None:
            if tc.metric is None:
                raise ValueError(
                    "scheduler needs a metric: set it on the scheduler or "
                    "in TuneConfig(metric=...)"
                )
            scheduler.metric = tc.metric
        if getattr(scheduler, "mode", "") is None:
            scheduler.mode = tc.mode
        resources = getattr(self.trainable, "__tune_resources__", {"CPU": 1})
        trials = [
            Trial(
                trial_id=f"{name}_{i:05d}",
                config=cfg,
                resources=dict(resources),
            )
            for i, cfg in enumerate(configs)
        ]
        searcher = tc.search_alg
        trial_factory = None
        if searcher is not None:
            searcher.set_search_properties(tc.metric, tc.mode)
            if getattr(searcher, "metric", None) is None:
                # without a metric the searcher would silently drop every
                # completed-trial observation and degrade to random search
                raise ValueError(
                    "search_alg needs a metric: set it on the searcher or "
                    "in TuneConfig(metric=...)"
                )
            if getattr(searcher, "max_trials", None) is None:
                searcher.max_trials = tc.num_samples

            def trial_factory(tid, cfg):
                return Trial(trial_id=tid, config=cfg,
                             resources=dict(resources))

        fc = self.run_config.failure_config
        controller = TuneController(
            self._resolve_trainable(),
            trials,
            scheduler=scheduler,
            max_concurrent=tc.max_concurrent_trials,
            experiment_dir=exp_dir,
            experiment_name=name,
            searcher=searcher,
            trial_factory=trial_factory,
            max_failures=fc.max_failures if fc is not None else 0,
        )
        controller.run()
        trials = controller.trials
        results = [
            Result(
                metrics=t.last_result,
                checkpoint=t.checkpoint,
                path=os.path.join(exp_dir, t.trial_id),
                metrics_dataframe=t.results,
                error=RuntimeError(t.error) if t.status == ERROR else None,
            )
            for t in trials
        ]
        return ResultGrid(
            results, trials, default_metric=tc.metric, default_mode=tc.mode
        )
