"""The trial event loop.

Role-equivalent of ray: python/ray/tune/execution/tune_controller.py:68
(TuneController) + trial.py.  Trials run as single worker actors reusing
the Train session machinery (report/get_checkpoint are the same API in
both libraries, like the reference).  The loop multiplexes outstanding
next_report calls with ray_tpu.wait, feeds results to the scheduler, and
kills trials it stops early.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.errors import ActorDiedError, TaskError
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import TrainContext
from ray_tpu.train.worker_group import TrainWorkerActor
from ray_tpu.tune.schedulers import CONTINUE, RESTART, STOP, FIFOScheduler

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclasses.dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    resources: Dict[str, float]
    status: str = PENDING
    results: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[str] = None
    early_stopped: bool = False
    actor: Any = None
    num_failures: int = 0  # crashes absorbed so far (FailureConfig)

    @property
    def last_result(self) -> Dict[str, Any]:
        return self.results[-1] if self.results else {}


class TuneController:
    def __init__(
        self,
        trainable: Callable[[Dict[str, Any]], Any],
        trials: List[Trial],
        *,
        scheduler=None,
        max_concurrent: int = 0,
        experiment_dir: str = "/tmp/ray_tpu_results/tune",
        experiment_name: str = "tune",
        searcher=None,
        trial_factory: Optional[Callable[[Dict[str, Any]], Trial]] = None,
        max_failures: int = 0,
    ):
        self.trainable = trainable
        self.trials = trials
        self.scheduler = scheduler or FIFOScheduler()
        self.max_concurrent = max_concurrent  # 0 = unlimited
        # trial-level fault tolerance (reference: FailureConfig.max_failures,
        # python/ray/air/config.py:399-409): a crashed trial is relaunched
        # from its latest checkpoint up to this many times; < 0 = forever
        self.max_failures = max_failures
        self.experiment_dir = experiment_dir
        self.experiment_name = experiment_name
        # sequential search (TPE etc.): trials are created on demand from
        # searcher.suggest() instead of all up front (reference:
        # tune/search/search_generator.py)
        self.searcher = searcher
        self.trial_factory = trial_factory
        self._search_exhausted = searcher is None

    # -- trial lifecycle -------------------------------------------------
    def _launch(self, trial: Trial, from_checkpoint: Optional[Checkpoint] = None):
        res = dict(trial.resources)
        extra = {k: v for k, v in res.items() if k != "CPU"}
        trial.actor = TrainWorkerActor.options(
            num_cpus=res.get("CPU", 1), resources=extra or None
        ).remote()
        ctx = TrainContext(
            world_size=1,
            world_rank=0,
            local_rank=0,
            local_world_size=1,
            node_rank=0,
            experiment_name=self.experiment_name,
            trial_dir=f"{self.experiment_dir}/{trial.trial_id}",
        )
        # dropped ref is safe: the run loop tracks this trial through
        # next_report refs on the same actor — a failed start kills the
        # actor and surfaces as an errored report there (rtflow RT202
        # audit: next_report refs live in the local `outstanding` dict
        # and every path pops them before re-arming)
        # rtlint: disable-next=RT105
        trial.actor.start_training.remote(
            self.trainable, trial.config, ctx, from_checkpoint
        )
        trial.status = RUNNING

    def _finalize(self, trial: Trial, status: str, error: Optional[str] = None):
        trial.status = status
        trial.error = error
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        if self.searcher is not None:
            try:
                self.searcher.on_trial_complete(
                    trial.trial_id, trial.last_result or None
                )
            except Exception:
                pass

    # -- the loop --------------------------------------------------------
    def run(self) -> List[Trial]:
        try:
            return self._run_inner()
        except BaseException:
            # don't leak live trial actors past an unexpected controller
            # failure (e.g. a scheduler bug)
            for t in self.trials:
                if t.status == RUNNING:
                    self._finalize(t, ERROR, "tune controller failed")
            raise

    def _run_inner(self) -> List[Trial]:
        # population-based schedulers exchange checkpoints between trials
        if hasattr(self.scheduler, "set_trials"):
            self.scheduler.set_trials(self.trials)
        pending = [t for t in self.trials if t.status == PENDING]
        outstanding: Dict[Any, Trial] = {}  # next_report ref -> trial

        def top_up():
            """Pull new trials from the searcher up to free capacity.

            Never pulls while running == max_concurrent: sequential
            searchers (TPE) condition each suggestion on completed
            results, so consuming suggestions early skews the search and
            holds a pending trial beyond the concurrency cap.  With
            unlimited concurrency we feed one trial per loop pass.
            """
            if self._search_exhausted:
                return
            while (len(pending) < capacity()
                   if self.max_concurrent > 0 else not pending):
                tid = f"{self.experiment_name}_{len(self.trials):05d}"
                cfg = self.searcher.suggest(tid)
                if cfg is None:
                    self._search_exhausted = True
                    return
                trial = self.trial_factory(tid, cfg)
                self.trials.append(trial)
                pending.append(trial)

        def capacity() -> int:
            running = sum(1 for t in self.trials if t.status == RUNNING)
            if self.max_concurrent <= 0:
                return len(pending)
            return max(0, self.max_concurrent - running)

        top_up()
        while pending or outstanding or not self._search_exhausted:
            top_up()
            for _ in range(min(capacity(), len(pending))):
                trial = pending.pop(0)
                self._launch(trial)
                ref = trial.actor.next_report.remote(timeout=30.0)
                outstanding[ref] = trial
            if not outstanding:
                time.sleep(0.05)
                continue
            ready, _ = ray_tpu.wait(
                list(outstanding.keys()), num_returns=1, timeout=5.0
            )
            for ref in ready:
                trial = outstanding.pop(ref)
                try:
                    report = ray_tpu.get(ref, timeout=60)
                except (TaskError, ActorDiedError) as e:
                    if (
                        self.max_failures < 0
                        or trial.num_failures < self.max_failures
                    ):
                        # restore: relaunch from the trial's latest
                        # checkpoint (possibly on a different node) and
                        # keep polling — the trainable resumes via
                        # session.get_checkpoint(), like gang restart
                        trial.num_failures += 1
                        if trial.actor is not None:
                            try:
                                ray_tpu.kill(trial.actor)
                            except Exception:
                                pass
                            trial.actor = None
                        self._launch(trial, from_checkpoint=trial.checkpoint)
                        nref = trial.actor.next_report.remote(timeout=30.0)
                        outstanding[nref] = trial
                    else:
                        self._finalize(trial, ERROR, str(e))
                    continue
                if report is None:  # loop finished cleanly
                    self._finalize(trial, TERMINATED)
                    continue
                if report.get("pending"):
                    # nothing reported inside the poll slice (legal: e.g. a
                    # long compile) — re-poll; trial liveness is carried by
                    # the actor call itself, not a report deadline
                    nref = trial.actor.next_report.remote(timeout=30.0)
                    outstanding[nref] = trial
                    continue
                result = report["metrics"]
                result.setdefault("training_iteration", len(trial.results) + 1)
                result.setdefault("_timestamp", time.time())
                trial.results.append(result)
                if report["checkpoint"] is not None:
                    trial.checkpoint = report["checkpoint"]
                decision = self.scheduler.on_trial_result(
                    trial.trial_id, result
                )
                if decision == STOP:
                    trial.early_stopped = True
                    self._finalize(trial, TERMINATED)
                elif decision == RESTART:
                    # exploit/explore (PBT): the scheduler already swapped
                    # trial.config/checkpoint; relaunch from that state
                    if trial.actor is not None:
                        try:
                            ray_tpu.kill(trial.actor)
                        except Exception:
                            pass
                        trial.actor = None
                    self._launch(trial, from_checkpoint=trial.checkpoint)
                    nref = trial.actor.next_report.remote(timeout=30.0)
                    outstanding[nref] = trial
                else:
                    assert decision == CONTINUE
                    nref = trial.actor.next_report.remote(timeout=30.0)
                    outstanding[nref] = trial
        return self.trials
