"""Search spaces and the basic variant generator.

Role-equivalent of ray: python/ray/tune/search/ (sample.py domains,
basic_variant.py BasicVariantGenerator): grid_search cross-products,
random distributions for sampled dimensions, num_samples repetitions.
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


# -- domains ---------------------------------------------------------------


@dataclass
class Domain:
    sampler: Callable[[random.Random], Any]

    def sample(self, rng: random.Random) -> Any:
        return self.sampler(rng)


def uniform(low: float, high: float) -> Domain:
    return Domain(lambda rng: rng.uniform(low, high))


def loguniform(low: float, high: float) -> Domain:
    import math

    lo, hi = math.log(low), math.log(high)
    return Domain(lambda rng: math.exp(rng.uniform(lo, hi)))


def randint(low: int, high: int) -> Domain:
    """Uniform integer in [low, high) (reference semantics)."""
    return Domain(lambda rng: rng.randrange(low, high))


def choice(options: List[Any]) -> Domain:
    opts = list(options)
    return Domain(lambda rng: rng.choice(opts))


def sample_from(fn: Callable[[Dict[str, Any]], Any]) -> Domain:
    """Sample from a callable receiving the partially-resolved config."""
    d = Domain(None)  # type: ignore[arg-type]
    d.needs_config = fn  # type: ignore[attr-defined]
    return d


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}


# -- variant generation ----------------------------------------------------


def _walk(space: Any, path=()):
    """Yield (path, spec) for every grid/domain leaf in a nested dict."""
    if isinstance(space, dict):
        if set(space.keys()) == {"grid_search"}:
            yield path, space
            return
        for k, v in space.items():
            yield from _walk(v, path + (k,))
    elif isinstance(space, Domain):
        yield path, space


def _set_path(cfg: dict, path, value):
    node = cfg
    for k in path[:-1]:
        node = node.setdefault(k, {})
    node[path[-1]] = value


def _contains_domain(value) -> bool:
    if isinstance(value, Domain):
        return True
    if isinstance(value, dict):
        return any(_contains_domain(v) for v in value.values())
    if isinstance(value, (list, tuple)):
        return any(_contains_domain(v) for v in value)
    return False


def _deep_copy_plain(space):
    if isinstance(space, dict):
        return {k: _deep_copy_plain(v) for k, v in space.items()}
    return space


def generate_variants(
    param_space: Dict[str, Any],
    num_samples: int = 1,
    seed: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """Resolve the space: full grid cross-product × num_samples random draws."""
    rng = random.Random(seed)
    leaves = list(_walk(param_space))
    grid_leaves = [(p, s["grid_search"]) for p, s in leaves if isinstance(s, dict)]
    domain_leaves = [(p, s) for p, s in leaves if isinstance(s, Domain)]

    grids = (
        itertools.product(*[vals for _, vals in grid_leaves])
        if grid_leaves
        else [()]
    )
    samplers = [
        (p, d) for p, d in domain_leaves if getattr(d, "needs_config", None) is None
    ]
    dependent = [
        (p, d) for p, d in domain_leaves if getattr(d, "needs_config", None) is not None
    ]
    configs: List[Dict[str, Any]] = []
    for combo in grids:
        for _ in range(num_samples):
            cfg = _deep_copy_plain(param_space)
            for (path, _), val in zip(grid_leaves, combo):
                _set_path(cfg, path, val)
            for path, dom in samplers:
                _set_path(cfg, path, dom.sample(rng))
            # sample_from callables may reference other sampled values:
            # resolve in passes, deferring ones whose inputs aren't ready
            # (reference: BasicVariantGenerator iterative resolution)
            todo = list(dependent)
            for _pass in range(len(todo) + 1):
                if not todo:
                    break
                deferred, last_err = [], None
                for path, dom in todo:
                    try:
                        val = dom.needs_config(cfg)
                        if _contains_domain(val):
                            # fn read a still-unresolved Domain: not ready
                            deferred.append((path, dom))
                            continue
                        _set_path(cfg, path, val)
                    except Exception as e:  # inputs unresolved yet
                        deferred.append((path, dom))
                        last_err = e
                if len(deferred) == len(todo):
                    raise ValueError(
                        f"could not resolve sample_from at {deferred[0][0]}: "
                        f"circular or invalid reference ({last_err!r})"
                    )
                todo = deferred
            configs.append(cfg)
    return configs
