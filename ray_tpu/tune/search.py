"""Search spaces and the basic variant generator.

Role-equivalent of ray: python/ray/tune/search/ (sample.py domains,
basic_variant.py BasicVariantGenerator): grid_search cross-products,
random distributions for sampled dimensions, num_samples repetitions.
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


# -- domains ---------------------------------------------------------------


@dataclass
class Domain:
    sampler: Callable[[random.Random], Any]
    # metadata for model-based searchers (TPE): how to model this leaf
    kind: str = "opaque"  # uniform | loguniform | randint | choice | opaque
    low: float = 0.0
    high: float = 0.0
    options: Optional[List[Any]] = None

    def sample(self, rng: random.Random) -> Any:
        return self.sampler(rng)


def uniform(low: float, high: float) -> Domain:
    return Domain(lambda rng: rng.uniform(low, high), kind="uniform",
                  low=low, high=high)


def loguniform(low: float, high: float) -> Domain:
    import math

    lo, hi = math.log(low), math.log(high)
    return Domain(
        lambda rng: math.exp(rng.uniform(lo, hi)),
        kind="loguniform", low=low, high=high,
    )


def randint(low: int, high: int) -> Domain:
    """Uniform integer in [low, high) (reference semantics)."""
    return Domain(lambda rng: rng.randrange(low, high), kind="randint",
                  low=low, high=high)


def choice(options: List[Any]) -> Domain:
    opts = list(options)
    return Domain(lambda rng: rng.choice(opts), kind="choice", options=opts)


def sample_from(fn: Callable[[Dict[str, Any]], Any]) -> Domain:
    """Sample from a callable receiving the partially-resolved config."""
    d = Domain(None)  # type: ignore[arg-type]
    d.needs_config = fn  # type: ignore[attr-defined]
    return d


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}


# -- variant generation ----------------------------------------------------


def _walk(space: Any, path=()):
    """Yield (path, spec) for every grid/domain leaf in a nested dict."""
    if isinstance(space, dict):
        if set(space.keys()) == {"grid_search"}:
            yield path, space
            return
        for k, v in space.items():
            yield from _walk(v, path + (k,))
    elif isinstance(space, Domain):
        yield path, space


def _set_path(cfg: dict, path, value):
    node = cfg
    for k in path[:-1]:
        node = node.setdefault(k, {})
    node[path[-1]] = value


def _contains_domain(value) -> bool:
    if isinstance(value, Domain):
        return True
    if isinstance(value, dict):
        return any(_contains_domain(v) for v in value.values())
    if isinstance(value, (list, tuple)):
        return any(_contains_domain(v) for v in value)
    return False


def _deep_copy_plain(space):
    if isinstance(space, dict):
        return {k: _deep_copy_plain(v) for k, v in space.items()}
    return space


def generate_variants(
    param_space: Dict[str, Any],
    num_samples: int = 1,
    seed: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """Resolve the space: full grid cross-product × num_samples random draws."""
    rng = random.Random(seed)
    leaves = list(_walk(param_space))
    grid_leaves = [(p, s["grid_search"]) for p, s in leaves if isinstance(s, dict)]
    domain_leaves = [(p, s) for p, s in leaves if isinstance(s, Domain)]

    grids = (
        itertools.product(*[vals for _, vals in grid_leaves])
        if grid_leaves
        else [()]
    )
    samplers = [
        (p, d) for p, d in domain_leaves if getattr(d, "needs_config", None) is None
    ]
    dependent = [
        (p, d) for p, d in domain_leaves if getattr(d, "needs_config", None) is not None
    ]
    configs: List[Dict[str, Any]] = []
    for combo in grids:
        for _ in range(num_samples):
            cfg = _deep_copy_plain(param_space)
            for (path, _), val in zip(grid_leaves, combo):
                _set_path(cfg, path, val)
            for path, dom in samplers:
                _set_path(cfg, path, dom.sample(rng))
            # sample_from callables may reference other sampled values:
            # resolve in passes, deferring ones whose inputs aren't ready
            # (reference: BasicVariantGenerator iterative resolution)
            todo = list(dependent)
            for _pass in range(len(todo) + 1):
                if not todo:
                    break
                deferred, last_err = [], None
                for path, dom in todo:
                    try:
                        val = dom.needs_config(cfg)
                        if _contains_domain(val):
                            # fn read a still-unresolved Domain: not ready
                            deferred.append((path, dom))
                            continue
                        _set_path(cfg, path, val)
                    except Exception as e:  # inputs unresolved yet
                        deferred.append((path, dom))
                        last_err = e
                if len(deferred) == len(todo):
                    raise ValueError(
                        f"could not resolve sample_from at {deferred[0][0]}: "
                        f"circular or invalid reference ({last_err!r})"
                    )
                todo = deferred
            configs.append(cfg)
    return configs


# -- sequential searchers --------------------------------------------------


class Searcher:
    """Sequential config proposer (ray: python/ray/tune/search/searcher.py).

    Unlike `generate_variants` (all configs up front), a Searcher is
    consulted one trial at a time and learns from completed results —
    the hook that model-based search (TPE here; Optuna/HyperOpt/Ax in
    the reference) plugs into.
    """

    metric: Optional[str] = None
    mode: Optional[str] = None

    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str]) -> None:
        if self.metric is None:
            self.metric = metric
        if self.mode is None:
            self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Random/grid search behind the Searcher interface
    (ray: tune/search/basic_variant.py)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self._configs = generate_variants(
            param_space, num_samples=num_samples, seed=seed
        )
        self._i = 0

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._i >= len(self._configs):
            return None
        cfg = self._configs[self._i]
        self._i += 1
        return cfg


def _norm_pdf(x: float, mu: float, sigma: float) -> float:
    import math

    z = (x - mu) / sigma
    return math.exp(-0.5 * z * z) / (sigma * math.sqrt(2 * math.pi))


class TPESearcher(Searcher):
    """Independent Tree-structured Parzen Estimator search.

    Role-equivalent of the reference's OptunaSearch default sampler
    (ray: tune/search/optuna/optuna_search.py; Bergstra et al. 2011):
    per dimension, completed trials are split into a good quantile
    (gamma) and the rest; candidates are drawn from a Parzen mixture
    over the good set and ranked by the density ratio good/bad.
    Dimensions are modeled independently (like Optuna's default);
    `sample_from` leaves resolve after the modeled leaves, as in
    generate_variants.  Combine with AsyncHyperBandScheduler to get the
    BOHB pairing (scheduler culls, searcher models).
    """

    def __init__(
        self,
        param_space: Dict[str, Any],
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        n_startup: int = 10,
        gamma: float = 0.25,
        n_candidates: int = 24,
        max_trials: Optional[int] = None,
        seed: Optional[int] = None,
    ):
        assert mode in (None, "min", "max")
        self.metric = metric
        self.mode = mode
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.max_trials = max_trials
        self._rng = random.Random(seed)
        self.set_space(param_space)
        self._suggested = 0
        # completed observations: list of (dict path->model-space value, score)
        self._obs: List[tuple] = []
        self._pending: Dict[str, Dict[tuple, Any]] = {}

    def set_space(self, param_space: Dict[str, Any]) -> None:
        """(Re)bind the search space — the Tuner injects its param_space
        into a searcher constructed without one (reference:
        set_search_properties)."""
        self.space = param_space
        leaves = list(_walk(param_space))
        # grid leaves are modeled as categoricals; opaque/sample_from
        # leaves stay random
        self._dims: List[tuple] = []
        self._dependent: List[tuple] = []
        for path, spec in leaves:
            if isinstance(spec, dict):  # grid_search
                self._dims.append(
                    (path, Domain(None, kind="choice",
                                  options=list(spec["grid_search"])))
                )
            elif getattr(spec, "needs_config", None) is not None:
                self._dependent.append((path, spec))
            else:
                self._dims.append((path, spec))

    # -- model-space transforms ---------------------------------------

    def _to_model(self, dom: Domain, value: Any) -> float:
        import math

        if dom.kind == "choice":
            try:
                return float(dom.options.index(value))
            except ValueError:
                return 0.0
        if dom.kind == "loguniform":
            return math.log(value)
        return float(value)

    def _from_model(self, dom: Domain, x: float) -> Any:
        import math

        if dom.kind == "choice":
            return dom.options[int(round(x)) % len(dom.options)]
        if dom.kind == "loguniform":
            # exp(log(low)) can land a ulp outside the bounds
            return min(dom.high, max(dom.low, math.exp(x)))
        if dom.kind == "randint":
            return int(min(dom.high - 1, max(dom.low, round(x))))
        return min(dom.high, max(dom.low, x))

    def _bounds(self, dom: Domain) -> tuple:
        import math

        if dom.kind == "loguniform":
            return math.log(dom.low), math.log(dom.high)
        if dom.kind == "choice":
            return 0.0, float(len(dom.options) - 1)
        return float(dom.low), float(dom.high)

    # -- TPE core ------------------------------------------------------

    def _sample_dim(self, path: tuple, dom: Domain) -> Any:
        obs = [(xs[path], score) for xs, score in self._obs if path in xs]
        if dom.kind == "opaque" or len(obs) < self.n_startup:
            if dom.kind == "choice" and dom.sampler is None:
                return self._rng.choice(dom.options)
            return dom.sample(self._rng) if dom.sampler else self._rng.choice(
                dom.options
            )
        obs.sort(key=lambda t: t[1], reverse=True)  # higher = better
        n_good = max(1, int(self.gamma * len(obs)))
        good = [x for x, _ in obs[:n_good]]
        bad = [x for x, _ in obs[n_good:]] or good
        if dom.kind == "choice":
            k = len(dom.options)
            gc = [1.0] * k
            bc = [1.0] * k
            for x in good:
                gc[int(x) % k] += 1
            for x in bad:
                bc[int(x) % k] += 1
            gsum, bsum = sum(gc), sum(bc)
            # draw candidates from the good distribution, rank by ratio
            cand = self._rng.choices(range(k), weights=gc,
                                     k=self.n_candidates)
            best = max(cand, key=lambda i: (gc[i] / gsum) / (bc[i] / bsum))
            return dom.options[best]
        lo, hi = self._bounds(dom)
        width = max(hi - lo, 1e-12)
        sigma = max(width / max(len(good), 1) ** 0.5, 1e-3 * width)

        def density(x: float, centers: List[float]) -> float:
            return sum(_norm_pdf(x, c, sigma) for c in centers) / len(centers)

        best_x, best_ratio = None, -1.0
        for _ in range(self.n_candidates):
            c = self._rng.choice(good)
            x = min(hi, max(lo, self._rng.gauss(c, sigma)))
            ratio = density(x, good) / max(density(x, bad), 1e-12)
            if ratio > best_ratio:
                best_x, best_ratio = x, ratio
        return self._from_model(dom, best_x)

    # -- Searcher interface -------------------------------------------

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self.max_trials is not None and self._suggested >= self.max_trials:
            return None
        self._suggested += 1
        cfg = _deep_copy_plain(self.space)
        xs: Dict[tuple, Any] = {}
        for path, dom in self._dims:
            val = self._sample_dim(path, dom)
            xs[path] = self._to_model(dom, val)
            _set_path(cfg, path, val)
        for path, dom in self._dependent:
            _set_path(cfg, path, dom.needs_config(cfg))
        self._pending[trial_id] = xs
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        xs = self._pending.pop(trial_id, None)
        if xs is None or not result or self.metric not in result:
            return
        v = float(result[self.metric])
        score = v if (self.mode or "max") == "max" else -v
        self._obs.append((xs, score))


class TuneBOHB(TPESearcher):
    """BOHB's model-based half (reference: ray.tune.search.bohb.TuneBOHB,
    built on the BOHB paper's TPE-style KDE sampler).  Pair with
    HyperBandForBOHB: the scheduler runs successive-halving brackets,
    this searcher proposes configs from a density model of completed
    results — together the BOHB algorithm (Falkner et al. 2018).

    Reference-style construction: the space may be omitted and is then
    injected by the Tuner from its ``param_space``."""

    def __init__(self, space=None, metric=None, mode=None, **kw):
        super().__init__(space or {}, metric=metric, mode=mode, **kw)


