"""ray_tpu.tune: experiment execution and hyperparameter search.

Role-equivalent of ray: python/ray/tune/.  Trials are single-actor
training loops sharing the Train session API (report/get_checkpoint);
Tuner resolves a param space into trials, runs them through the
TuneController with an optional scheduler (ASHA), and returns a
ResultGrid.
"""

from ray_tpu.train.session import get_checkpoint, report  # noqa: F401
from ray_tpu.tune.schedulers import (
    HyperBandForBOHB,  # noqa: F401
    ASHAScheduler,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import (  # noqa: F401
    BasicVariantGenerator,
    Searcher,
    TPESearcher,
    TuneBOHB,
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.tuner import (  # noqa: F401
    ResultGrid,
    TuneConfig,
    Tuner,
    with_resources,
)
