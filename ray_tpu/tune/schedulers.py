"""Trial schedulers: decide per-result whether a trial lives on.

Role-equivalent of ray: python/ray/tune/schedulers/ — FIFOScheduler
(trial_scheduler.py), ASHA (async_hyperband.py AsyncHyperBandScheduler):
asynchronous successive halving with geometric rungs, and PBT (pbt.py
PopulationBasedTraining): exploit/explore — bottom-quantile trials clone
a top-quantile trial's checkpoint and mutate its hyperparameters, then
RESTART from that state.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
RESTART = "RESTART"  # relaunch the trial from trial.checkpoint + new config


class FIFOScheduler:
    def on_trial_result(self, trial_id: str, result: dict) -> str:
        return CONTINUE


class ASHAScheduler:
    def __init__(
        self,
        metric: str = None,
        mode: str = None,  # None = inherit from TuneConfig (default "max")
        time_attr: str = "training_iteration",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 4,
    ):
        assert mode in (None, "min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung value -> list of recorded scores (sign-normalized: higher=better)
        self._rungs: Dict[int, List[float]] = {}
        # trial -> highest rung already evaluated (each rung checked once)
        self._trial_rung: Dict[str, int] = {}
        rung = grace_period
        self._rung_levels: List[int] = []
        while rung < max_t:
            self._rung_levels.append(rung)
            rung *= reduction_factor

    def _score(self, result: dict) -> float:
        v = float(result[self.metric])
        return v if (self.mode or "max") == "max" else -v

    def on_trial_result(self, trial_id: str, result: dict) -> str:
        t = int(result.get(self.time_attr, 0))
        if t >= self.max_t:
            return STOP  # budget exhausted (scheduler-complete, not failure)
        # Evaluate at the highest rung <= t not yet checked for this trial:
        # reports need not land exactly on rung values (reference ASHA
        # cull-checks at the highest milestone <= t).
        done_rung = self._trial_rung.get(trial_id, 0)
        eligible = [r for r in self._rung_levels if done_rung < r <= t]
        if not eligible:
            return CONTINUE
        rung = max(eligible)
        self._trial_rung[trial_id] = rung
        scores = self._rungs.setdefault(rung, [])
        score = self._score(result)
        scores.append(score)
        # top 1/rf quantile survives: k = ceil(n / rf)
        k = max(1, (len(scores) + self.rf - 1) // self.rf)
        cutoff = sorted(scores, reverse=True)[k - 1]
        return STOP if score < cutoff else CONTINUE


class PopulationBasedTraining:
    """PBT (ray: python/ray/tune/schedulers/pbt.py PopulationBasedTraining).

    Every ``perturbation_interval`` iterations a trial is ranked against
    the population's latest scores.  A bottom-quantile trial *exploits*
    (adopts a random top-quantile trial's checkpoint and config) and
    *explores* (mutates hyperparameters: resample with probability
    ``resample_probability``, else perturb by x1.2 / x0.8, matching the
    reference's _explore), then signals RESTART so the controller
    relaunches it from the adopted checkpoint.

    ``hyperparam_mutations`` maps config keys to either a list of
    choices or a callable returning a sample.
    """

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: Optional[str] = None,  # None = inherit from TuneConfig
        time_attr: str = "training_iteration",
        perturbation_interval: int = 4,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        seed: Optional[int] = None,
    ):
        assert mode in (None, "min", "max")
        assert 0.0 < quantile_fraction <= 0.5
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self.mutations = hyperparam_mutations or {}
        self._rng = random.Random(seed)
        self._trials: Dict[str, Any] = {}
        self._scores: Dict[str, float] = {}  # latest sign-normalized score
        self._last_perturb: Dict[str, int] = {}
        self.num_perturbations = 0

    def set_trials(self, trials: List[Any]) -> None:
        """Controller hands us the population (for checkpoint exchange)."""
        self._trials = {t.trial_id: t for t in trials}

    def _score(self, result: dict) -> float:
        v = float(result[self.metric])
        return v if (self.mode or "max") == "max" else -v

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(config)
        for key, spec in self.mutations.items():
            if key not in out:
                continue
            if self._rng.random() < self.resample_prob or not isinstance(
                out[key], (int, float)
            ):
                if callable(spec):
                    out[key] = spec()
                else:
                    out[key] = self._rng.choice(list(spec))
            else:
                factor = 1.2 if self._rng.random() > 0.5 else 0.8
                out[key] = type(out[key])(out[key] * factor)
        return out

    def on_trial_result(self, trial_id: str, result: dict) -> str:
        t = int(result.get(self.time_attr, 0))
        self._scores[trial_id] = self._score(result)
        if t - self._last_perturb.get(trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        n = len(self._scores)
        # rank only once the whole population has reported — early in the
        # run a 2-of-N comparison would mark the second reporter "bottom
        # quantile" spuriously
        if n < 2 or (self._trials and n < len(self._trials)):
            return CONTINUE
        ranked = sorted(
            self._scores.items(), key=lambda kv: kv[1], reverse=True
        )
        k = max(1, int(n * self.quantile))
        top = [tid for tid, _ in ranked[:k]]
        bottom = {tid for tid, _ in ranked[-k:]}
        if trial_id not in bottom or trial_id in top:
            return CONTINUE
        src_id = self._rng.choice(top)
        src = self._trials.get(src_id)
        me = self._trials.get(trial_id)
        if src is None or me is None or src.checkpoint is None:
            return CONTINUE  # nothing to exploit yet
        me.checkpoint = src.checkpoint
        me.config = self._explore(dict(src.config))
        self.num_perturbations += 1
        return RESTART


class PB2(PopulationBasedTraining):
    """Population Based Bandits (ray: python/ray/tune/schedulers/pb2.py).

    PBT's exploit step with a MODEL-BASED explore: instead of random
    x1.2/x0.8 perturbation, a Gaussian process is fit to
    (time, hyperparams) -> score-improvement observations from the whole
    population, and the new config maximizes the GP's UCB
    (mu + kappa * sigma) within ``hyperparam_bounds``.  Sample-efficient
    where PBT's random walk thrashes — the paper's claim, and why the
    reference ships both.

    The GP uses an RBF kernel with fixed hyperparameters on normalized
    data (the reference fits them via GPy, unavailable here; at
    population scale — tens of points — fixed length-scales behave
    comparably).  ``hyperparam_bounds`` maps config keys to (low, high);
    values stay floats (cast back to int when the incumbent was int).
    """

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        time_attr: str = "training_iteration",
        perturbation_interval: int = 4,
        quantile_fraction: float = 0.25,
        hyperparam_bounds: Optional[Dict[str, Any]] = None,
        ucb_kappa: float = 2.0,
        candidates: int = 256,
        seed: Optional[int] = None,
    ):
        assert hyperparam_bounds, "PB2 requires hyperparam_bounds"
        super().__init__(
            metric=metric,
            mode=mode,
            time_attr=time_attr,
            perturbation_interval=perturbation_interval,
            quantile_fraction=quantile_fraction,
            hyperparam_mutations={},  # explore is GP-driven
            seed=seed,
        )
        self.bounds = {
            k: (float(lo), float(hi))
            for k, (lo, hi) in hyperparam_bounds.items()
        }
        self.kappa = ucb_kappa
        self.candidates = candidates
        self.max_observations = 500  # GP fit is O(n^3): keep recent rows
        # observations: rows of (t, hp_1..hp_d) -> score improvement
        self._X: List[List[float]] = []
        self._y: List[float] = []
        self._prev_score: Dict[str, float] = {}
        self._trial_hps: Dict[str, List[float]] = {}
        self._current_t: float = 0.0

    # -- data collection --------------------------------------------------
    def on_trial_result(self, trial_id: str, result: dict) -> str:
        t = float(result.get(self.time_attr, 0))
        self._current_t = t
        score = self._score(result)
        trial = self._trials.get(trial_id)
        if trial is not None:
            hps = [
                float(trial.config.get(k, lo))
                for k, (lo, _hi) in self.bounds.items()
            ]
            prev = self._prev_score.get(trial_id)
            if prev is not None:
                self._X.append([t, *self._trial_hps.get(trial_id, hps)])
                self._y.append(score - prev)
                if len(self._y) > self.max_observations:
                    # bound the GP fit (O(n^3)): recent rows carry the
                    # relevant time context anyway
                    del self._X[0], self._y[0]
            self._trial_hps[trial_id] = hps
        self._prev_score[trial_id] = score
        decision = super().on_trial_result(trial_id, result)
        if decision == RESTART:
            # the next report's score jump comes from the CLONED
            # checkpoint, not from this trial's old hyperparams — it
            # must not enter the GP as an observation for them
            self._prev_score.pop(trial_id, None)
            self._trial_hps.pop(trial_id, None)
        return decision

    # -- GP-UCB explore ---------------------------------------------------
    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        import numpy as np

        out = dict(config)
        keys = list(self.bounds)
        lo = np.array([self.bounds[k][0] for k in keys])
        hi = np.array([self.bounds[k][1] for k in keys])
        rng = np.random.default_rng(self._rng.randrange(2 ** 31))
        if len(self._y) < 4:  # cold start: uniform resample
            pick = lo + rng.random(len(keys)) * (hi - lo)
        else:
            X = np.asarray(self._X, float)
            y = np.asarray(self._y, float)
            # normalize inputs to [0,1]^d (incl. the time column) and
            # standardize outputs — fixed-kernel GPs need this
            xmin, xmax = X.min(0), X.max(0)
            span = np.where(xmax > xmin, xmax - xmin, 1.0)
            Xn = (X - xmin) / span
            ystd = y.std() or 1.0
            yn = (y - y.mean()) / ystd
            cand = np.empty((self.candidates, X.shape[1]))
            cand[:, 0] = self._current_t  # context: NOW
            cand[:, 1:] = lo + rng.random(
                (self.candidates, len(keys))
            ) * (hi - lo)
            candn = (cand - xmin) / span
            mu, sigma = _gp_posterior(Xn, yn, candn)
            pick = cand[int(np.argmax(mu + self.kappa * sigma)), 1:]
        for k, v in zip(keys, pick):
            if isinstance(out.get(k), int):
                v = int(round(v))
            out[k] = v
        return out


def _gp_posterior(X, y, Xq, lengthscale: float = 0.3,
                  noise: float = 1e-2):
    """RBF-kernel GP posterior mean/std at query points (numpy only).

    Fixed hyperparameters on normalized data (see PB2 docstring).
    """
    import numpy as np

    def k(a, b):
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / lengthscale ** 2)

    K = k(X, X) + noise * np.eye(len(X))
    L = np.linalg.cholesky(K)
    alpha = np.linalg.solve(L.T, np.linalg.solve(L, y))
    Ks = k(X, Xq)
    mu = Ks.T @ alpha
    v = np.linalg.solve(L, Ks)
    var = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
    return mu, np.sqrt(var)


class AsyncHyperBandScheduler:
    """Multi-bracket asynchronous HyperBand.

    Role-equivalent of ray: python/ray/tune/schedulers/async_hyperband.py
    (AsyncHyperBandScheduler with brackets > 1; the repo's ASHAScheduler
    is the single-bracket special case).  Trials are assigned round-robin
    to `brackets` ASHA instances whose grace periods grow geometrically
    (grace, grace*rf, grace*rf^2, ...), hedging the early-culling
    aggressiveness against slow starters.  Pair with TPESearcher for the
    BOHB pairing (schedulers cull, searcher models; ray: tune/schedulers/
    hb_bohb.py + search/bohb/).
    """

    def __init__(
        self,
        metric: str = None,
        mode: str = None,
        time_attr: str = "training_iteration",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 4,
        brackets: int = 3,
    ):
        assert mode in (None, "min", "max")
        self.metric = metric
        self.mode = mode
        self._brackets = []
        for s in range(max(1, brackets)):
            g = grace_period * (reduction_factor ** s)
            if g >= max_t:
                break
            b = ASHAScheduler(
                metric=metric, mode=mode, time_attr=time_attr, max_t=max_t,
                grace_period=g, reduction_factor=reduction_factor,
            )
            self._brackets.append(b)
        if not self._brackets:
            self._brackets.append(
                ASHAScheduler(metric=metric, mode=mode, time_attr=time_attr,
                              max_t=max_t, grace_period=grace_period,
                              reduction_factor=reduction_factor)
            )
        self._assignment: Dict[str, int] = {}
        self._next = 0

    def __setattr__(self, name, value):
        # metric/mode set late by the Tuner propagate into the brackets
        super().__setattr__(name, value)
        if name in ("metric", "mode") and getattr(self, "_brackets", None):
            for b in self._brackets:
                setattr(b, name, value)

    def on_trial_result(self, trial_id: str, result: dict) -> str:
        i = self._assignment.get(trial_id)
        if i is None:
            i = self._assignment[trial_id] = self._next % len(self._brackets)
            self._next += 1
        return self._brackets[i].on_trial_result(trial_id, result)


class MedianStoppingRule:
    """Stop a trial whose running-average falls below the median of the
    other trials' running averages at the same step.

    Role-equivalent of ray: python/ray/tune/schedulers/median_stopping_rule.py
    (MedianStoppingRule): per-trial mean over reported scores so far,
    compared against the median of completed means after a grace period.
    """

    def __init__(
        self,
        metric: str = None,
        mode: str = None,
        time_attr: str = "training_iteration",
        grace_period: int = 4,
        min_samples_required: int = 3,
    ):
        assert mode in (None, "min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._sums: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}

    def _score(self, result: dict) -> float:
        v = float(result[self.metric])
        return v if (self.mode or "max") == "max" else -v

    def on_trial_result(self, trial_id: str, result: dict) -> str:
        s = self._score(result)
        self._sums[trial_id] = self._sums.get(trial_id, 0.0) + s
        self._counts[trial_id] = self._counts.get(trial_id, 0) + 1
        t = int(result.get(self.time_attr, self._counts[trial_id]))
        if t < self.grace_period:
            return CONTINUE
        means = [
            self._sums[tid] / self._counts[tid]
            for tid in self._sums
            if tid != trial_id
        ]
        if len(means) < self.min_samples:
            return CONTINUE
        means.sort()
        median = means[len(means) // 2]
        my_mean = self._sums[trial_id] / self._counts[trial_id]
        return STOP if my_mean < median else CONTINUE


class HyperBandForBOHB(AsyncHyperBandScheduler):
    """BOHB's bandit half (reference: ray.tune.schedulers.HyperBandForBOHB):
    async HyperBand whose trials are proposed by TuneBOHB's density
    model instead of random sampling.  Functionally the async-bracket
    variant is what the reference's implementation reduces to on this
    stack (trial proposals already arrive sequentially from the
    searcher, so no bracket-filling coordination is needed)."""


