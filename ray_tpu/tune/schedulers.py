"""Trial schedulers: decide per-result whether a trial lives on.

Role-equivalent of ray: python/ray/tune/schedulers/ — FIFOScheduler
(trial_scheduler.py) and ASHA (async_hyperband.py AsyncHyperBandScheduler):
asynchronous successive halving with geometric rungs; a trial reaching a
rung must be in the top 1/reduction_factor of that rung's recorded scores
or it stops.
"""

from __future__ import annotations

from typing import Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_trial_result(self, trial_id: str, result: dict) -> str:
        return CONTINUE


class ASHAScheduler:
    def __init__(
        self,
        metric: str = None,
        mode: str = None,  # None = inherit from TuneConfig (default "max")
        time_attr: str = "training_iteration",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 4,
    ):
        assert mode in (None, "min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung value -> list of recorded scores (sign-normalized: higher=better)
        self._rungs: Dict[int, List[float]] = {}
        # trial -> highest rung already evaluated (each rung checked once)
        self._trial_rung: Dict[str, int] = {}
        rung = grace_period
        self._rung_levels: List[int] = []
        while rung < max_t:
            self._rung_levels.append(rung)
            rung *= reduction_factor

    def _score(self, result: dict) -> float:
        v = float(result[self.metric])
        return v if (self.mode or "max") == "max" else -v

    def on_trial_result(self, trial_id: str, result: dict) -> str:
        t = int(result.get(self.time_attr, 0))
        if t >= self.max_t:
            return STOP  # budget exhausted (scheduler-complete, not failure)
        # Evaluate at the highest rung <= t not yet checked for this trial:
        # reports need not land exactly on rung values (reference ASHA
        # cull-checks at the highest milestone <= t).
        done_rung = self._trial_rung.get(trial_id, 0)
        eligible = [r for r in self._rung_levels if done_rung < r <= t]
        if not eligible:
            return CONTINUE
        rung = max(eligible)
        self._trial_rung[trial_id] = rung
        scores = self._rungs.setdefault(rung, [])
        score = self._score(result)
        scores.append(score)
        # top 1/rf quantile survives: k = ceil(n / rf)
        k = max(1, (len(scores) + self.rf - 1) // self.rf)
        cutoff = sorted(scores, reverse=True)[k - 1]
        return STOP if score < cutoff else CONTINUE
