"""rtrace engine: plane classification + RT3xx concurrency rules over
the whole-program index, plus the native lock-order checker over
``_native`` C++ sources.  Findings ride the SAME Finding/suppression/
fingerprint machinery as the RT1xx/RT2xx tiers; C++ files honor the
same directives inside ``//`` comments
(``// rtlint: disable-next=RT304``).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

from ray_tpu.devtools.lint import (
    _SUPPRESS_RE,
    Finding,
    _apply_suppressions,
)

DEFAULT_TRACE_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "trace_baseline.json"
)

NATIVE_SUFFIXES = (".cc", ".cpp", ".cxx", ".h", ".hpp")


class TraceRule:
    """Whole-program concurrency rule: ``check(index, planes)`` walks
    the index with the plane classification and reports through ``add``
    into the owning module's context (so suppression comments apply)."""

    id: str = ""
    name: str = ""
    description: str = ""
    hint: str = ""
    kind: str = "python"

    def check(self, index, planes) -> None:
        raise NotImplementedError

    def add(self, module, node, message=None, hint=None) -> None:
        module.ctx.add(self, node, message=message, hint=hint)


class NativeTraceRule(TraceRule):
    """C++-side rule: ``check_native(path, source)`` returns
    ``(lineno, col, message)`` tuples; the engine builds Findings and
    applies ``//``-comment suppressions."""

    kind = "native"

    def check(self, index, planes) -> None:  # pragma: no cover
        pass

    def check_native(
        self, path: str, source: str
    ) -> List[Tuple[int, int, str]]:
        raise NotImplementedError


def all_trace_rules() -> List[TraceRule]:
    # imported here: the rule modules import TraceRule from this module
    from ray_tpu.devtools.trace.native import NativeLockOrder
    from ray_tpu.devtools.trace.oneshot import OneShotReassign
    from ray_tpu.devtools.trace.races import CrossPlaneMutation
    from ray_tpu.devtools.trace.toctou import AwaitGapToctou

    return [
        CrossPlaneMutation(),
        AwaitGapToctou(),
        OneShotReassign(),
        NativeLockOrder(),
    ]


def trace_rule_ids() -> Tuple[str, ...]:
    return tuple(r.id for r in all_trace_rules())


@dataclasses.dataclass
class TraceReport:
    findings: List[Finding]
    files_indexed: int
    parse_errors: List[str]


def _select(rules: Optional[Sequence[str]]) -> List[TraceRule]:
    selected = all_trace_rules()
    if rules is not None:
        wanted = set(rules)
        unknown = wanted - {r.id for r in selected}
        if unknown:
            raise ValueError(f"unknown trace rule id(s): {sorted(unknown)}")
        selected = [r for r in selected if r.id in wanted]
    return selected


# ---------------------------------------------------------------------------
# Native-file suppressions (// rtlint: disable=RT304 ...)
# ---------------------------------------------------------------------------


def _native_suppressions(source: str):
    per_line: Dict[int, set] = {}
    file_wide: set = set()
    for i, text in enumerate(source.splitlines(), start=1):
        pos = text.find("//")
        if pos < 0:
            continue
        # _SUPPRESS_RE anchors on the Python comment marker; present
        # the C++ comment body as one
        m = _SUPPRESS_RE.search("# " + text[pos + 2:])
        if not m:
            continue
        kind, ids_text = m.group(1), m.group(2)
        ids = {s.strip() for s in ids_text.split(",")}
        if kind == "disable":
            per_line.setdefault(i, set()).update(ids)
        elif kind == "disable-next":
            per_line.setdefault(i + 1, set()).update(ids)
        else:
            file_wide.update(ids)
    return per_line, file_wide


def _check_native_file(
    path: str, source: str, rules: Sequence[NativeTraceRule]
) -> List[Finding]:
    lines = source.splitlines()
    per_line, file_wide = _native_suppressions(source)
    out: List[Finding] = []
    for rule in rules:
        for lineno, col, message in rule.check_native(path, source):
            ids = per_line.get(lineno, set()) | file_wide
            if rule.id in ids or "all" in ids:
                continue
            text = lines[lineno - 1] if 1 <= lineno <= len(lines) else ""
            out.append(Finding(
                path=path,
                line=lineno,
                col=col,
                rule=rule.id,
                message=message,
                hint=rule.hint,
                line_text=text,
            ))
    return out


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def _run(py_entries, native_files, rules) -> List[Finding]:
    """py_entries: (finding_path, module_name, source, tree);
    native_files: (finding_path, source)."""
    from ray_tpu.devtools.flow.index import build_index
    from ray_tpu.devtools.trace.planes import build_planes

    selected = _select(rules)
    py_rules = [r for r in selected if r.kind == "python"]
    native_rules = [r for r in selected if r.kind == "native"]

    findings: List[Finding] = []
    if py_entries and py_rules:
        index = build_index(py_entries)
        planes = build_planes(index)
        for rule in py_rules:
            rule.check(index, planes)
        for mname in sorted(index.modules):
            findings.extend(_apply_suppressions(index.modules[mname].ctx))
    if native_files and native_rules:
        for path, source in native_files:
            findings.extend(_check_native_file(path, source, native_rules))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_sources(
    files: Dict[str, str], rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Fixture/test entry point: ``files`` maps package-relative paths
    to sources; ``.py`` paths double as module names, native suffixes
    route to the C++ checker."""
    from ray_tpu.devtools.flow.index import module_name_from_relpath

    py_entries = []
    native_files = []
    for path in sorted(files):
        norm = path.replace(os.sep, "/")
        if norm.endswith(NATIVE_SUFFIXES):
            native_files.append((norm, files[path]))
            continue
        tree = ast.parse(files[path], filename=norm)
        py_entries.append(
            (norm, module_name_from_relpath(norm), files[path], tree)
        )
    return _run(py_entries, native_files, rules)


def _collect_native(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    seen = set()
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(NATIVE_SUFFIXES):
                out.append(p)
            continue
        for root, dirs, fnames in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if not d.startswith(".") and d != "__pycache__"
            )
            for f in sorted(fnames):
                if f.endswith(NATIVE_SUFFIXES):
                    fp = os.path.join(root, f)
                    ap = os.path.abspath(fp)
                    if ap not in seen:
                        seen.add(ap)
                        out.append(fp)
    return out


def _finding_path(fpath: str) -> str:
    rel = fpath
    if os.path.isabs(fpath):
        candidate = os.path.relpath(fpath)
        if not candidate.startswith(".."):
            rel = candidate
    return rel.replace(os.sep, "/")


def analyze_paths(
    paths: Sequence[str], rules: Optional[Sequence[str]] = None
) -> TraceReport:
    from ray_tpu.devtools.flow.engine import _collect_entries
    from ray_tpu.devtools.flow.index import module_name_from_relpath

    py_entries = []
    errors: List[str] = []
    for finding_path, rel_for_name, apath in _collect_entries(paths):
        try:
            with open(apath, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=finding_path)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append(f"{finding_path}: {e}")
            continue
        py_entries.append((
            finding_path,
            module_name_from_relpath(rel_for_name),
            source,
            tree,
        ))
    native_files = []
    for fpath in _collect_native(paths):
        try:
            with open(fpath, "r", encoding="utf-8") as fh:
                native_files.append((_finding_path(fpath), fh.read()))
        except (UnicodeDecodeError, OSError) as e:
            errors.append(f"{_finding_path(fpath)}: {e}")
    findings = _run(py_entries, native_files, rules)
    return TraceReport(
        findings, len(py_entries) + len(native_files), errors
    )
