"""RT301: instance/module attribute rebound from two execution planes
with no lock (and no loop hand-off) on at least one side.

The walker visits every planed function body, carrying the current
plane set (switching to a nested def's dispatch override when entering
one) and a lexical lock depth (``with <lockish>:`` regions).  A
mutation site is a plain rebind — ``self.x = ...``, ``self.x += ...``,
``del self.x``, or a declared-``global`` assignment.  Container method
calls (``self.q.append``) are deliberately NOT mutations here: the
GIL-atomic deque/dict protocols the runtime documents would all flag,
and torn *rebinds* are the class PRs 7-13 actually shipped.

A finding fires per unlocked mutation site of any attribute whose
mutation sites span >= 2 planes.  ``__init__``-family bodies are exempt
(construction happens-before publication), as are lock-named
attributes.  The ``call_soon_threadsafe`` hand-off needs no special
case: a callback handed to the loop IS classified ``loop``, so a
properly funneled attribute collapses to one plane and never fires.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ray_tpu.devtools import astutil
from ray_tpu.devtools.trace.engine import TraceRule
from ray_tpu.devtools.trace.planes import CTOR_NAMES


class _Site:
    __slots__ = ("fn", "node", "planes", "locked")

    def __init__(self, fn, node, planes, locked):
        self.fn = fn
        self.node = node
        self.planes = planes
        self.locked = locked


def _lockish_with(stmt) -> bool:
    return any(astutil.is_lockish(item.context_expr) for item in stmt.items)


def _global_names(fn_node: ast.AST) -> set:
    out = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


def _mutation_keys(stmt, owner_qual: Optional[str], module_name: str,
                   globals_declared: set) -> List[Tuple[tuple, ast.AST]]:
    """(key, anchor node) per attribute/global this statement rebinds."""
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.Delete):
        targets = list(stmt.targets)
    out: List[Tuple[tuple, ast.AST]] = []
    for t in targets:
        if isinstance(t, ast.Tuple):
            out.extend(
                _mutation_keys_from_target(
                    e, owner_qual, module_name, globals_declared
                )
                for e in t.elts
            )
            out = [x for x in out if x is not None]
            continue
        hit = _mutation_keys_from_target(
            t, owner_qual, module_name, globals_declared
        )
        if hit is not None:
            out.append(hit)
    return out


def _mutation_keys_from_target(
    t, owner_qual, module_name, globals_declared
) -> Optional[Tuple[tuple, ast.AST]]:
    if (
        isinstance(t, ast.Attribute)
        and isinstance(t.value, ast.Name)
        and t.value.id == "self"
        and owner_qual is not None
    ):
        if astutil.is_lockish(t):
            return None  # rebinding a lock object is a different sin
        return (("attr", owner_qual, t.attr), t)
    if isinstance(t, ast.Name) and t.id in globals_declared:
        return (("global", module_name, t.id), t)
    return None


class CrossPlaneMutation(TraceRule):
    id = "RT301"
    name = "cross-plane-unlocked-mutation"
    description = (
        "attribute rebound from two execution planes without a lock "
        "or a call_soon_threadsafe hand-off on this side"
    )
    hint = (
        "hold one lock at every rebind site, or funnel all mutations "
        "onto the loop with call_soon_threadsafe"
    )

    def check(self, index, planes) -> None:
        groups: Dict[tuple, List[_Site]] = {}
        for qual in sorted(index.functions):
            fn = index.functions[qual]
            if fn.name in CTOR_NAMES:
                continue
            self._scan(fn, planes, groups)
        for key in sorted(groups):
            sites = groups[key]
            spanned = set()
            for s in sites:
                spanned.update(s.planes)
            if len(spanned) < 2:
                continue
            label = "+".join(sorted(spanned))
            _, owner, attr = key
            short = owner.rsplit(".", 1)[-1] if key[0] == "attr" else owner
            for s in sites:
                if s.locked:
                    continue
                self.add(
                    s.fn.module,
                    s.node,
                    message=(
                        f"`{short}.{attr}` is rebound from planes "
                        f"{label}; this site holds no lock and is not "
                        f"funneled through the loop"
                    ),
                )

    def _scan(self, fn, planes, groups) -> None:
        owner_qual = fn.owner.qualname if fn.owner is not None else None
        module_name = fn.module.name
        globals_declared = _global_names(fn.node)
        base_planes = planes.of(fn.qualname)

        def visit(node, cur_planes, lock_depth):
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    if child.name in CTOR_NAMES:
                        continue
                    ov = planes.overrides.get(child)
                    nxt = {ov} if ov is not None else cur_planes
                    visit(child, nxt, lock_depth)
                    continue
                if isinstance(child, ast.Lambda):
                    ov = planes.overrides.get(child)
                    nxt = {ov} if ov is not None else cur_planes
                    visit(child, nxt, lock_depth)
                    continue
                depth = lock_depth
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    if _lockish_with(child):
                        depth += 1
                if cur_planes and isinstance(
                    child,
                    (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Delete),
                ):
                    for key, anchor in _mutation_keys(
                        child, owner_qual, module_name, globals_declared
                    ):
                        groups.setdefault(key, []).append(_Site(
                            fn, anchor,
                            frozenset(cur_planes), depth > 0,
                        ))
                visit(child, cur_planes, depth)

        if base_planes or planes.overrides:
            visit(fn.node, set(base_planes), 0)
