"""RT304: lexical RAII lock-order checker for the native shm arena.

``_native/shm_store.cc`` documents a strict acquisition order —
**MAIN < shard < ledger** — with one sanctioned composite: stop-world
takes MAIN and then every shard ascending (the ``StopWorld`` RAII
guard).  Every historical near-miss in review was an unwind path that
re-entered the allocator (MAIN) while still inside a shard or ledger
scope, so the checker tracks exactly that: brace-scoped lifetimes of
``MainLock`` / ``ShardLock`` / ``LedgerLock`` declarations plus raw
``lock_robust`` / ``pthread_mutex_lock`` / ``pthread_mutex_unlock``
calls, classifying each mutex expression as MAIN (``hdr()->mutex``),
shard (``shards[i].mutex``) or ledger (``ledger_mu``).

Violations:

- MAIN acquired while MAIN, a shard, or the ledger is held (order
  inversion / self-deadlock — these mutexes are not recursive);
- a shard acquired while the ledger is held (order inversion);
- a second shard acquired while one is held (only stop-world may hold
  multiple shards, and its ascending loop releases per lexical scope);
- the ledger acquired while the ledger is held (self-deadlock).

Approximations (documented, deliberate): raw ``lock_robust`` /
``pthread_mutex_lock`` acquisitions are scoped to their enclosing
brace like an RAII guard (this is how every live call site behaves,
and it sanctions the stop-world ascending loop), and calls are not
followed interprocedurally — a helper that takes MAIN internally
documents that contract in a comment, same as the source does today.
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

from ray_tpu.devtools.trace.engine import NativeTraceRule

MAIN, SHARD, LEDGER = "MAIN", "shard", "ledger"

_DECL_RE = re.compile(r"\b(MainLock|ShardLock|LedgerLock)\s+\w+\s*[({]")
_RAW_LOCK_RE = re.compile(
    r"\b(?:lock_robust|pthread_mutex_lock)\s*\(\s*([^()]*(?:\([^()]*\))?"
    r"[^()]*)\)"
)
_UNLOCK_RE = re.compile(
    r"\bpthread_mutex_unlock\s*\(\s*([^()]*(?:\([^()]*\))?[^()]*)\)"
)
_DECL_KIND = {"MainLock": MAIN, "ShardLock": SHARD, "LedgerLock": LEDGER}


def _classify(mutex_expr: str) -> str:
    if "shard" in mutex_expr:
        return SHARD
    if "ledger" in mutex_expr:
        return LEDGER
    return MAIN


def strip_code(source: str) -> str:
    """Blank out comments, string and char literals (preserving line
    structure) so brace/lock scanning never trips on their contents."""
    out = []
    i, n = 0, len(source)
    state = None  # None | "line" | "block" | '"' | "'"
    while i < n:
        c = source[i]
        nxt = source[i + 1] if i + 1 < n else ""
        if state is None:
            if c == "/" and nxt == "/":
                state = "line"
                out.append("  ")
                i += 2
                continue
            if c == "/" and nxt == "*":
                state = "block"
                out.append("  ")
                i += 2
                continue
            if c in "\"'":
                state = c
                out.append(" ")
                i += 1
                continue
            out.append(c)
        elif state == "line":
            if c == "\n":
                state = None
                out.append(c)
            else:
                out.append(" ")
        elif state == "block":
            if c == "*" and nxt == "/":
                state = None
                out.append("  ")
                i += 2
                continue
            out.append(c if c == "\n" else " ")
        else:  # string/char literal
            if c == "\\":
                out.append("  ")
                i += 2
                continue
            if c == state:
                state = None
            out.append(c if c == "\n" else " ")
        i += 1
    return "".join(out)


class _Held:
    __slots__ = ("kind", "depth", "line", "raw")

    def __init__(self, kind: str, depth: int, line: int, raw: bool):
        self.kind = kind
        self.depth = depth
        self.line = line
        self.raw = raw


class NativeLockOrder(NativeTraceRule):
    id = "RT304"
    name = "native-lock-order"
    description = (
        "shm arena lock acquired against the documented MAIN < shard "
        "< ledger order (or re-acquired while already held)"
    )
    hint = (
        "close the inner scope before taking the outer lock; only "
        "StopWorld may hold MAIN plus shards (ascending)"
    )

    def check_native(
        self, path: str, source: str
    ) -> List[Tuple[int, int, str]]:
        clean = strip_code(source)
        findings: List[Tuple[int, int, str]] = []
        held: List[_Held] = []
        depth = 0
        for lineno, line in enumerate(clean.splitlines(), start=1):
            events: List[Tuple[int, str, Optional[str]]] = []
            for m in _DECL_RE.finditer(line):
                events.append(
                    (m.start(), "acquire", _DECL_KIND[m.group(1)])
                )
            for m in _RAW_LOCK_RE.finditer(line):
                # a parameter list ("void lock_robust(pthread_mutex_t*
                # m)") is a definition, not an acquisition
                if "pthread_mutex_t" in m.group(1):
                    continue
                events.append(
                    (m.start(), "raw-acquire", _classify(m.group(1)))
                )
            for m in _UNLOCK_RE.finditer(line):
                events.append((m.start(), "unlock", _classify(m.group(1))))
            for col, ch in enumerate(line):
                if ch == "{":
                    events.append((col, "open", None))
                elif ch == "}":
                    events.append((col, "close", None))
            events.sort(key=lambda e: e[0])
            for col, kind, lock in events:
                if kind == "open":
                    depth += 1
                elif kind == "close":
                    depth -= 1
                    held[:] = [h for h in held if h.depth <= depth]
                elif kind == "unlock":
                    for i in range(len(held) - 1, -1, -1):
                        if held[i].kind == lock and held[i].raw:
                            del held[i]
                            break
                else:
                    msg = self._violation(lock, held)
                    if msg is not None:
                        findings.append((lineno, col + 1, msg))
                    held.append(_Held(
                        lock, depth, lineno, kind == "raw-acquire",
                    ))
        return findings

    def _violation(self, lock: str, held: List[_Held]) -> Optional[str]:
        if not held:
            return None
        if lock == MAIN:
            worst = held[-1]
            return (
                f"MAIN acquired while {worst.kind} (line {worst.line}) "
                f"is held — lock order is MAIN < shard < ledger"
            )
        if lock == SHARD:
            for h in held:
                if h.kind == LEDGER:
                    return (
                        f"shard acquired while ledger (line {h.line}) "
                        f"is held — lock order is MAIN < shard < ledger"
                    )
            for h in held:
                if h.kind == SHARD:
                    return (
                        f"second shard acquired while shard (line "
                        f"{h.line}) is held — only StopWorld may hold "
                        f"multiple shards"
                    )
            return None
        # ledger
        for h in held:
            if h.kind == LEDGER:
                return (
                    f"ledger re-acquired while already held (line "
                    f"{h.line}) — ledger_mu is not recursive"
                )
        return None
