"""RT302: check-then-act on shared attribute state split across an
``await`` — the TOCTOU shape behind the PR 13 drain-fence bugs.

Inside a coroutine, an ``if`` that tests ``self._x`` makes a decision;
any ``await`` inside the guarded body yields the loop, and every other
coroutine (and every ``call_soon_threadsafe`` hand-off) may run and
change ``self._x`` before the body resumes.  Acting on the stale
decision afterwards — rebinding ``self._x`` past the await, or in the
same statement as the await (``self._x = await make()`` under an
``if self._x is None:`` guard, the async double-lazy-init) — is flagged.

Compliant shapes stay silent: re-checking the attribute in a fresh
``if`` after the await, holding an ``async with <lock>`` across the
whole check+act region, and ``while self._x: await ...`` loops (the
loop re-evaluates its test every iteration by construction).
"""

from __future__ import annotations

import ast
from typing import List, Set

from ray_tpu.devtools import astutil
from ray_tpu.devtools.trace.engine import TraceRule


def _self_attr_reads(expr: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(expr):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and isinstance(node.ctx, ast.Load)
        ):
            out.add(node.attr)
    return out


def _iter_preorder(body) -> List[ast.AST]:
    """Preorder walk of a statement list that does not descend into
    nested function/class definitions (separate scopes)."""
    out: List[ast.AST] = []
    stack = list(reversed(list(body)))
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                   ast.Lambda)
        ):
            continue
        stack.extend(reversed(list(ast.iter_child_nodes(node))))
    return out


def _mutates_attr(node: ast.AST, attr: str) -> bool:
    targets: List[ast.AST] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    elif isinstance(node, ast.Delete):
        targets = list(node.targets)
    for t in targets:
        elts = t.elts if isinstance(t, ast.Tuple) else [t]
        for e in elts:
            if (
                isinstance(e, ast.Attribute)
                and isinstance(e.value, ast.Name)
                and e.value.id == "self"
                and e.attr == attr
            ):
                return True
    return False


class AwaitGapToctou(TraceRule):
    id = "RT302"
    name = "await-gap-check-then-act"
    description = (
        "attribute checked before an await and acted on after it — "
        "the loop ran other coroutines in between and the check is "
        "stale"
    )
    hint = (
        "re-check the attribute after the await, or hold an "
        "asyncio.Lock across the whole check-then-act region"
    )

    def check(self, index, planes) -> None:
        for qual in sorted(index.functions):
            fn = index.functions[qual]
            if not fn.is_async:
                continue
            self._scan_stmts(fn, fn.node.body, 0)

    def _scan_stmts(self, fn, body, lock_depth) -> None:
        for stmt in body:
            depth = lock_depth
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                if any(
                    astutil.is_lockish(item.context_expr)
                    for item in stmt.items
                ):
                    depth += 1
            if isinstance(stmt, ast.If) and depth == 0:
                for attr in sorted(_self_attr_reads(stmt.test)):
                    self._check_guard(fn, stmt, attr)
            for field in ("body", "orelse", "finalbody", "handlers"):
                sub = getattr(stmt, field, None)
                if not sub:
                    continue
                if field == "handlers":
                    for h in sub:
                        self._scan_stmts(fn, h.body, depth)
                else:
                    self._scan_stmts(fn, sub, depth)

    def _check_guard(self, fn, if_stmt: ast.If, attr: str) -> None:
        nodes = _iter_preorder(if_stmt.body)
        # regions freshly re-guarded by a nested test of the same attr
        rechecked: Set[int] = set()
        for node in nodes:
            if (
                isinstance(node, (ast.If, ast.While))
                and attr in _self_attr_reads(node.test)
            ):
                rechecked.update(id(sub) for sub in ast.walk(node))
                rechecked.discard(id(node))
        await_seen = False
        for node in nodes:
            if isinstance(node, ast.Await) and id(node) not in rechecked:
                await_seen = True
                continue
            if id(node) in rechecked:
                continue
            if not _mutates_attr(node, attr):
                continue
            gapped = await_seen
            if not gapped and isinstance(node, (ast.Assign, ast.AugAssign)):
                # `self._x = await make()` — the rebind lands after the
                # value's own await completes
                gapped = any(
                    isinstance(sub, ast.Await)
                    for sub in ast.walk(node.value)
                )
            if gapped:
                self.add(
                    fn.module,
                    node,
                    message=(
                        f"`self.{attr}` was checked at line "
                        f"{if_stmt.lineno} but the loop ran between "
                        f"check and act (await in the gap); this "
                        f"rebind acts on a stale read"
                    ),
                )
