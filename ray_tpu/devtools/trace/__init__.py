"""rtrace: the concurrency-analysis tier (RT3xx).

The per-file tier (RT1xx) sees one module; the flow tier (RT2xx) sees
the remote surface.  This third tier sees *threads*: it classifies
every function by execution plane — the rt-io event loop, executor
threads, caller threads entering the sync API — and checks the
hand-off discipline between them, plus the native shm arena's
documented lock order.

- RT301 cross-plane-unlocked-mutation: an attribute rebound from two
  planes with no lock and no ``call_soon_threadsafe`` funnel.
- RT302 await-gap-check-then-act: ``self._x`` checked before an
  ``await`` and acted on after it (the PR 13 drain-fence TOCTOU).
- RT303 oneshot-rebound-under-waiters: an ``asyncio.Event``/``Future``
  attribute replaced while waiters may be parked on the old instance.
- RT304 native-lock-order: a ``MainLock``/``ShardLock``/``LedgerLock``
  scope in ``_native/*.cc`` acquired against MAIN < shard < ledger.

Findings ride the same ``Finding`` type, suppression comments, and
baseline machinery as the other tiers; run everything with::

    python -m ray_tpu.devtools.lint --all ray_tpu
"""

from ray_tpu.devtools.trace.engine import (  # noqa: F401
    DEFAULT_TRACE_BASELINE,
    TraceReport,
    all_trace_rules,
    analyze_paths,
    analyze_sources,
    trace_rule_ids,
)
from ray_tpu.devtools.trace.planes import (  # noqa: F401
    CALLER,
    EXEC,
    LOOP,
    PlaneMap,
    build_planes,
)
