"""RT303: one-shot synchronization object rebound while waiters may be
parked on the old instance.

``asyncio.Event`` / ``Future`` (and their threading/concurrent
equivalents) are waited on BY IDENTITY: a coroutine parked in
``await self._ev.wait()`` holds a reference to the *object*, not the
attribute.  Rebinding ``self._ev = asyncio.Event()`` strands every
parked waiter on the orphaned instance forever — the exact PR 13
round-2 and round-3 stranded-waiter bug, shipped twice.

A finding fires on any ``self.<attr> = <one-shot ctor>`` outside the
``__init__`` family when some method of the same class waits on that
attribute (``await self.<attr>``, ``self.<attr>.wait()``,
``self.<attr>.result()``).  The compliant pattern — one persistent
instance, cycled with ``.set()`` / ``.clear()`` — never rebinds and
stays silent, as does rebinding an attribute nothing ever waits on.
"""

from __future__ import annotations

import ast
from typing import Dict, Set

from ray_tpu.devtools import astutil
from ray_tpu.devtools.trace.engine import TraceRule
from ray_tpu.devtools.trace.planes import CTOR_NAMES

_ONESHOT_TYPES = {
    "asyncio.Event",
    "asyncio.Future",
    "threading.Event",
    "concurrent.futures.Future",
}
_WAIT_METHODS = ("wait", "result")


def _is_oneshot_ctor(module, expr: ast.AST) -> bool:
    if not isinstance(expr, ast.Call):
        return False
    f = expr.func
    if isinstance(f, ast.Attribute) and f.attr == "create_future":
        return True
    resolved = module.resolve(f) or astutil.dotted_text(f) or ""
    if resolved in _ONESHOT_TYPES:
        return True
    return any(resolved.endswith("." + t) for t in _ONESHOT_TYPES)


def _self_attr(node: ast.AST):
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _waited_attrs(cls) -> Set[str]:
    out: Set[str] = set()
    for mname in cls.methods:
        for node in ast.walk(cls.methods[mname].node):
            if isinstance(node, ast.Await):
                attr = _self_attr(node.value)
                if attr is not None:
                    out.add(attr)
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _WAIT_METHODS
            ):
                attr = _self_attr(node.func.value)
                if attr is not None:
                    out.add(attr)
    return out


class OneShotReassign(TraceRule):
    id = "RT303"
    name = "oneshot-rebound-under-waiters"
    description = (
        "one-shot Event/Future attribute rebound outside __init__ "
        "while other code waits on it by identity — parked waiters "
        "stay parked on the orphaned instance forever"
    )
    hint = (
        "keep ONE persistent instance and cycle it with .set()/"
        ".clear(), or resolve the old instance before replacing it"
    )

    def check(self, index, planes) -> None:
        for cqual in sorted(index.classes):
            cls = index.classes[cqual]
            waited = _waited_attrs(cls)
            if not waited:
                continue
            for mname in sorted(cls.methods):
                meth = cls.methods[mname]
                if meth.name in CTOR_NAMES:
                    continue
                self._scan_method(cls, meth, waited)

    def _scan_method(self, cls, meth, waited: Set[str]) -> None:
        for node in ast.walk(meth.node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node is not meth.node and node.name in CTOR_NAMES:
                    continue
            if not isinstance(node, ast.Assign):
                continue
            if not _is_oneshot_ctor(cls.module, node.value):
                continue
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None and attr in waited:
                    self.add(
                        cls.module,
                        node,
                        message=(
                            f"`{cls.name}.{attr}` is waited on by "
                            f"identity elsewhere in the class; "
                            f"rebinding it here strands parked waiters "
                            f"on the old instance"
                        ),
                    )
