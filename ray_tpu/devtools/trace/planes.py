"""Execution-plane classification for the rtrace tier.

The runtime has exactly three kinds of thread a Python frame can run
on (docs/architecture.md "Concurrency model"):

- ``loop``   — the rt-io event loop thread: every coroutine body, every
  ``loop.call_soon`` / ``call_soon_threadsafe`` / ``call_later``
  callback.
- ``exec``   — executor threads: sync actor methods (the worker's
  ``rt-exec`` pool, concurrency-group pools), anything shipped through
  ``run_in_executor`` / ``asyncio.to_thread`` / ``<pool>.submit`` /
  ``threading.Thread(target=...)``, and plain ``@remote`` task bodies.
- ``caller`` — user threads entering the public sync API of a class
  that bridges onto a loop with ``run_coroutine_threadsafe`` (the
  ``Runtime`` facade pattern).

Classification is seeded from those dispatch-site shapes, then
propagated caller -> callee over the sync call graph to a fixpoint, so
a private helper invoked from both a coroutine and an executor-shipped
method is known to run on both planes.  Nested ``def``s handed to a
dispatch primitive get a per-node plane override (they do NOT inherit
the enclosing function's planes); nested defs that are only called
inline inherit the enclosing planes.

An unreached function has no plane and contributes nothing — precision
over recall, same contract as the flow tier.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools import astutil

LOOP = "loop"
EXEC = "exec"
CALLER = "caller"

# method names whose body runs before the object is reachable from any
# other plane (construction happens-before publication)
CTOR_NAMES = frozenset(
    {"__init__", "__new__", "__post_init__", "__init_subclass__"}
)


class PlaneMap:
    """qualname -> plane set, plus per-AST-node overrides for nested
    defs/lambdas that a dispatch primitive ships to a specific plane."""

    def __init__(self) -> None:
        self.planes: Dict[object, Set[str]] = {}
        self.overrides: Dict[ast.AST, str] = {}

    def of(self, key: object) -> Set[str]:
        return self.planes.get(key, set())

    def add(self, key: object, plane: str) -> bool:
        s = self.planes.setdefault(key, set())
        if plane in s:
            return False
        s.add(plane)
        return True


def _uses_bridge(cls_node: ast.ClassDef) -> bool:
    """Does this class hand coroutines to a loop it owns?  That is the
    signature of a caller-thread facade (``Runtime._run``)."""
    for node in ast.walk(cls_node):
        if isinstance(node, ast.Attribute):
            if node.attr == "run_coroutine_threadsafe":
                return True
        elif isinstance(node, ast.Name):
            if node.id == "run_coroutine_threadsafe":
                return True
    return False


_POOLISH = ("exec", "pool", "thread")


def _dispatch_args(call: ast.Call) -> Optional[Tuple[str, List[ast.AST]]]:
    """(plane, [callable exprs]) when ``call`` is a dispatch primitive
    that moves its argument onto a specific plane, else None."""
    f = call.func
    if isinstance(f, ast.Attribute):
        a = f.attr
        if a in ("call_soon", "call_soon_threadsafe"):
            return (LOOP, call.args[:1])
        if a in ("call_later", "call_at"):
            return (LOOP, call.args[1:2])
        if a == "run_in_executor":
            return (EXEC, call.args[1:2])
        if a == "to_thread":
            return (EXEC, call.args[:1])
        if a == "submit":
            recv = astutil.dotted_text(f.value) or ""
            if any(t in recv.lower() for t in _POOLISH):
                return (EXEC, call.args[:1])
            return None
    name = astutil.dotted_text(f) or ""
    if name == "Thread" or name.endswith(".Thread"):
        targets = [kw.value for kw in call.keywords if kw.arg == "target"]
        if targets:
            return (EXEC, targets)
        return None
    if name == "to_thread" or name.endswith(".to_thread"):
        return (EXEC, call.args[:1])
    return None


def _nested_defs_by_name(fn_node: ast.AST) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(fn_node):
        if node is fn_node:
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def _sync_callee(index, fn, expr: ast.AST):
    """Resolve a callable expression to a sync FunctionInfo in the
    index (module function, ``self.<m>``, or method of a typed
    ``self.<attr>`` receiver).  Async targets return None — coroutine
    bodies always run on the loop regardless of who created them."""
    if isinstance(expr, ast.Name):
        dotted = index.resolve_name(fn.module, expr)
        tgt = index.functions.get(dotted) if dotted else None
        if tgt is not None and not tgt.is_async:
            return tgt
        return None
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if (
            isinstance(base, ast.Name)
            and base.id == "self"
            and fn.owner is not None
        ):
            m = fn.owner.methods.get(expr.attr)
            if m is not None and not m.is_async:
                return m
            return None
        recv = index.receiver_type(fn.module, base, None, fn.owner)
        if recv is not None:
            cls = index.classes.get(recv)
            if cls is not None:
                m = cls.methods.get(expr.attr)
                if m is not None and not m.is_async:
                    return m
    return None


def _mark_dispatched(index, fn, expr: ast.AST, plane: str, pm: PlaneMap,
                     nested: Dict[str, ast.AST]) -> None:
    # functools.partial(f, ...) wraps; classify the wrapped callable
    if isinstance(expr, ast.Call):
        nm = astutil.dotted_text(expr.func) or ""
        if nm == "partial" or nm.endswith(".partial"):
            for sub in expr.args[:1]:
                _mark_dispatched(index, fn, sub, plane, pm, nested)
        return
    if isinstance(expr, ast.Lambda):
        pm.overrides[expr] = plane
        return
    if isinstance(expr, ast.Name) and expr.id in nested:
        nd = nested[expr.id]
        if not isinstance(nd, ast.AsyncFunctionDef):
            pm.overrides[nd] = plane
        return
    tgt = _sync_callee(index, fn, expr)
    if tgt is not None:
        pm.add(tgt.qualname, plane)


def _collect_edges(index, fn, pm: PlaneMap, edges: list) -> None:
    """(source key, callee qualname) edges for the sync call graph.
    The source key switches to a pseudo node when descending into a
    nested def that a dispatch primitive placed on a fixed plane."""

    def walk(node: ast.AST, src: object) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ov = pm.overrides.get(child)
                if ov is not None:
                    key = (fn.qualname, child.name, child.lineno)
                    pm.planes.setdefault(key, set()).add(ov)
                    walk(child, key)
                else:
                    walk(child, src)
                continue
            if isinstance(child, ast.Call):
                if _dispatch_args(child) is None:
                    tgt = _sync_callee(index, fn, child.func)
                    if tgt is not None:
                        edges.append((src, tgt.qualname))
            walk(child, src)

    walk(fn.node, fn.qualname)


def build_planes(index) -> PlaneMap:
    pm = PlaneMap()

    # ---- seeds ----------------------------------------------------------
    for qual in sorted(index.functions):
        fn = index.functions[qual]
        if fn.is_async:
            pm.add(qual, LOOP)
        elif fn.is_remote and not fn.name.startswith("_"):
            # public sync actor methods + plain remote task bodies run
            # on a worker executor thread
            pm.add(qual, EXEC)

    for cqual in sorted(index.classes):
        cls = index.classes[cqual]
        if not _uses_bridge(cls.node):
            continue
        for name in sorted(cls.methods):
            meth = cls.methods[name]
            if not name.startswith("_") and not meth.is_async:
                pm.add(meth.qualname, CALLER)

    # ---- dispatch sites (also records nested-def overrides) -------------
    for qual in sorted(index.functions):
        fn = index.functions[qual]
        nested = _nested_defs_by_name(fn.node)
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            hit = _dispatch_args(node)
            if hit is None:
                continue
            plane, exprs = hit
            for expr in exprs:
                _mark_dispatched(index, fn, expr, plane, pm, nested)

    # ---- caller -> callee propagation to fixpoint -----------------------
    edges: List[Tuple[object, str]] = []
    for qual in sorted(index.functions):
        fn = index.functions[qual]
        if fn.is_async:
            # the coroutine body is LOOP; its sync callees inherit LOOP
            # through the edge below, not through an override
            pass
        _collect_edges(index, fn, pm, edges)

    changed = True
    while changed:
        changed = False
        for src, dst in edges:
            dst_fn = index.functions.get(dst)
            if dst_fn is None or dst_fn.is_async:
                continue
            for plane in pm.of(src):
                if pm.add(dst, plane):
                    changed = True
    return pm
