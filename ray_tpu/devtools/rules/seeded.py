"""RT116: unseeded or wall-clock-seeded randomness in replay-critical
code.

The soak plane's whole contract is that a scenario seed replays: the
storm timeline, the fault-plan firings, the arrival schedule, the spot
revocation process — byte-identical scorecards from the same seed.
One call into Python's GLOBAL random module (``random.random()``,
``random.choice(...)``) inside that code breaks the contract silently:
the global RNG is seeded from OS entropy at import and shared with
every library in the process, so the "replayable" log stops replaying
and nobody notices until a storm can't be reproduced under a debugger.
Seeding from the wall clock (``random.Random(time.time())``,
``rng.seed(time.time_ns())``) is the same bug wearing a seed costume.

Scope: ``soak/`` and ``common/faults.py`` (the replay-critical set) —
elsewhere ad-hoc randomness is fine and common.  What fires:

- any call through the global random module or a name imported from
  it (``random.random()``, ``from random import choice; choice(...)``)
  — replayable code must draw from an explicitly-seeded
  ``random.Random(seed)`` instance,
- ``random.Random()`` with no arguments (an unseeded instance is the
  global RNG with extra steps),
- a wall-clock call (``time.time()``, ``time.time_ns()``,
  ``time.monotonic()``) or ``os.urandom`` / ``uuid4`` appearing inside
  the seed argument of ``random.Random(...)`` / ``.seed(...)``, or
  assigned to a name containing ``seed``.

``random.Random(f"{seed}:storm")`` — the derived-substream idiom this
package uses — passes: the argument chain starts from a caller-supplied
seed, not from entropy.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools import astutil
from ray_tpu.devtools.lint import Rule

#: module-level random functions that draw from the GLOBAL RNG
_GLOBAL_DRAWS = {
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "expovariate", "gauss", "normalvariate",
    "betavariate", "triangular", "vonmisesvariate", "paretovariate",
    "weibullvariate", "lognormvariate", "getrandbits", "seed",
}

#: entropy sources that make a seed non-replayable
_ENTROPY_CALLS = {
    ("time", "time"), ("time", "time_ns"), ("time", "monotonic"),
    ("time", "monotonic_ns"), ("time", "perf_counter"),
    ("time", "perf_counter_ns"), ("os", "urandom"), ("uuid", "uuid4"),
    ("secrets", "token_bytes"), ("secrets", "randbits"),
}


def _is_entropy_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        return (fn.value.id, fn.attr) in _ENTROPY_CALLS
    if isinstance(fn, ast.Name):
        return any(fn.id == f for _m, f in _ENTROPY_CALLS
                   if f not in ("time",)) or fn.id == "uuid4"
    return False


def _subtree_has_entropy(node: ast.AST) -> bool:
    return any(_is_entropy_call(sub) for sub in ast.walk(node))


class _SeededVisitor(astutil.ScopedVisitor):
    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        self._random_aliases = {"random"}
        #: bare names bound to global-RNG functions via
        #: ``from random import choice [as pick]``
        self._fn_aliases: dict = {}

    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            if alias.name == "random":
                self._random_aliases.add(alias.asname or alias.name)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module == "random":
            for alias in node.names:
                if alias.name in _GLOBAL_DRAWS:
                    self._fn_aliases[alias.asname or alias.name] = (
                        alias.name
                    )
        self.generic_visit(node)

    # -- classification -------------------------------------------------

    def _global_draw(self, node: ast.Call) -> str:
        """Name of the global-RNG function this call draws from, or ''."""
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if (
                isinstance(fn.value, ast.Name)
                and fn.value.id in self._random_aliases
                and fn.attr in _GLOBAL_DRAWS
            ):
                return f"random.{fn.attr}"
        elif isinstance(fn, ast.Name) and fn.id in self._fn_aliases:
            return f"random.{self._fn_aliases[fn.id]}"
        return ""

    def _is_random_ctor(self, node: ast.Call) -> bool:
        fn = node.func
        return (
            isinstance(fn, ast.Attribute)
            and fn.attr in ("Random", "SystemRandom")
            and isinstance(fn.value, ast.Name)
            and fn.value.id in self._random_aliases
        ) or (isinstance(fn, ast.Name) and fn.id in ("Random",
                                                     "SystemRandom"))

    def visit_Call(self, node: ast.Call):
        draw = self._global_draw(node)
        if draw:
            self.ctx.add(
                self.rule, node,
                message=f"{draw}() draws from the process-global RNG — "
                        "in replay-critical code every draw must come "
                        "from an explicitly seeded random.Random "
                        "instance or the scenario can't replay",
                hint="derive a substream: "
                     "rng = random.Random(f'{seed}:purpose')",
            )
        elif self._is_random_ctor(node):
            if not node.args and not node.keywords:
                self.ctx.add(
                    self.rule, node,
                    message="random.Random() with no seed is OS entropy "
                            "— an unseeded instance cannot replay",
                    hint="pass the scenario seed (or a derived "
                         "substream string) to Random(...)",
                )
            elif any(_subtree_has_entropy(a) for a in node.args) or any(
                _subtree_has_entropy(kw.value) for kw in node.keywords
            ):
                self.ctx.add(
                    self.rule, node,
                    message="seeding an RNG from the clock/entropy is "
                            "unseeded randomness wearing a seed costume "
                            "— the value differs every run",
                    hint="seed from the scenario's seed field, never "
                         "from time.time()/urandom",
                )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "seed"
            and any(_subtree_has_entropy(a) for a in node.args)
        ):
            self.ctx.add(
                self.rule, node,
                message="re-seeding from the clock/entropy makes the "
                        "stream non-replayable",
                hint="seed from the scenario's seed field",
            )
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if (
            any("seed" in n.lower() for n in names)
            and _subtree_has_entropy(node.value)
        ):
            self.ctx.add(
                self.rule, node,
                message="a seed derived from the clock/entropy differs "
                        "every run — the log it stamps can't replay",
                hint="take the seed from the scenario (or config) "
                     "instead of time.time()",
            )
        self.generic_visit(node)


class UnseededRandomness(Rule):
    id = "RT116"
    name = "unseeded-randomness"
    description = (
        "global-RNG draw or wall-clock-derived seed in replay-critical "
        "code (soak/, common/faults.py) — seeded replay is the "
        "contract; one entropy draw silently breaks it"
    )
    hint = (
        "draw from an explicitly seeded random.Random; derive "
        "substreams as random.Random(f'{seed}:purpose')"
    )
    path_markers = ("soak/", "common/faults")
    visitor_cls = _SeededVisitor
