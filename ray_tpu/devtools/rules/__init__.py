"""rtlint rule registry.

Rule ids are stable and grouped by family:

- RT101 blocking-call-in-async     (async_rules)
- RT102 non-atomic-write           (persistence)
- RT103 impure-traced-fn           (traced)
- RT104 nested-blocking-get        (remote_api)
- RT105 unawaited-coroutine        (async_rules)
- RT106 mutable-default-arg        (remote_api)
- RT107 swallowed-cancellation     (async_rules)
- RT108 unlocked-lazy-init         (concurrency)
- RT109 blocking-collective-in-async (async_rules)
- RT110 unpoliced-call-soon-backlog (backlog)
- RT111 unbounded-serve-dispatch    (backlog)
- RT112 unbounded-retry-loop        (retry)
- RT113 half-checkpoint-pair        (checkpoint)
- RT114 wall-clock-liveness         (clock)
- RT115 bytes-copy-on-hot-path      (bytescopy)
- RT116 unseeded-randomness         (seeded)

The RT2xx series (actor-deadlock, objectref-leak, unserializable-
capture, rank-divergent-collective) is the whole-program rtflow tier —
see ``ray_tpu.devtools.flow``; those rules need the cross-module index
and are not registered here.
"""

from ray_tpu.devtools.rules.async_rules import (
    BlockingCallInAsync,
    BlockingCollectiveInAsync,
    SwallowedCancellation,
    UnawaitedCoroutine,
)
from ray_tpu.devtools.rules.backlog import (
    UnboundedServeDispatch,
    UnpolicedCallSoon,
)
from ray_tpu.devtools.rules.bytescopy import BytesCopyOnHotPath
from ray_tpu.devtools.rules.checkpoint import HalfCheckpointPair
from ray_tpu.devtools.rules.clock import WallClockLiveness
from ray_tpu.devtools.rules.concurrency import UnlockedLazyInit
from ray_tpu.devtools.rules.persistence import NonAtomicWrite
from ray_tpu.devtools.rules.remote_api import (
    MutableDefaultArg,
    NestedBlockingGet,
)
from ray_tpu.devtools.rules.retry import UnboundedRetryLoop
from ray_tpu.devtools.rules.seeded import UnseededRandomness
from ray_tpu.devtools.rules.traced import ImpureTracedFn

ALL_RULES = [
    BlockingCallInAsync,
    NonAtomicWrite,
    ImpureTracedFn,
    NestedBlockingGet,
    UnawaitedCoroutine,
    MutableDefaultArg,
    SwallowedCancellation,
    UnlockedLazyInit,
    BlockingCollectiveInAsync,
    UnpolicedCallSoon,
    UnboundedServeDispatch,
    UnboundedRetryLoop,
    HalfCheckpointPair,
    WallClockLiveness,
    BytesCopyOnHotPath,
    UnseededRandomness,
]
