"""RT102: non-atomic checkpoint/sidecar writes.

The persist-race family fixed by hand in the gang-restart hardening PR:
a crash between ``open(path, "w")`` and the final ``write()`` leaves a
truncated file that recovery code then trusts.  Durable state must be
written to a temp sibling and ``os.replace``d into place (see
``workflow/storage.py::_atomic_write`` for the canonical shape).

Scoped to the persistence-bearing trees: ``train/``, ``tune/``,
``workflow/``.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools import astutil
from ray_tpu.devtools.lint import Rule

_ATOMIC_MOVES = ("os.replace", "os.rename", "shutil.move")


def _expr_mentions_tmp(node: ast.AST) -> bool:
    """Does the filename expression visibly route through a temp path
    (`path + ".tmp"`, a `tmp` variable, `tempfile.*`)?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            if "tmp" in sub.value.lower() or "temp" in sub.value.lower():
                return True
        elif isinstance(sub, ast.Name):
            if "tmp" in sub.id.lower() or "temp" in sub.id.lower():
                return True
        elif isinstance(sub, ast.Attribute):
            if "tmp" in sub.attr.lower() or "temp" in sub.attr.lower():
                return True
    return False


class _AtomicWriteVisitor(astutil.ScopedVisitor):
    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx

    def _write_mode(self, call: ast.Call):
        mode = None
        if len(call.args) >= 2:
            mode = call.args[1]
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if (
            isinstance(mode, ast.Constant)
            and isinstance(mode.value, str)
            and "w" in mode.value
        ):
            return mode.value
        return None

    def _enclosing_is_atomic(self) -> bool:
        fn = self.current_function
        if fn is None:
            return False
        if "atomic" in fn.name.lower():
            return True
        return astutil.body_contains_call(
            fn.body, self.ctx.imports, _ATOMIC_MOVES,
            suffixes=("_atomic_write", "atomic_write"),
        )

    def visit_Call(self, node: ast.Call):
        resolved = self.ctx.imports.resolve(node.func)
        if resolved == "open" and node.args:
            mode = self._write_mode(node)
            if mode is not None:
                target = node.args[0]
                if not _expr_mentions_tmp(target) and (
                    not self._enclosing_is_atomic()
                ):
                    self.ctx.add(
                        self.rule, node,
                        message=f"non-atomic write: `open(..., "
                                f"\"{mode}\")` straight to the final "
                                f"path — a crash mid-write leaves a "
                                f"truncated file recovery will trust",
                    )
        self.generic_visit(node)


class NonAtomicWrite(Rule):
    id = "RT102"
    name = "non-atomic-write"
    description = (
        "durable file written in place instead of temp-file + rename"
    )
    hint = (
        "write to `<path>.tmp`, flush+fsync, then `os.replace(tmp, "
        "path)` (see workflow/storage.py::_atomic_write)"
    )
    path_markers = ("train/", "tune/", "workflow/")
    visitor_cls = _AtomicWriteVisitor
