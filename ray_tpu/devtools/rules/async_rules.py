"""Event-loop correctness rules: RT101, RT105, RT107.

The whole runtime shares ONE asyncio loop per process (core/runtime.py
runs it on the rt-io thread; serve replicas and async actors execute on
it directly).  A single blocking call inside an ``async def`` stalls
every in-flight RPC, actor call, and stream on that process — the
deadlock class behind the weak ``actor_calls_async_n_n`` benchmark row.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools import astutil
from ray_tpu.devtools.lint import Rule

# Calls that park the calling thread, resolved through the import map.
_BLOCKING_EXACT = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "ray_tpu.get": "use `await ref` / `await rt.await_ref(ref)`",
    "ray_tpu.wait": "use `asyncio.wait` on awaitables or rt async APIs",
    "socket.create_connection": "use `asyncio.open_connection`",
    "socket.getaddrinfo": "use `loop.getaddrinfo`",
    "subprocess.run": "use `asyncio.create_subprocess_exec`",
    "subprocess.call": "use `asyncio.create_subprocess_exec`",
    "subprocess.check_call": "use `asyncio.create_subprocess_exec`",
    "subprocess.check_output": "use `asyncio.create_subprocess_exec`",
    "os.system": "use `asyncio.create_subprocess_shell`",
}
_BLOCKING_PREFIX = ("requests.", "urllib.request.", "http.client.")

# Receiver names that conventionally hold the Runtime in this codebase:
# `rt.get(refs)` inside an async def round-trips through the very loop
# it is running on — a guaranteed deadlock (runtime.py _run bridges via
# run_coroutine_threadsafe and blocks on fut.result()).
_RUNTIME_RECEIVERS = {"rt"}
_RUNTIME_BLOCKING_ATTRS = {"get", "wait"}


class _BlockingVisitor(astutil.ScopedVisitor):
    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx

    def visit_Call(self, node: ast.Call):
        if self.in_async_function:
            resolved = self.ctx.imports.resolve(node.func)
            if resolved in _BLOCKING_EXACT:
                self.ctx.add(
                    self.rule, node,
                    message=f"blocking call `{resolved}` inside `async "
                            f"def` stalls the shared event loop",
                    hint=_BLOCKING_EXACT[resolved],
                )
            elif resolved is not None and resolved.startswith(
                _BLOCKING_PREFIX
            ):
                self.ctx.add(
                    self.rule, node,
                    message=f"blocking I/O call `{resolved}` inside "
                            f"`async def` stalls the shared event loop",
                )
            elif isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                base = node.func.value
                if attr == "result":
                    self.ctx.add(
                        self.rule, node,
                        message="`.result()` on a future inside `async "
                                "def` blocks the loop the result may "
                                "need to arrive on",
                        hint="await the coroutine/future directly, or "
                             "wrap with `asyncio.wrap_future`",
                    )
                elif (
                    attr in _RUNTIME_BLOCKING_ATTRS
                    and isinstance(base, ast.Name)
                    and base.id in _RUNTIME_RECEIVERS
                ):
                    self.ctx.add(
                        self.rule, node,
                        message=f"blocking runtime call `{base.id}."
                                f"{attr}(...)` inside `async def` "
                                f"deadlocks the io loop it runs on",
                        hint="use `await rt.await_ref(ref)` / the async "
                             "runtime APIs",
                    )
        self.generic_visit(node)


class BlockingCallInAsync(Rule):
    id = "RT101"
    name = "blocking-call-in-async"
    description = (
        "blocking call (sleep / sync get / sync I/O / future.result) "
        "inside an `async def` body"
    )
    hint = "use the asyncio-native equivalent or asyncio.to_thread"
    visitor_cls = _BlockingVisitor


_COLLECTIVE_PKG = "ray_tpu.util.collective"
# the blocking op surface of util.collective; each op has an awaitable
# `<op>_async` twin that is the in-loop-legal spelling
_COLLECTIVE_BLOCKING_OPS = {
    "allreduce",
    "allgather",
    "reducescatter",
    "broadcast",
    "broadcast_object",
    "barrier",
    "send",
    "recv",
}
# lifecycle calls block too but have NO *_async twin: the only legal
# async-context spelling is an executor handoff
_COLLECTIVE_BLOCKING_LIFECYCLE = {
    "init_collective_group",
    "create_collective_group",
    "destroy_collective_group",
}


class _BlockingCollectiveVisitor(astutil.ScopedVisitor):
    """RT109: blocking runtime-collective calls inside ``async def``.

    The sync collective ops bridge into the runtime's io loop and BLOCK
    until peer traffic completes — called from a coroutine they park
    the very loop the chunks must arrive on (best case they stall every
    in-flight RPC on the process; on the loop thread itself they
    deadlock).  Legal spellings from async code: the ``*_async`` twins,
    or an executor handoff (``await asyncio.to_thread(col.allreduce,
    ...)`` — the op is then a function *reference*, not a call, so this
    visitor never sees it)."""

    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx

    def visit_Call(self, node: ast.Call):
        if self.in_async_function:
            resolved = self.ctx.imports.resolve(node.func)
            if resolved is not None and resolved.startswith(
                _COLLECTIVE_PKG + "."
            ):
                op = resolved.rsplit(".", 1)[1]
                if op in _COLLECTIVE_BLOCKING_OPS:
                    self.ctx.add(
                        self.rule, node,
                        message=f"blocking collective op `{op}(...)` "
                                f"inside `async def` parks the io loop "
                                f"its own chunks arrive on",
                        hint=f"`await {op}_async(...)`, or hand the "
                             f"sync op to a thread: `await asyncio."
                             f"to_thread(collective.{op}, ...)`",
                    )
                elif op in _COLLECTIVE_BLOCKING_LIFECYCLE:
                    self.ctx.add(
                        self.rule, node,
                        message=f"blocking collective lifecycle call "
                                f"`{op}(...)` inside `async def` parks "
                                f"the io loop rendezvous rides on",
                        hint=f"hand it to a thread: `await asyncio."
                             f"to_thread(collective.{op}, ...)` "
                             f"(lifecycle calls have no *_async twin)",
                    )
        self.generic_visit(node)


class BlockingCollectiveInAsync(Rule):
    id = "RT109"
    name = "blocking-collective-in-async"
    description = (
        "blocking runtime-collective call (allreduce/send/recv/barrier/"
        "...) inside an `async def` body without await/executor handoff"
    )
    hint = "use the *_async twin or asyncio.to_thread"
    visitor_cls = _BlockingCollectiveVisitor


class _UnawaitedVisitor(astutil.ScopedVisitor):
    """RT105: coroutine called as a bare statement (never awaited — the
    body silently never runs) and `.remote()` calls whose ObjectRef is
    dropped on the floor (task errors become invisible and the result is
    freed under the caller)."""

    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        # name -> True for every `async def` in the file, plus
        # (class, method) pairs for `self.<m>()` resolution
        self.async_names = set()
        self.async_methods = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                self.async_names.add(node.name)
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, ast.AsyncFunctionDef):
                        self.async_methods.add((node.name, item.name))

    def visit_Expr(self, node: ast.Expr):
        call = node.value
        if isinstance(call, ast.Call):
            func = call.func
            if (
                isinstance(func, ast.Name)
                and func.id in self.async_names
            ):
                self.ctx.add(
                    self.rule, node,
                    message=f"coroutine `{func.id}(...)` is never "
                            f"awaited — its body will not run",
                    hint="await it, or schedule it with "
                         "`loop.create_task` and keep the handle",
                )
            elif isinstance(func, ast.Attribute):
                if (
                    isinstance(func.value, ast.Name)
                    and func.value.id == "self"
                    and self.current_class is not None
                    and (self.current_class.name, func.attr)
                    in self.async_methods
                ):
                    self.ctx.add(
                        self.rule, node,
                        message=f"coroutine `self.{func.attr}(...)` is "
                                f"never awaited — its body will not run",
                        hint="await it, or schedule it with "
                             "`loop.create_task` and keep the handle",
                    )
                elif func.attr == "remote":
                    self.ctx.add(
                        self.rule, node,
                        message="`.remote()` result dropped — task "
                                "errors become invisible and the "
                                "ObjectRef is freed immediately",
                        hint="keep the ref (and eventually get/wait "
                             "it), even for fire-and-forget calls",
                    )
        self.generic_visit(node)


class UnawaitedCoroutine(Rule):
    id = "RT105"
    name = "unawaited-coroutine"
    description = "coroutine never awaited or ObjectRef dropped"
    hint = "await the coroutine / keep the ObjectRef"
    visitor_cls = _UnawaitedVisitor


class _CancellationVisitor(astutil.ScopedVisitor):
    """RT107: handlers that eat cancellation/teardown signals on
    supervision paths.  `except BaseException` (or an explicit
    `except asyncio.CancelledError`) without a re-raise converts task
    cancellation into silent success — gang restarts and shutdown paths
    then hang waiting on work that will never finish."""

    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx

    def _handler_names(self, type_node):
        if type_node is None:
            return []
        if isinstance(type_node, ast.Tuple):
            elts = type_node.elts
        else:
            elts = [type_node]
        out = []
        for e in elts:
            resolved = self.ctx.imports.resolve(e)
            if resolved is not None:
                out.append(resolved)
        return out

    def _exception_used(self, node: ast.ExceptHandler) -> bool:
        """The handler binds the exception and the body actually reads
        it (error-reply conversion, ``session.error = e``, ...) — that's
        supervision reporting, not swallowing: the failure stays
        observable somewhere."""
        if node.name is None:
            return False
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Name) and sub.id == node.name:
                    return True
        return False

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        observed = astutil.body_contains_raise(
            node.body
        ) or self._exception_used(node)
        if node.type is None:
            if not observed:
                self.ctx.add(
                    self.rule, node,
                    message="bare `except:` swallows "
                            "CancelledError/SystemExit on this path",
                    hint="catch `Exception` (or the specific errors); "
                         "re-raise BaseException",
                )
        else:
            names = self._handler_names(node.type)
            # NOTE: exact names only — this repo's TaskCancelledError is
            # a task *result* (a remote call was cancelled), and catching
            # it is normal control flow, not swallowed loop cancellation.
            swallowed = [
                n for n in names
                if n in (
                    "BaseException",
                    "CancelledError",
                    "asyncio.CancelledError",
                    "concurrent.futures.CancelledError",
                )
            ]
            if swallowed and not observed:
                self.ctx.add(
                    self.rule, node,
                    message=f"`except {swallowed[0]}` without re-raise "
                            f"swallows cancellation",
                    hint="re-raise after cleanup (`raise`), or narrow "
                         "to `Exception`",
                )
        self.generic_visit(node)


class SwallowedCancellation(Rule):
    id = "RT107"
    name = "swallowed-cancellation"
    description = (
        "bare except / BaseException / CancelledError handler without "
        "re-raise"
    )
    hint = "re-raise cancellation after cleanup, or narrow the handler"
    visitor_cls = _CancellationVisitor
