"""RT112: unbounded retry loop without backoff.

A ``while True:`` loop that wraps a reconnect/retry-shaped call and
whose body shows neither a sleep/backoff reference nor any visible
attempt/deadline bound retries at full speed forever while the peer is
down — the hot-spin shape ``common/backoff.py`` exists to replace
(one dead GCS turns every such site into a busy loop, and a fleet of
them into a reconnect stampede).

Scope, tuned for precision over recall:

- Only constant-true ``while`` loops are candidates; a ``for`` loop or
  a ``while`` with a real condition is already bounded by construction.
- The body must contain a retry-shaped call: a callee whose NAME
  contains a reconnect/retry marker (``connect``, ``retry``,
  ``redial``, ``resubscribe``), or an rpc verb —
  ``.call("<method>", ...)`` / ``.notify("<method>", ...)`` whose
  method string names a retried control-plane operation (``lease``,
  ``pull``, ``connect``, ``subscribe``, ``register``, ``fetch``,
  ``kv_get``).
- Compliance: the body references a sleep (``time.sleep`` /
  ``asyncio.sleep`` / any ``.sleep``), anything whose identifier
  contains ``backoff``, or a visible bound — an identifier containing
  ``deadline``, ``attempt``, ``retries``, ``tries``, or ``budget``.

Sites that police their bound elsewhere (a helper owning the backoff)
should name it locally or carry a justified ``rtlint: disable=RT112``.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools import astutil
from ray_tpu.devtools.lint import Rule

# callee-name substrings that mean "this call dials/retries something"
_RETRY_CALL_MARKERS = ("connect", "redial", "retry", "resubscribe")

# method-string markers for the `.call("<method>", ...)` rpc shape
_RETRY_RPC_MARKERS = (
    "connect", "lease", "pull", "subscribe", "register", "fetch", "kv_get",
)

# identifier substrings that count as a bound or a pacing mechanism
_BOUND_MARKERS = ("backoff", "deadline", "attempt", "retries", "tries",
                  "budget")


def _callee_name(func: ast.AST) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _is_retry_call(node: ast.Call) -> bool:
    name = _callee_name(node.func).lower()
    if any(m in name for m in _RETRY_CALL_MARKERS):
        return True
    if name in ("call", "notify") and node.args:
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            method = first.value.lower()
            return any(m in method for m in _RETRY_RPC_MARKERS)
    return False


def _loop_has_retry_call(node: ast.While) -> bool:
    for stmt in node.body:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call) and _is_retry_call(sub):
                return True
    return False


def _loop_shows_bound_or_backoff(node: ast.While) -> bool:
    for stmt in node.body:
        for sub in ast.walk(stmt):
            ident = ""
            if isinstance(sub, ast.Name):
                ident = sub.id
            elif isinstance(sub, ast.Attribute):
                ident = sub.attr
            if not ident:
                continue
            low = ident.lower()
            if low == "sleep":
                return True
            if any(m in low for m in _BOUND_MARKERS):
                return True
    return False


class _RetryLoopVisitor(astutil.ScopedVisitor):
    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx

    def visit_While(self, node: ast.While):
        test = node.test
        if (
            isinstance(test, ast.Constant)
            and test.value
            and _loop_has_retry_call(node)
            and not _loop_shows_bound_or_backoff(node)
        ):
            self.ctx.add(
                self.rule, node,
                message="`while True:` retry loop with neither backoff "
                        "nor a visible attempt/deadline bound — a dead "
                        "peer turns this into a hot spin (and a fleet of "
                        "them into a reconnect stampede)",
                hint="pace it with common/backoff.py (Backoff.wait() "
                     "against a deadline or max_attempts), or make the "
                     "bound visible in the loop (attempt counter, "
                     "deadline check)",
            )
        self.generic_visit(node)


class UnboundedRetryLoop(Rule):
    id = "RT112"
    name = "unbounded-retry-loop"
    description = (
        "constant-true retry loop wrapping a reconnect/retry-shaped "
        "call with no sleep/backoff reference and no visible attempt "
        "or deadline bound in its body"
    )
    hint = (
        "use common/backoff.py's Backoff (deadline- or attempt-bounded, "
        "jittered) instead of hand-rolled hot retries"
    )
    visitor_cls = _RetryLoopVisitor
