"""RT114: wall-clock arithmetic deciding a liveness verdict.

``time.time()`` is NTP-disciplined: it steps — backward on slew
corrections, forward after a suspend, by whole seconds when a host's
clock is wrong at boot.  Liveness math (heartbeat ages, death
timeouts, drain deadlines) built on it turns every clock step into a
mass false-positive: one NTP correction on the GCS host and the whole
fleet's ``now - last_heartbeat`` jumps past ``node_death_timeout_s``
at once — the exact detection-storm the adaptive health plane exists
to prevent.  Liveness intervals must ride ``time.monotonic()``.

Scope, tuned for precision over recall:

- Only ``Compare`` expressions are candidates (a verdict is a
  comparison; plain wall-clock *timestamps* — logging, ``started_at``
  bookkeeping — are legal and common).
- The comparison's subtree must contain the wall clock: a direct
  ``time.time()`` call (module-attribute or ``from time import time``
  alias form), or a local name assigned from one in the same function
  (the idiomatic ``now = time.time()`` ... ``now - last > timeout``
  shape).  Reassigning the name from another source clears it.
- AND the subtree must reference a liveness-marked name: an identifier
  or attribute containing ``heartbeat``, ``timeout``, ``deadline``,
  ``expire``, ``liveness``, or ``ttl`` (config knobs like
  ``cfg.node_death_timeout_s`` and locals like ``drain_deadline``
  both match).

Wall-clock comparisons against *calendar* quantities (cron schedules,
certificate expiry dates parsed from wall time) are rare in this tree;
carry a justified ``rtlint: disable=RT114`` where one is real.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools import astutil
from ray_tpu.devtools.lint import Rule

#: identifier substrings that mark a liveness/deadline quantity
_LIVENESS_MARKERS = (
    "heartbeat", "timeout", "deadline", "expire", "liveness", "ttl",
)


def _is_wall_clock_call(node: ast.AST, time_aliases: set) -> bool:
    """``time.time()`` / ``<alias>.time()`` attribute form, or a bare
    ``time()`` call whose name was imported from the time module."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return (
            fn.attr == "time"
            and isinstance(fn.value, ast.Name)
            and fn.value.id in time_aliases
        )
    if isinstance(fn, ast.Name):
        return fn.id in time_aliases and fn.id != "time_module"
    return False


def _subtree_has_wall_clock(node: ast.AST, time_aliases: set,
                            wall_names: set) -> bool:
    for sub in ast.walk(node):
        if _is_wall_clock_call(sub, time_aliases):
            return True
        if isinstance(sub, ast.Name) and sub.id in wall_names:
            return True
    return False


def _subtree_has_liveness_name(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        ident = ""
        if isinstance(sub, ast.Name):
            ident = sub.id
        elif isinstance(sub, ast.Attribute):
            ident = sub.attr
        if ident:
            low = ident.lower()
            if any(m in low for m in _LIVENESS_MARKERS):
                return True
    return False


class _ClockVisitor(astutil.ScopedVisitor):
    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        # module aliases that resolve to the time module, and bare names
        # bound to time.time via `from time import time [as t]`
        self._module_aliases = {"time"}
        self._fn_aliases: set = set()
        # per-scope names assigned from a wall-clock call
        # (`now = time.time()`); innermost scope last
        self._wall_scopes: list = [set()]

    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            if alias.name == "time":
                self._module_aliases.add(alias.asname or alias.name)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module == "time":
            for alias in node.names:
                if alias.name == "time":
                    self._fn_aliases.add(alias.asname or alias.name)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self._wall_scopes.append(set())
        super().visit_FunctionDef(node)
        self._wall_scopes.pop()

    def visit_AsyncFunctionDef(self, node):
        self._wall_scopes.append(set())
        super().visit_AsyncFunctionDef(node)
        self._wall_scopes.pop()

    def visit_Assign(self, node: ast.Assign):
        aliases = self._module_aliases | self._fn_aliases
        names = [
            t.id for t in node.targets if isinstance(t, ast.Name)
        ]
        if names:
            scope = self._wall_scopes[-1]
            if _is_wall_clock_call(node.value, aliases):
                scope.update(names)
            else:
                # reassigned from something else (e.g. time.monotonic):
                # the name no longer carries wall-clock taint
                scope.difference_update(names)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare):
        aliases = self._module_aliases | self._fn_aliases
        wall_names = set().union(*self._wall_scopes)
        if _subtree_has_wall_clock(node, aliases, wall_names) and (
            _subtree_has_liveness_name(node)
        ):
            self.ctx.add(
                self.rule, node,
                message="wall-clock time.time() arithmetic compared "
                        "against a heartbeat/timeout/deadline quantity "
                        "— one NTP step turns this into a mass false "
                        "liveness verdict",
                hint="use time.monotonic() for liveness intervals; "
                     "time.time() is for human-facing timestamps only",
            )
        self.generic_visit(node)


class WallClockLiveness(Rule):
    id = "RT114"
    name = "wall-clock-liveness"
    description = (
        "time.time() arithmetic compared against a heartbeat/timeout/"
        "deadline value — liveness verdicts must ride time.monotonic() "
        "(an NTP step would mass-trigger false deaths)"
    )
    hint = (
        "compute liveness intervals from time.monotonic(); keep "
        "time.time() for human-facing timestamps"
    )
    visitor_cls = _ClockVisitor
