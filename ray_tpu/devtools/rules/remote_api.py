"""Remote-surface rules: RT104 (nested blocking get) and RT106
(mutable default arguments on remote functions / actor classes).

RT104: a remote function or actor method that calls ``ray_tpu.get()``
occupies its leased worker while waiting on a task that may need that
same worker — the nested-get deadlock (reference: Ray's long-standing
"don't block in tasks" guidance; this runtime's leases make it a hard
hang once the pool saturates).

RT106: a remote function's defaults are captured ONCE when the function
is exported (cloudpickled); a mutable default then aliases one object
across every execution on a worker — cross-call state leakage that only
shows up under load.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools import astutil
from ray_tpu.devtools.lint import Rule

_BLOCKING_GET = {"ray_tpu.get", "ray_tpu.wait"}
_RUNTIME_RECEIVERS = {"rt"}


class _NestedGetVisitor(astutil.ScopedVisitor):
    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        self.remote_stack = []

    def _in_remote_body(self) -> bool:
        return bool(self.remote_stack) and self.remote_stack[-1]

    def enter_function(self, node):
        remote = astutil.is_remote_decorated(node, self.ctx.imports)
        if (
            not remote
            and self.current_class is not None
            and len(self.func_stack) == 1
            and astutil.is_remote_decorated(
                self.current_class, self.ctx.imports
            )
        ):
            remote = True  # actor method
        self.remote_stack.append(
            remote or bool(self.remote_stack and self.remote_stack[-1])
        )

    def visit_FunctionDef(self, node):
        super().visit_FunctionDef(node)
        self.remote_stack.pop()

    def visit_AsyncFunctionDef(self, node):
        super().visit_AsyncFunctionDef(node)
        self.remote_stack.pop()

    def _has_bounded_timeout(self, node: ast.Call) -> bool:
        """An explicit non-None ``timeout=`` bounds the wait: the call
        degrades to latency instead of deadlock, which is the documented
        pattern for supervision actors (serve controller health probes,
        route polls).  ``timeout=None`` spelled out still flags."""
        for kw in node.keywords:
            if kw.arg == "timeout":
                return not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is None
                )
        return False

    def visit_Call(self, node: ast.Call):
        if self._in_remote_body() and not self._has_bounded_timeout(node):
            resolved = self.ctx.imports.resolve(node.func)
            flagged = resolved in _BLOCKING_GET
            if (
                not flagged
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("get", "wait")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in _RUNTIME_RECEIVERS
            ):
                flagged = True
                resolved = (
                    f"{node.func.value.id}.{node.func.attr}"
                )
            if flagged:
                self.ctx.add(
                    self.rule, node,
                    message=f"blocking `{resolved}(...)` inside a "
                            f"remote function / actor method holds the "
                            f"leased worker while waiting — nested-get "
                            f"deadlock once the pool saturates",
                )
        self.generic_visit(node)


class NestedBlockingGet(Rule):
    id = "RT104"
    name = "nested-blocking-get"
    description = (
        "ray_tpu.get()/wait() inside a remote function or actor method"
    )
    hint = (
        "pass ObjectRefs through as arguments (the scheduler resolves "
        "them before dispatch), or make the actor async and await"
    )
    visitor_cls = _NestedGetVisitor


_MUTABLE_CTORS = {"dict", "list", "set", "collections.defaultdict",
                  "collections.OrderedDict", "collections.deque"}


def _is_mutable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        resolved = astutil.dotted_text(node.func)
        return resolved in _MUTABLE_CTORS
    return False


class _MutableDefaultVisitor(astutil.ScopedVisitor):
    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx

    def enter_function(self, node):
        remote = astutil.is_remote_decorated(node, self.ctx.imports)
        if (
            not remote
            and self.current_class is not None
            and len(self.func_stack) == 1
        ):
            remote = astutil.is_remote_decorated(
                self.current_class, self.ctx.imports
            )
        if not remote:
            return
        args = node.args
        defaults = list(args.defaults) + [
            d for d in args.kw_defaults if d is not None
        ]
        for d in defaults:
            if _is_mutable_default(d):
                self.ctx.add(
                    self.rule, d,
                    message=f"mutable default on remote "
                            f"`{node.name}(...)` is captured once at "
                            f"export and shared across every execution "
                            f"on a worker",
                )


class MutableDefaultArg(Rule):
    id = "RT106"
    name = "mutable-default-arg"
    description = (
        "mutable default argument on a remote function or actor method"
    )
    hint = "default to None and construct inside the body"
    visitor_cls = _MutableDefaultVisitor
