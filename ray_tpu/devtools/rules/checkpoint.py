"""RT113: half-implemented actor checkpoint hook pair.

The graceful-drain plane migrates an actor's state off a preempted node
only when the class implements BOTH ``__rt_checkpoint__`` and
``__rt_restore__`` (worker_main.handle_checkpoint_actor treats a half
pair as unsupported).  A class defining exactly one of the two *looks*
migration-capable but silently degrades to a fresh restart — state loss
that surfaces only during an actual preemption, which is exactly when
nobody is watching.

Scope: any class definition carrying exactly one hook of the pair
(plain ``def``/``async def`` or a class-level assignment to the hook
name).  The hook names are runtime-specific, so false positives outside
actor classes are implausible.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools import astutil
from ray_tpu.devtools.lint import Rule

_HOOKS = ("__rt_checkpoint__", "__rt_restore__")


def _class_hook_names(node: ast.ClassDef) -> set:
    found = set()
    for stmt in node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if stmt.name in _HOOKS:
                found.add(stmt.name)
        elif isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name) and tgt.id in _HOOKS:
                    found.add(tgt.id)
    return found


class _CheckpointPairVisitor(astutil.ScopedVisitor):
    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx

    def visit_ClassDef(self, node: ast.ClassDef):
        found = _class_hook_names(node)
        if len(found) == 1:
            have = next(iter(found))
            missing = next(h for h in _HOOKS if h != have)
            self.ctx.add(
                self.rule, node,
                message=(
                    f"class {node.name} defines {have} without {missing}: "
                    f"the drain plane treats a half pair as "
                    f"not-checkpointable and the actor silently migrates "
                    f"FRESH (state lost) on node preemption"
                ),
                hint=f"implement {missing} (the pair is all-or-nothing), "
                     f"or drop {have} if fresh restarts are intended",
            )
        self.generic_visit(node)


class HalfCheckpointPair(Rule):
    id = "RT113"
    name = "half-checkpoint-pair"
    description = (
        "class defines exactly one of __rt_checkpoint__/__rt_restore__ — "
        "drain migration silently degrades to a fresh restart"
    )
    hint = (
        "implement both hooks (state handoff) or neither (explicit "
        "fresh-restart semantics)"
    )
    visitor_cls = _CheckpointPairVisitor
