"""RT115: intermediate bytes materialization on a put/send hot path.

The data plane's put path is single-pass by construction: serialization
collects zero-copy views (pickle5 out-of-band buffers, ``getbuffer()``
scratch) and ``write_into`` memcpys each exactly once into the arena
reservation.  A ``bytes(<memoryview>)`` or ``b"".join(...)`` inside that
path silently reintroduces the second pass the data-plane-v2 rebuild
removed — every payload byte is touched twice and the put roofline halves
(BENCH.md put-bandwidth roofline).  The fix is vectored segment writes:
hand the views to ``SerializedObject.write_into`` / ``ShmStore.
put_vectored`` instead of concatenating.

Scope, tuned for precision over recall:

- Only functions *reachable from a put/send seed* are candidates —
  reachability is a module-local call graph (callee names resolved
  against functions defined in the same file) rooted at: ``put``,
  ``put_vectored``, ``reserve``, ``commit``, ``_write_to_store``,
  ``write_into``, ``serialize``, ``serialize_small``, and — in
  collective modules (path contains ``collective``) — any function
  whose name contains ``send``, ``allreduce``, ``allgather``,
  ``reducescatter``, or ``broadcast``.
- Flagged shapes inside a hot function:
  * ``b"".join(...)`` (and ``bytes().join(...)``) — the classic
    concatenating materializer;
  * ``bytes(X)`` where ``X`` is memoryview-tainted: a direct
    ``memoryview(...)`` / ``.cast(...)`` / ``.getbuffer()`` /
    ``.toreadonly()`` / ``.raw()`` call, a local name assigned from one
    (reassignment from another source clears the taint), or an
    attribute named ``view`` (the PinnedBuffer payload convention).
- ``bytes(object_id)`` / ``bytes(n)`` and read-path copies in functions
  not reachable from a seed are legal; a deliberate hot-path copy-out
  (e.g. releasing a pin early) carries a justified
  ``rtlint: disable=RT115``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from ray_tpu.devtools.lint import Rule

#: function names that root the put-path reachability walk
_SEED_NAMES = frozenset((
    "put", "put_vectored", "reserve", "commit", "_write_to_store",
    "write_into", "serialize", "serialize_small",
))

#: extra seed-name substrings armed only in collective modules
_COLLECTIVE_SEED_MARKERS = (
    "send", "allreduce", "allgather", "reducescatter", "broadcast",
)

#: attribute/callee names whose call result is a memoryview
_VIEW_PRODUCERS = frozenset((
    "memoryview", "cast", "getbuffer", "toreadonly", "raw",
))

#: attribute names conventionally holding a memoryview payload
_VIEW_ATTRS = frozenset(("view",))


def _callee_name(func: ast.AST) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _graph_callee_name(func: ast.AST) -> str:
    """Callee name for the reachability graph.  Attribute calls only
    count on a ``self`` receiver — ``d.get(...)`` / ``fut.cancel(...)``
    on arbitrary objects would alias into same-named methods of the
    module and wire the whole file together."""
    if isinstance(func, ast.Name):
        return func.id
    if (
        isinstance(func, ast.Attribute)
        and isinstance(func.value, ast.Name)
        and func.value.id == "self"
    ):
        return func.attr
    return ""


def _is_view_producer_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and _callee_name(node.func) in _VIEW_PRODUCERS
    )


def _is_empty_bytes(node: ast.AST) -> bool:
    """``b""`` literal or ``bytes()`` call."""
    if isinstance(node, ast.Constant) and node.value == b"":
        return True
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "bytes"
        and not node.args
    )


class _FnInfo:
    __slots__ = ("name", "node", "callees")

    def __init__(self, name: str, node: ast.AST):
        self.name = name
        self.node = node
        self.callees: Set[str] = set()


def _collect_functions(tree: ast.AST) -> List[_FnInfo]:
    """Every function/method in the module with the set of names it
    calls (simple callee-name resolution; precision is fine for the
    intra-module reachability this rule needs)."""
    out: List[_FnInfo] = []

    class V(ast.NodeVisitor):
        def _fn(self, node):
            info = _FnInfo(node.name, node)
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    name = _graph_callee_name(sub.func)
                    if name:
                        info.callees.add(name)
            out.append(info)
            # nested defs are collected too (walk continues via generic)
            self.generic_visit(node)

        visit_FunctionDef = _fn
        visit_AsyncFunctionDef = _fn

    V().visit(tree)
    return out


def _reachable_functions(fns: List[_FnInfo], path: str) -> List[_FnInfo]:
    by_name: Dict[str, List[_FnInfo]] = {}
    for f in fns:
        by_name.setdefault(f.name, []).append(f)
    seeds = set(_SEED_NAMES)
    if "collective" in path:
        for f in fns:
            low = f.name.lower()
            if any(m in low for m in _COLLECTIVE_SEED_MARKERS):
                seeds.add(f.name)
    work = [f for f in fns if f.name in seeds]
    hot: Set[int] = set()
    hot_names: Set[str] = set()
    while work:
        f = work.pop()
        if id(f) in hot:
            continue
        hot.add(id(f))
        hot_names.add(f.name)
        for callee in f.callees:
            if callee in by_name and callee not in hot_names:
                work.extend(by_name[callee])
    return [f for f in fns if id(f) in hot]


class BytesCopyOnHotPath(Rule):
    id = "RT115"
    name = "bytes-copy-on-hot-path"
    description = (
        "bytes(<memoryview>) / b\"\".join materialization inside a "
        "function reachable from the put/_write_to_store/collective-send "
        "path — reintroduces the second payload pass the vectored data "
        "plane removed"
    )
    hint = (
        "write segments directly into the reserved buffer "
        "(SerializedObject.write_into / ShmStore.put_vectored) instead "
        "of concatenating into an intermediate bytes"
    )

    def check(self, ctx) -> None:
        fns = _collect_functions(ctx.tree)
        for f in _reachable_functions(fns, ctx.path):
            self._scan_function(ctx, f.node)

    def _scan_function(self, ctx, fn_node) -> None:
        tainted: Set[str] = set()
        # parameters annotated as memoryview carry taint in
        args = getattr(fn_node, "args", None)
        if args is not None:
            for a in list(args.args) + list(args.kwonlyargs):
                ann = a.annotation
                if isinstance(ann, ast.Name) and ann.id == "memoryview":
                    tainted.add(a.arg)

        def is_tainted(expr: ast.AST) -> bool:
            if _is_view_producer_call(expr):
                return True
            if isinstance(expr, ast.Name) and expr.id in tainted:
                return True
            if (
                isinstance(expr, ast.Attribute)
                and expr.attr in _VIEW_ATTRS
            ):
                return True
            return False

        rule = self

        def check_call(sub: ast.Call) -> None:
            func = sub.func
            # b"".join(...) / bytes().join(...)
            if (
                isinstance(func, ast.Attribute)
                and func.attr == "join"
                and _is_empty_bytes(func.value)
            ):
                ctx.add(
                    rule, sub,
                    message="b\"\".join materializes an intermediate "
                            "bytes on the put/send hot path (second "
                            "pass over every payload byte)",
                    hint=rule.hint,
                )
            # bytes(<memoryview-tainted>)
            elif (
                isinstance(func, ast.Name)
                and func.id == "bytes"
                and len(sub.args) == 1
                and is_tainted(sub.args[0])
            ):
                ctx.add(
                    rule, sub,
                    message="bytes(<memoryview>) copies the payload "
                            "on the put/send hot path — the vectored "
                            "plane writes views straight into the "
                            "destination",
                    hint=rule.hint,
                )

        def visit(node: ast.AST) -> None:
            # statement-ordered traversal of THIS function only: taint
            # assignments apply in program order, and nested defs are
            # scanned separately iff themselves reachable from a seed
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)
                ):
                    continue
                if isinstance(child, ast.Assign):
                    names = [
                        t.id for t in child.targets
                        if isinstance(t, ast.Name)
                    ]
                    if names:
                        if is_tainted(child.value):
                            tainted.update(names)
                        else:
                            tainted.difference_update(names)
                if isinstance(child, ast.Call):
                    check_call(child)
                visit(child)

        visit(fn_node)
