"""RT103: host-side impurity inside jit/pjit/shard_map-traced functions.

A traced function runs ONCE at trace time; `time.time()`, `np.random`,
`.item()` and friends bake a single host value into the compiled
program (or silently force a device sync), so every later step reuses
the trace-time value — the classic "my noise is identical every step"
bug.  Scoped to the compiled-model trees: ``models/``, ``ops/``,
``parallel/``, ``train/``.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools import astutil
from ray_tpu.devtools.lint import Rule

_JIT_EXACT = {
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
}
_JIT_SUFFIX = ("shard_map",)

_IMPURE_EXACT = {
    "time.time": "thread a step counter / use jax.lax primitives",
    "time.monotonic": "time outside the traced function",
    "time.perf_counter": "time outside the traced function",
    "time.time_ns": "time outside the traced function",
    "datetime.datetime.now": "timestamp outside the traced function",
    "jax.device_get": "return the array; transfer outside the trace",
    "print": "use `jax.debug.print` (runs per-execution, not per-trace)",
}
_IMPURE_PREFIX = ("numpy.random.", "random.")
_IMPURE_ATTRS = {
    "item": "forces a device sync and bakes in the trace-time value",
    "block_until_ready": "host sync inside a trace is a no-op footgun",
}


def _is_jit_name(resolved) -> bool:
    if resolved is None:
        return False
    return resolved in _JIT_EXACT or resolved.endswith(_JIT_SUFFIX)


class _TracedVisitor(astutil.ScopedVisitor):
    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        # functions wrapped by assignment (`step = jax.jit(train_step)`)
        # or passed straight into a jit call anywhere in the module
        self.jitted_names = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _is_jit_name(
                ctx.imports.resolve(node.func)
            ):
                if node.args and isinstance(node.args[0], ast.Name):
                    self.jitted_names.add(node.args[0].id)
                for kw in node.keywords:
                    if kw.arg in ("fun", "f") and isinstance(
                        kw.value, ast.Name
                    ):
                        self.jitted_names.add(kw.value.id)
        self.traced_stack = []

    def _is_traced_def(self, node) -> bool:
        if node.name in self.jitted_names:
            return True
        for resolved, dec in astutil.resolved_decorators(
            node, self.ctx.imports
        ):
            if _is_jit_name(resolved):
                return True
            # @partial(jax.jit, static_argnums=...) / @partial(shard_map, ...)
            if resolved in ("functools.partial", "partial") and isinstance(
                dec, ast.Call
            ) and dec.args:
                inner = self.ctx.imports.resolve(dec.args[0])
                if _is_jit_name(inner):
                    return True
        return False

    def enter_function(self, node):
        # a def nested inside a traced function is traced with it
        traced = self._is_traced_def(node) or bool(
            self.traced_stack and self.traced_stack[-1]
        )
        self.traced_stack.append(traced)

    def visit_FunctionDef(self, node):
        super().visit_FunctionDef(node)
        self.traced_stack.pop()

    def visit_AsyncFunctionDef(self, node):
        super().visit_AsyncFunctionDef(node)
        self.traced_stack.pop()

    @property
    def in_traced(self) -> bool:
        return bool(self.traced_stack) and self.traced_stack[-1]

    def visit_Call(self, node: ast.Call):
        if self.in_traced:
            resolved = self.ctx.imports.resolve(node.func)
            if resolved in _IMPURE_EXACT:
                self.ctx.add(
                    self.rule, node,
                    message=f"host-side `{resolved}` inside a traced "
                            f"function runs once at trace time, not "
                            f"per step",
                    hint=_IMPURE_EXACT[resolved],
                )
            elif resolved is not None and resolved.startswith(
                _IMPURE_PREFIX
            ):
                self.ctx.add(
                    self.rule, node,
                    message=f"host RNG `{resolved}` inside a traced "
                            f"function is frozen at trace time",
                    hint="use `jax.random` with an explicit threaded key",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _IMPURE_ATTRS
                and not node.args
            ):
                self.ctx.add(
                    self.rule, node,
                    message=f"`.{node.func.attr}()` inside a traced "
                            f"function: "
                            f"{_IMPURE_ATTRS[node.func.attr]}",
                    hint="keep values on-device inside the trace",
                )
        self.generic_visit(node)


class ImpureTracedFn(Rule):
    id = "RT103"
    name = "impure-traced-fn"
    description = (
        "host-side impurity (wall clock / host RNG / device sync) "
        "inside a jit/pjit/shard_map-traced function"
    )
    hint = "traced code must be pure; move host effects outside the trace"
    path_markers = ("models/", "ops/", "parallel/", "train/")
    visitor_cls = _TracedVisitor
