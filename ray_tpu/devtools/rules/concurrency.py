"""RT108: thread-unsafe lazy init (check-then-set without a lock).

Scoped to the two files where caller threads, the rt-io loop thread,
and worker executor threads all touch shared state:
``core/runtime.py`` and ``core/gcs.py``.  Two arms:

- a function that declares ``global X`` and does ``if X is None: X =
  ...`` outside any ``with <lock>`` — two threads race the init and one
  of the two constructed objects leaks half-initialized;
- ``if self._x is None: self._x = ...`` outside a lock in a class that
  OWNS a ``threading.Lock/RLock/Condition`` (i.e. a class that has
  already admitted it is shared across threads).
"""

from __future__ import annotations

import ast

from ray_tpu.devtools import astutil
from ray_tpu.devtools.lint import Rule

_LOCK_CTORS = (
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
)


def _lazy_check_target(test: ast.AST):
    """The checked expression for `if X is None:` / `if not X:` shapes,
    else None."""
    if (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and isinstance(test.ops[0], ast.Is)
        and isinstance(test.comparators[0], ast.Constant)
        and test.comparators[0].value is None
    ):
        return test.left
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return test.operand
    return None


def _is_assign_to(node: ast.AST, target_text: str) -> bool:
    if isinstance(node, ast.Assign):
        return any(
            astutil.dotted_text(t) == target_text for t in node.targets
        )
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        return astutil.dotted_text(node.target) == target_text
    return False


def _assigns_target_unlocked(body, target_text: str) -> bool:
    """Any assignment to ``target_text`` in the statement list that is
    NOT under a lock-ish ``with``?  Assignments inside ``with <lock>:``
    don't count — ``if X is None: with lock: if X is None: X = ...`` is
    the canonical double-checked pattern this rule's hint recommends,
    and must stay silent."""
    for stmt in body:
        if _is_assign_to(stmt, target_text):
            return True
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            if any(astutil.is_lockish(i.context_expr) for i in stmt.items):
                continue  # locked subtree: compliant by definition
            if _assigns_target_unlocked(stmt.body, target_text):
                return True
        elif isinstance(stmt, ast.If):
            if _assigns_target_unlocked(
                stmt.body, target_text
            ) or _assigns_target_unlocked(stmt.orelse, target_text):
                return True
        elif isinstance(stmt, ast.Try):
            for sub in (
                stmt.body, stmt.orelse, stmt.finalbody,
                *[h.body for h in stmt.handlers],
            ):
                if _assigns_target_unlocked(sub, target_text):
                    return True
        elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            if _assigns_target_unlocked(
                stmt.body, target_text
            ) or _assigns_target_unlocked(stmt.orelse, target_text):
                return True
    return False


class _LazyInitVisitor(astutil.ScopedVisitor):
    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        # classes that construct a threading lock anywhere in their body
        self.lock_owning_classes = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) and ctx.imports.resolve(
                        sub.func
                    ) in _LOCK_CTORS:
                        self.lock_owning_classes.add(node.name)
                        break

    def _globals_declared(self):
        fn = self.current_function
        if fn is None:
            return set()
        names = set()
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Global):
                names.update(stmt.names)
        return names

    def visit_If(self, node: ast.If):
        if not self.lock_held:
            target = _lazy_check_target(node.test)
            if target is not None:
                text = astutil.dotted_text(target)
                if text is not None and _assigns_target_unlocked(
                    node.body, text
                ):
                    self._classify(node, target, text)
        self.generic_visit(node)

    def _classify(self, node, target, text):
        if isinstance(target, ast.Name):
            if target.id in self._globals_declared():
                self.ctx.add(
                    self.rule, node,
                    message=f"check-then-set on module global "
                            f"`{text}` without holding a lock — "
                            f"concurrent initializers race",
                )
        elif (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and self.current_class is not None
            and self.current_class.name in self.lock_owning_classes
            and self.current_function is not None
            and self.current_function.name != "__init__"
        ):
            self.ctx.add(
                self.rule, node,
                message=f"check-then-set on `{text}` without a lock in "
                        f"a class that owns one — if this state is "
                        f"reachable from more than one thread the init "
                        f"races",
            )


class UnlockedLazyInit(Rule):
    id = "RT108"
    name = "unlocked-lazy-init"
    description = (
        "check-then-set lazy initialization of shared state without a "
        "lock"
    )
    hint = (
        "hold the owning lock around the check AND the set (or "
        "double-check inside it); single-thread-confined state can "
        "suppress with a comment saying which thread owns it"
    )
    path_markers = ("core/runtime.py", "core/gcs.py")
    visitor_cls = _LazyInitVisitor
