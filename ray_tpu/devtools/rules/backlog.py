"""RT110: unpoliced Connection.call_soon (unbounded transport buffering).

``Connection.call_soon`` deliberately skips asyncio's write flow control
(core/rpc.py documents the contract): the frame is queued/written without
awaiting ``drain()``, so ``transport.write`` buffers unboundedly.  Every
call site must therefore police ``send_backlog`` (falling back to an
awaiting ``drain()`` past its budget) — or be explicitly audited and
baselined, with the policing documented at the site (e.g. a pump loop
that drains on behalf of its push helper).

The check is per enclosing function: a ``<conn>.call_soon(...)`` call is
compliant when the same function also references ``send_backlog`` or
calls ``.drain(...)``.  Event-loop ``call_soon`` (``loop.call_soon``,
``get_running_loop().call_soon``) is a different API and is ignored.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools import astutil
from ray_tpu.devtools.lint import Rule

# receiver spellings that mean the asyncio event loop, not an rpc
# Connection — resolved names and bare attribute chains alike
_LOOP_NAMES = {"loop", "_loop", "io_loop", "event_loop"}
_LOOP_FACTORIES = ("get_event_loop", "get_running_loop", "new_event_loop")


def _is_event_loop_receiver(func: ast.Attribute) -> bool:
    base = func.value
    # loop.call_soon / self._loop.call_soon / rt._loop.call_soon
    if isinstance(base, ast.Name) and base.id in _LOOP_NAMES:
        return True
    if isinstance(base, ast.Attribute) and base.attr in _LOOP_NAMES:
        return True
    # asyncio.get_running_loop().call_soon(...)
    if isinstance(base, ast.Call):
        f = base.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else ""
        )
        if name in _LOOP_FACTORIES:
            return True
    return False


def _function_polices_backlog(fn_node: ast.AST) -> bool:
    """True when the function body references ``send_backlog`` or calls
    ``.drain(...)`` anywhere (including conditions and nested awaits)."""
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Attribute):
            if node.attr == "send_backlog":
                return True
            if node.attr == "drain":
                return True
    return False


class _CallSoonVisitor(astutil.ScopedVisitor):
    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx

    def visit_Call(self, node: ast.Call):
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "call_soon"
            and not _is_event_loop_receiver(func)
        ):
            fn = self.current_function
            if fn is None or not _function_polices_backlog(fn):
                self.ctx.add(
                    self.rule, node,
                    message="`.call_soon(...)` skips rpc write flow "
                            "control and this function never polices "
                            "`send_backlog`/`drain()` — the transport "
                            "buffer can grow without bound under a slow "
                            "peer",
                    hint="check `conn.send_backlog` against the budget "
                         "and `await conn.drain()` past it (or audit the "
                         "site, document who polices, and baseline it)",
                )
        self.generic_visit(node)


class UnpolicedCallSoon(Rule):
    id = "RT110"
    name = "unpoliced-call-soon-backlog"
    description = (
        "Connection.call_soon call site whose enclosing function never "
        "references send_backlog or drain() — unbounded transport "
        "buffering under a slow peer"
    )
    hint = "police conn.send_backlog and fall back to await conn.drain()"
    visitor_cls = _CallSoonVisitor
