"""RT110: unpoliced Connection.call_soon (unbounded transport buffering).

``Connection.call_soon`` deliberately skips asyncio's write flow control
(core/rpc.py documents the contract): the frame is queued/written without
awaiting ``drain()``, so ``transport.write`` buffers unboundedly.  Every
call site must therefore police ``send_backlog`` (falling back to an
awaiting ``drain()`` past its budget) — or be explicitly audited and
baselined, with the policing documented at the site (e.g. a pump loop
that drains on behalf of its push helper).

The check is per enclosing function: a ``<conn>.call_soon(...)`` call is
compliant when the same function also references ``send_backlog`` or
calls ``.drain(...)``.  Event-loop ``call_soon`` (``loop.call_soon``,
``get_running_loop().call_soon``) is a different API and is ignored.
"""

from __future__ import annotations

import ast

from ray_tpu.devtools import astutil
from ray_tpu.devtools.lint import Rule

# receiver spellings that mean the asyncio event loop, not an rpc
# Connection — resolved names and bare attribute chains alike
_LOOP_NAMES = {"loop", "_loop", "io_loop", "event_loop"}
_LOOP_FACTORIES = ("get_event_loop", "get_running_loop", "new_event_loop")


def _is_event_loop_receiver(func: ast.Attribute) -> bool:
    base = func.value
    # loop.call_soon / self._loop.call_soon / rt._loop.call_soon
    if isinstance(base, ast.Name) and base.id in _LOOP_NAMES:
        return True
    if isinstance(base, ast.Attribute) and base.attr in _LOOP_NAMES:
        return True
    # asyncio.get_running_loop().call_soon(...)
    if isinstance(base, ast.Call):
        f = base.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else ""
        )
        if name in _LOOP_FACTORIES:
            return True
    return False


def _function_polices_backlog(fn_node: ast.AST) -> bool:
    """True when the function body references ``send_backlog`` or calls
    ``.drain(...)`` anywhere (including conditions and nested awaits)."""
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Attribute):
            if node.attr == "send_backlog":
                return True
            if node.attr == "drain":
                return True
    return False


class _CallSoonVisitor(astutil.ScopedVisitor):
    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx

    def visit_Call(self, node: ast.Call):
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "call_soon"
            and not _is_event_loop_receiver(func)
        ):
            fn = self.current_function
            if fn is None or not _function_polices_backlog(fn):
                self.ctx.add(
                    self.rule, node,
                    message="`.call_soon(...)` skips rpc write flow "
                            "control and this function never polices "
                            "`send_backlog`/`drain()` — the transport "
                            "buffer can grow without bound under a slow "
                            "peer",
                    hint="check `conn.send_backlog` against the budget "
                         "and `await conn.drain()` past it (or audit the "
                         "site, document who polices, and baseline it)",
                )
        self.generic_visit(node)


class UnpolicedCallSoon(Rule):
    id = "RT110"
    name = "unpoliced-call-soon-backlog"
    description = (
        "Connection.call_soon call site whose enclosing function never "
        "references send_backlog or drain() — unbounded transport "
        "buffering under a slow peer"
    )
    hint = "police conn.send_backlog and fall back to await conn.drain()"
    visitor_cls = _CallSoonVisitor


# -- RT111: serve dispatch without a bound ---------------------------------
#
# Serve's replica dispatch (`<replica>.handle_request.remote(...)` /
# `.handle_request_stream`) rides the actor pump, which enqueues onto
# ``Connection.call_soon`` on the caller's behalf — the pump's RT110
# audit assumes every dispatch layer ABOVE it is bounded.  A dispatch
# site that consults no bound (the traffic plane's admission controller,
# the router's in-flight accounting via ``pick``/``max_ongoing``, or the
# transport's ``send_backlog`` directly) re-creates the unbounded-
# buffering footgun one layer up: overload accumulates in the replica
# mailbox and the transport buffer instead of being shed at the door.

#: referencing any of these in the enclosing function counts as
#: consulting a bound before dispatch
_DISPATCH_BOUND_ATTRS = {"admission", "send_backlog", "max_ongoing"}
_DISPATCH_BOUND_CALLS = {"pick", "drain", "check"}
_DISPATCH_METHODS = {"handle_request", "handle_request_stream"}


def _dispatch_method_of(func: ast.Attribute):
    """The serve dispatch method name when ``func`` is the ``.remote``
    of ``<x>.handle_request[.options(...)].remote`` — else None."""
    if func.attr != "remote":
        return None
    base = func.value
    # <x>.handle_request.options(...).remote
    if isinstance(base, ast.Call) and isinstance(base.func, ast.Attribute):
        if base.func.attr != "options":
            return None
        base = base.func.value
    if isinstance(base, ast.Attribute) and base.attr in _DISPATCH_METHODS:
        return base.attr
    return None


def _function_consults_bound(fn_node: ast.AST) -> bool:
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Attribute):
            if node.attr in _DISPATCH_BOUND_ATTRS:
                return True
            if (
                node.attr in _DISPATCH_BOUND_CALLS
                and isinstance(getattr(node, "ctx", None), ast.Load)
            ):
                return True
    return False


class _ServeDispatchVisitor(astutil.ScopedVisitor):
    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx

    def visit_Call(self, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute):
            meth = _dispatch_method_of(func)
            if meth is not None:
                fn = self.current_function
                if fn is None or not _function_consults_bound(fn):
                    self.ctx.add(
                        self.rule, node,
                        message=f"`.{meth}.remote(...)` dispatches to a "
                                "replica without consulting any bound — "
                                "no admission check, in-flight cap, or "
                                "send_backlog reference in this "
                                "function; overload buffers unboundedly "
                                "in the replica mailbox",
                        hint="route through the traffic scheduler "
                             "(admission.check() + bounded queue), or "
                             "consult router.pick()/max_ongoing before "
                             "dispatching (or audit + baseline the "
                             "site)",
                    )
        self.generic_visit(node)


class UnboundedServeDispatch(Rule):
    id = "RT111"
    name = "unbounded-serve-dispatch"
    description = (
        "serve replica dispatch site whose enclosing function consults "
        "no bound (admission, pick/max_ongoing, send_backlog) before "
        "enqueueing onto the transport"
    )
    hint = (
        "check admission / the router's in-flight cap before dispatch, "
        "or audit and baseline the site"
    )
    visitor_cls = _ServeDispatchVisitor
