"""rtproto engine: builds the program index, derives the wire-surface
tables (:mod:`ray_tpu.devtools.proto.extract`), runs the RT4xx rules,
and funnels findings through the SAME suppression + fingerprint
machinery as the other tiers, so ``# rtlint: disable-next=RT401``
comments and baseline entries behave identically across all four.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

from ray_tpu.devtools.lint import (
    Finding,
    _apply_suppressions,
)

DEFAULT_PROTO_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "proto_baseline.json"
)


class ProtoRule:
    """Wire-contract rule: ``check(index, wire)`` walks the extracted
    wire tables and reports through ``add`` into the owning module's
    context (so per-module suppression comments apply)."""

    id: str = ""
    name: str = ""
    description: str = ""
    hint: str = ""

    def check(self, index, wire) -> None:
        raise NotImplementedError

    def add(self, module, node, message=None, hint=None) -> None:
        module.ctx.add(self, node, message=message, hint=hint)


def all_proto_rules() -> List[ProtoRule]:
    # imported here: the rule module imports ProtoRule from this module
    from ray_tpu.devtools.proto.rules import (
        OrphanHandler,
        PubsubTopicMismatch,
        RpcShapeMismatch,
        UnknownChaosSite,
        UnknownConfigKnob,
        UnknownRpcTarget,
    )

    return [
        UnknownRpcTarget(),
        RpcShapeMismatch(),
        OrphanHandler(),
        UnknownChaosSite(),
        UnknownConfigKnob(),
        PubsubTopicMismatch(),
    ]


def proto_rule_ids() -> Tuple[str, ...]:
    return tuple(r.id for r in all_proto_rules())


@dataclasses.dataclass
class ProtoReport:
    findings: List[Finding]
    files_indexed: int
    parse_errors: List[str]


def _select(rules: Optional[Sequence[str]]) -> List[ProtoRule]:
    selected = all_proto_rules()
    if rules is not None:
        wanted = set(rules)
        unknown = wanted - {r.id for r in selected}
        if unknown:
            raise ValueError(
                f"unknown proto rule id(s): {sorted(unknown)}"
            )
        selected = [r for r in selected if r.id in wanted]
    return selected


def analyze_index(
    index, rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    from ray_tpu.devtools.proto.extract import build_wire_index

    wire = build_wire_index(index)
    for rule in _select(rules):
        rule.check(index, wire)
    findings: List[Finding] = []
    for mname in sorted(index.modules):
        findings.extend(_apply_suppressions(index.modules[mname].ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_sources(
    files: Dict[str, str], rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Fixture/test entry point: ``files`` maps package-relative paths
    (``pkg/mod.py``) to sources; paths double as module names."""
    from ray_tpu.devtools.flow.index import (
        build_index,
        module_name_from_relpath,
    )

    entries = []
    for path in sorted(files):
        norm = path.replace(os.sep, "/")
        tree = ast.parse(files[path], filename=norm)
        entries.append(
            (norm, module_name_from_relpath(norm), files[path], tree)
        )
    index = build_index(entries)
    return analyze_index(index, rules=rules)


def analyze_paths(
    paths: Sequence[str], rules: Optional[Sequence[str]] = None
) -> ProtoReport:
    from ray_tpu.devtools.flow.engine import _collect_entries
    from ray_tpu.devtools.flow.index import (
        build_index,
        module_name_from_relpath,
    )

    entries = []
    errors: List[str] = []
    for finding_path, rel_for_name, apath in _collect_entries(paths):
        try:
            with open(apath, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=finding_path)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            # RT000 is the per-file tier's finding; this tier just
            # indexes what parses and reports the rest as errors
            errors.append(f"{finding_path}: {e}")
            continue
        entries.append((
            finding_path,
            module_name_from_relpath(rel_for_name),
            source,
            tree,
        ))
    index = build_index(entries)
    findings = analyze_index(index, rules=rules)
    return ProtoReport(findings, len(entries), errors)
