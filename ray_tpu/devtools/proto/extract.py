"""rtproto extraction: both sides of every wire surface.

The control plane is string-keyed on purpose (no protoc step), which
means the contract between a ``conn.call("drain_node", {...})`` site and
``def rpc_drain_node`` exists only as matching literals.  This pass
walks the whole-program index (``flow.index.ProgramIndex``) once and
builds a :class:`WireIndex` with five tables:

- **handlers** — ``def rpc_<name>`` methods, ``register_rpc_handler``
  sites, and dispatcher-function branches (``method == "lit"`` inside a
  function taking both ``conn`` and ``method``), each with the payload
  keys it reads;
- **calls** — every ``.call`` / ``.call_soon`` / ``.notify`` site whose
  target resolves to a literal, a module-level string constant, or a
  static f-string prefix (variable names are skipped: precision over
  recall, same contract as the other tiers);
- **topics** — ``publish`` / ``subscribe`` / ``subscribe_async``
  literals and prefixes, including topics built by one-return helper
  functions (``reform_channel(g)`` → ``collective:reform:`` prefix) and
  the ``.call("subscribe", {"channel": ...})`` wire shape;
- **chaos sites** — names consumed by ``FaultPlan(site=...)`` /
  plan-shaped dict literals vs. names actually guarded by a
  ``fault_ctl.hit(...)`` runtime site, plus the canonical
  ``faults.SITES`` registry;
- **knobs** — ``_Config.define`` names vs. every attribute read /
  string ``override`` against the config singleton (function-local
  shadowing of the imported name is respected).

Soundness limits are documented per rule in docs/architecture.md; the
shared stance is that an unresolvable name produces *no* table entry and
therefore no finding.
"""

from __future__ import annotations

import ast
import dataclasses
from collections import Counter
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

# verbs that put a method name on the rpc wire
RPC_VERBS = ("call", "call_soon", "notify")
# _Config attrs that are API, not knobs
CONFIG_API_ATTRS = {"override", "reset", "define"}


@dataclasses.dataclass
class Handler:
    """One side of the rpc contract: something dispatchable by name."""

    name: str
    module: object            # flow.index.ModuleInfo
    node: ast.AST             # anchor for findings (the def / call site)
    kind: str                 # "rpc-def" | "registered" | "dispatcher"
    required: FrozenSet[str]  # payload keys read unconditionally
    optional: FrozenSet[str]  # payload keys read via .get()
    opaque: bool              # payload escapes / **kwargs / unresolvable
    self_mentions: int        # string constants its own declaration adds


@dataclasses.dataclass
class CallSite:
    module: object
    node: ast.AST
    verb: str
    name: Optional[str]       # exact target, or None for prefix/f-string
    prefix: Optional[str]     # static prefix of an f-string target
    keys: Optional[FrozenSet[str]]  # payload dict keys; None = opaque
    has_payload: bool


@dataclasses.dataclass
class TopicSite:
    module: object
    node: ast.AST
    role: str                 # "publish" | "subscribe"
    exact: Optional[str]
    prefix: Optional[str]     # exact is None → f-string/helper prefix

    @property
    def dynamic(self) -> bool:
        return self.exact is None and self.prefix is None


@dataclasses.dataclass
class SiteRef:
    module: object
    node: ast.AST
    name: str


@dataclasses.dataclass
class KnobRef:
    module: object
    node: ast.AST
    name: str
    kind: str                 # "read" | "override"


class WireIndex:
    """The five wire-surface tables over one program index."""

    def __init__(self):
        self.handlers: Dict[str, List[Handler]] = {}
        self.calls: List[CallSite] = []
        self.topics: List[TopicSite] = []
        self.plan_sites: List[SiteRef] = []
        self.checked_sites: List[SiteRef] = []
        self.declared_sites: List[SiteRef] = []
        self.knob_defs: Set[str] = set()
        self.knob_refs: List[KnobRef] = []
        self.singletons: Set[str] = set()   # config singleton qualnames
        self.mentions: Counter = Counter()  # every string constant

    def add_handler(self, h: Handler) -> None:
        self.handlers.setdefault(h.name, []).append(h)

    @property
    def checked_site_names(self) -> Set[str]:
        return {s.name for s in self.checked_sites}

    @property
    def declared_site_names(self) -> Set[str]:
        return {s.name for s in self.declared_sites}

    @property
    def exact_call_names(self) -> Set[str]:
        return {c.name for c in self.calls if c.name is not None}

    @property
    def call_prefixes(self) -> Set[str]:
        return {
            c.prefix for c in self.calls
            if c.name is None and c.prefix
        }


# ---------------------------------------------------------------------------
# Constant / prefix resolution
# ---------------------------------------------------------------------------


def _module_const(pindex, dotted: str) -> Optional[str]:
    """``pkg.mod.NAME`` → the module-level string constant it names, or
    None.  One alias hop (``NAME = OTHER`` in the same module) is
    followed; anything deeper stays unresolved."""
    for _hop in range(2):
        head, _, attr = dotted.rpartition(".")
        if not head or not attr:
            return None
        mod = pindex.modules.get(head)
        if mod is None:
            return None
        value = mod.top_assigns.get(attr)
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            return value.value
        if isinstance(value, ast.Name):
            nxt = mod.resolve(value)
            if nxt is None or nxt == dotted:
                return None
            dotted = nxt
            continue
        return None
    return None


def _joined_prefix(node: ast.JoinedStr) -> Tuple[Optional[str], str]:
    """(exact, prefix) of an f-string: exact when every piece is a
    constant, else the leading static prefix (possibly empty)."""
    parts: List[str] = []
    for piece in node.values:
        if isinstance(piece, ast.Constant) and isinstance(piece.value, str):
            parts.append(piece.value)
        else:
            return None, "".join(parts)
    return "".join(parts), ""


def _single_return(fn_node: ast.AST) -> Optional[ast.expr]:
    """The returned expression of a one-statement helper (docstring
    allowed), e.g. ``def reform_channel(g): return f"...:{g}"``."""
    body = [
        s for s in fn_node.body
        if not (
            isinstance(s, ast.Expr)
            and isinstance(s.value, ast.Constant)
            and isinstance(s.value.value, str)
        )
    ]
    if len(body) == 1 and isinstance(body[0], ast.Return):
        return body[0].value
    return None


def resolve_wire_name(
    pindex, module, expr: ast.AST, follow_calls: bool = True
) -> Tuple[Optional[str], Optional[str]]:
    """(exact, prefix) for a wire-name expression.  ``(None, None)``
    means dynamic — the caller records nothing and flags nothing."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value, None
    if isinstance(expr, ast.JoinedStr):
        exact, prefix = _joined_prefix(expr)
        if exact is not None:
            return exact, None
        return None, (prefix or None)
    if isinstance(expr, (ast.Name, ast.Attribute)):
        dotted = module.resolve(expr)
        if dotted is not None:
            value = _module_const(pindex, dotted)
            if value is not None:
                return value, None
        return None, None
    if follow_calls and isinstance(expr, ast.Call):
        dotted = pindex.resolve_name(module, expr.func)
        fn = pindex.functions.get(dotted) if dotted else None
        if fn is not None:
            ret = _single_return(fn.node)
            if ret is not None:
                return resolve_wire_name(
                    pindex, fn.module, ret, follow_calls=False
                )
        return None, None
    return None, None


# ---------------------------------------------------------------------------
# Handler signatures
# ---------------------------------------------------------------------------


def _positional_params(fn_node: ast.AST) -> List[str]:
    a = fn_node.args
    return [p.arg for p in list(a.posonlyargs) + list(a.args)]


def _iter_skip_nested(body) -> List[ast.AST]:
    out: List[ast.AST] = []
    stack = list(body)
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


_UNCONDITIONAL_STMTS = (
    ast.Expr, ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Return,
    ast.Raise, ast.Assert, ast.With, ast.AsyncWith,
)


def _collect_keys(node: ast.AST, payload: str, out: Set[str]) -> None:
    """Constant keys of bare ``payload["k"]`` loads under ``node``,
    skipping conditional expression arms (IfExp, `and`/`or` tails) and
    nested defs."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef, ast.Lambda)):
        return
    if isinstance(node, ast.IfExp):
        _collect_keys(node.test, payload, out)
        return
    if isinstance(node, ast.BoolOp):
        if node.values:
            _collect_keys(node.values[0], payload, out)
        return
    if (
        isinstance(node, ast.Subscript)
        and isinstance(node.value, ast.Name)
        and node.value.id == payload
        and isinstance(node.ctx, ast.Load)
        and isinstance(node.slice, ast.Constant)
        and isinstance(node.slice.value, str)
    ):
        out.add(node.slice.value)
    for child in ast.iter_child_nodes(node):
        _collect_keys(child, payload, out)


def handler_signature(
    body, payload: Optional[str]
) -> Tuple[FrozenSet[str], FrozenSet[str], bool]:
    """(required, optional, opaque) for a handler body reading
    ``payload``.  Required keys come only from unconditional top-level
    statements (a key read inside an ``if`` is not a contract).  Any use
    of the payload other than ``p["k"]`` / ``p.get("k")`` / ``"k" in p``
    makes the handler opaque — it may forward the dict anywhere, so no
    shape claim is safe."""
    if payload is None:
        return frozenset(), frozenset(), True
    required: Set[str] = set()
    optional: Set[str] = set()
    sanctioned: Set[int] = set()
    for node in _iter_skip_nested(body):
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name)
            and node.value.id == payload
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, str)
        ):
            sanctioned.add(id(node.value))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == payload
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            sanctioned.add(id(node.func.value))
            optional.add(node.args[0].value)
        elif (
            isinstance(node, ast.Compare)
            and len(node.ops) == 1
            and isinstance(node.ops[0], (ast.In, ast.NotIn))
            and isinstance(node.comparators[0], ast.Name)
            and node.comparators[0].id == payload
        ):
            sanctioned.add(id(node.comparators[0]))
    for node in _iter_skip_nested(body):
        if (
            isinstance(node, ast.Name)
            and node.id == payload
            and id(node) not in sanctioned
        ):
            return frozenset(), frozenset(), True
    for stmt in body:
        if isinstance(stmt, _UNCONDITIONAL_STMTS):
            _collect_keys(stmt, payload, required)
    return frozenset(required), frozenset(optional), False


def _payload_param(fn_node: ast.AST, skip_self: bool) -> Optional[str]:
    """Wire convention: handlers are ``(conn, payload)`` (plus ``self``
    for methods) — the payload is the last positional parameter."""
    params = _positional_params(fn_node)
    if skip_self and params and params[0] in ("self", "cls"):
        params = params[1:]
    if len(params) >= 2:
        return params[-1]
    return None


def _has_kwargs(fn_node: ast.AST) -> bool:
    return fn_node.args.kwarg is not None


# ---------------------------------------------------------------------------
# Pass A: knob defs, config singletons, SITES registry, string mentions
# ---------------------------------------------------------------------------


def _collect_module_facts(mod, wire: WireIndex) -> None:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            wire.mentions[node.value] += 1

    # D = _Config.define style aliases, mapped to their owning class
    alias_owner: Dict[str, str] = {}
    for stmt in mod.tree.body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Attribute)
            and stmt.value.attr == "define"
            and isinstance(stmt.value.value, ast.Name)
        ):
            alias_owner[stmt.targets[0].id] = stmt.value.value.id

    owners: Set[str] = set()
    found_defs = False
    for stmt in mod.tree.body:
        if not (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)):
            continue
        call = stmt.value
        owner = None
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "define"
            and isinstance(call.func.value, ast.Name)
        ):
            owner = call.func.value.id
        elif (
            isinstance(call.func, ast.Name)
            and call.func.id in alias_owner
        ):
            owner = alias_owner[call.func.id]
        if owner is None or not call.args:
            continue
        first = call.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            wire.knob_defs.add(first.value)
            owners.add(owner)
            found_defs = True

    if found_defs:
        for stmt in mod.tree.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Name)
                and stmt.value.func.id in owners
            ):
                wire.singletons.add(
                    f"{mod.name}.{stmt.targets[0].id}"
                )

    # the canonical chaos-site registry lives in a `faults` module
    if mod.name.split(".")[-1] == "faults":
        for stmt in mod.tree.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "SITES"
                and isinstance(stmt.value, (ast.Tuple, ast.List))
            ):
                for elt in stmt.value.elts:
                    name = None
                    if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str
                    ):
                        name = elt.value
                    elif isinstance(elt, ast.Name):
                        value = mod.top_assigns.get(elt.id)
                        if isinstance(value, ast.Constant) and isinstance(
                            value.value, str
                        ):
                            name = value.value
                    if name is not None:
                        wire.declared_sites.append(
                            SiteRef(mod, elt, name)
                        )


# ---------------------------------------------------------------------------
# Pass B: handlers, calls, topics, chaos refs, knob refs
# ---------------------------------------------------------------------------


def _bound_names(fn_node: ast.AST) -> Set[str]:
    """Names the function binds (params, assignments, imports, loop and
    ``with``/``except`` targets) — over-approximated across nested
    scopes, so shadow checks under-report rather than false-positive."""
    bound: Set[str] = set()
    a = fn_node.args
    for arg in (
        list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
        + ([a.vararg] if a.vararg else [])
        + ([a.kwarg] if a.kwarg else [])
    ):
        bound.add(arg.arg)
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            bound.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                bound.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
    return bound


def _dict_keys(expr: ast.AST) -> Optional[FrozenSet[str]]:
    """Constant string keys of a dict literal; None (opaque) for any
    other payload expression, ``**`` expansion, or non-string key."""
    if isinstance(expr, ast.Constant) and expr.value is None:
        return frozenset()
    if not isinstance(expr, ast.Dict):
        return None
    keys: Set[str] = set()
    for k in expr.keys:
        if k is None:  # {**base, ...}
            return None
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return None
        keys.add(k.value)
    return frozenset(keys)


def _dict_value(expr: ast.AST, key: str) -> Optional[ast.AST]:
    if not isinstance(expr, ast.Dict):
        return None
    for k, v in zip(expr.keys, expr.values):
        if isinstance(k, ast.Constant) and k.value == key:
            return v
    return None


def _method_branch_literals(test: ast.AST) -> List[Tuple[str, bool]]:
    """(literal, signature_extractable) per rpc name a dispatcher branch
    test matches: ``method == "x"``, ``"x" == method``, ``method in
    ("a", "b")``, and ``or``-chains thereof."""
    out: List[Tuple[str, bool]] = []
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
        for v in test.values:
            out.extend(_method_branch_literals(v))
        return out
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1):
        return out
    left, op, right = test.left, test.ops[0], test.comparators[0]
    if isinstance(op, ast.Eq):
        for a, b in ((left, right), (right, left)):
            if (
                isinstance(a, ast.Name) and a.id == "method"
                and isinstance(b, ast.Constant)
                and isinstance(b.value, str)
            ):
                out.append((b.value, True))
    elif isinstance(op, ast.In):
        if (
            isinstance(left, ast.Name) and left.id == "method"
            and isinstance(right, (ast.Tuple, ast.List, ast.Set))
        ):
            for elt in right.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    out.append((elt.value, False))
    return out


class _ModuleWalker(ast.NodeVisitor):
    """Pass B over one module: every wire-surface fact that needs the
    cross-module resolution environment."""

    def __init__(self, pindex, wire: WireIndex, mod):
        self.pindex = pindex
        self.wire = wire
        self.mod = mod
        self.class_stack: List[ast.ClassDef] = []
        self.func_stack: List[ast.AST] = []
        self._bound_cache: Dict[int, Set[str]] = {}

    def run(self) -> None:
        self.visit(self.mod.tree)

    # -- scope tracking --------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef):
        self.class_stack.append(node)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_func(self, node):
        self._enter_function(node)
        self.func_stack.append(node)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _shadowed(self, name: str) -> bool:
        for fn in self.func_stack:
            cache = self._bound_cache.get(id(fn))
            if cache is None:
                cache = self._bound_cache[id(fn)] = _bound_names(fn)
            if name in cache:
                return True
        return False

    # -- handlers --------------------------------------------------------

    def _enter_function(self, node) -> None:
        if self.class_stack and node.name.startswith("rpc_"):
            payload = _payload_param(node, skip_self=True)
            req, opt, opaque = handler_signature(node.body, payload)
            if _has_kwargs(node):
                opaque = True
            self.wire.add_handler(Handler(
                name=node.name[len("rpc_"):],
                module=self.mod,
                node=node,
                kind="rpc-def",
                required=req,
                optional=opt,
                opaque=opaque,
                self_mentions=0,
            ))
        params = _positional_params(node)
        if "method" in params and "conn" in params:
            self._dispatcher_branches(node)

    def _dispatcher_branches(self, fn_node) -> None:
        payload = _payload_param(fn_node, skip_self=True)
        if payload in ("method", "conn"):
            payload = None
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.If):
                continue
            for literal, extractable in _method_branch_literals(node.test):
                if extractable and payload is not None:
                    req, opt, opaque = handler_signature(
                        node.body, payload
                    )
                else:
                    req, opt, opaque = frozenset(), frozenset(), True
                self.wire.add_handler(Handler(
                    name=literal,
                    module=self.mod,
                    node=node,
                    kind="dispatcher",
                    required=req,
                    optional=opt,
                    opaque=opaque,
                    self_mentions=1,
                ))

    def _registered_handler(self, node: ast.Call) -> None:
        if len(node.args) < 2:
            return
        name, _pfx = resolve_wire_name(
            self.pindex, self.mod, node.args[0], follow_calls=False
        )
        if name is None:
            return
        target = node.args[1]
        fn_node = None
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and self.class_stack
        ):
            for item in self.class_stack[-1].body:
                if isinstance(
                    item, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and item.name == target.attr:
                    fn_node = item
                    break
        elif isinstance(target, ast.Name):
            dotted = self.pindex.resolve_name(self.mod, target)
            fi = self.pindex.functions.get(dotted) if dotted else None
            if fi is not None:
                fn_node = fi.node
        if fn_node is not None:
            payload = _payload_param(fn_node, skip_self=True)
            req, opt, opaque = handler_signature(fn_node.body, payload)
            if _has_kwargs(fn_node):
                opaque = True
        else:
            req, opt, opaque = frozenset(), frozenset(), True
        self.wire.add_handler(Handler(
            name=name,
            module=self.mod,
            node=node,
            kind="registered",
            required=req,
            optional=opt,
            opaque=opaque,
            self_mentions=1,
        ))

    # -- calls / topics / chaos / knobs ----------------------------------

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in RPC_VERBS and node.args:
                self._rpc_call(node, f.attr)
            elif f.attr == "publish" and node.args:
                self._topic(node, node.args[0], "publish")
            elif f.attr in ("subscribe", "subscribe_async") and node.args:
                self._topic(node, node.args[0], "subscribe")
            elif f.attr == "hit" and node.args:
                self._checked_site(node)
            elif f.attr == "register_rpc_handler":
                self._registered_handler(node)
            elif f.attr == "override" and node.args:
                self._override(node, f)
        elif isinstance(f, ast.Name):
            if f.id == "hit" and node.args:
                self._checked_site(node)
        last = None
        if isinstance(f, ast.Attribute):
            last = f.attr
        elif isinstance(f, ast.Name):
            last = f.id
        if last == "FaultPlan":
            for kw in node.keywords:
                if kw.arg == "site" and isinstance(
                    kw.value, ast.Constant
                ) and isinstance(kw.value.value, str):
                    self.wire.plan_sites.append(
                        SiteRef(self.mod, node, kw.value.value)
                    )
            if node.args and isinstance(
                node.args[0], ast.Constant
            ) and isinstance(node.args[0].value, str):
                self.wire.plan_sites.append(
                    SiteRef(self.mod, node, node.args[0].value)
                )
        self.generic_visit(node)

    def visit_Dict(self, node: ast.Dict):
        # a plan-shaped dict literal ({"site": ..., "action": ...}) is a
        # wire-format FaultPlan (RT_FAULTS / scenario JSON)
        keys = {
            k.value for k in node.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        }
        if "site" in keys and "action" in keys:
            site = _dict_value(node, "site")
            if isinstance(site, ast.Constant) and isinstance(
                site.value, str
            ):
                self.wire.plan_sites.append(
                    SiteRef(self.mod, node, site.value)
                )
        self.generic_visit(node)

    def _rpc_call(self, node: ast.Call, verb: str) -> None:
        name, prefix = resolve_wire_name(
            self.pindex, self.mod, node.args[0]
        )
        if name is None and prefix is None:
            return
        keys: Optional[FrozenSet[str]]
        has_payload = len(node.args) >= 2
        if has_payload:
            keys = _dict_keys(node.args[1])
        else:
            kw = next(
                (k for k in node.keywords if k.arg == "payload"), None
            )
            if kw is not None:
                has_payload = True
                keys = _dict_keys(kw.value)
            else:
                keys = frozenset()
        self.wire.calls.append(CallSite(
            module=self.mod, node=node, verb=verb,
            name=name, prefix=prefix, keys=keys,
            has_payload=has_payload,
        ))
        # the wire shapes of pubsub: subscribing is an rpc whose payload
        # names the channel; publishing is a "publish" notify
        if name in ("subscribe", "publish") and has_payload:
            chan = len(node.args) >= 2 and _dict_value(
                node.args[1], "channel"
            )
            if chan:
                role = (
                    "subscribe" if name == "subscribe" else "publish"
                )
                self._topic(node, chan, role)

    def _topic(self, node: ast.AST, expr: ast.AST, role: str) -> None:
        exact, prefix = resolve_wire_name(self.pindex, self.mod, expr)
        self.wire.topics.append(TopicSite(
            module=self.mod, node=node, role=role,
            exact=exact, prefix=prefix,
        ))

    def _checked_site(self, node: ast.Call) -> None:
        name, _pfx = resolve_wire_name(
            self.pindex, self.mod, node.args[0], follow_calls=False
        )
        if name is not None:
            self.wire.checked_sites.append(SiteRef(self.mod, node, name))

    def _override(self, node: ast.Call, f: ast.Attribute) -> None:
        dotted = self.mod.resolve(f.value)
        if dotted is None or dotted not in self.wire.singletons:
            return
        if (
            isinstance(f.value, ast.Name)
            and self._shadowed(f.value.id)
        ):
            return
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(
            first.value, str
        ):
            self.wire.knob_refs.append(
                KnobRef(self.mod, node, first.value, "override")
            )

    def visit_Attribute(self, node: ast.Attribute):
        if (
            isinstance(node.ctx, ast.Load)
            and not node.attr.startswith("_")
            and node.attr not in CONFIG_API_ATTRS
            and isinstance(node.value, (ast.Name, ast.Attribute))
        ):
            dotted = self.mod.resolve(node.value)
            if dotted is not None and dotted in self.wire.singletons:
                root = node.value
                while isinstance(root, ast.Attribute):
                    root = root.value
                if not (
                    isinstance(root, ast.Name)
                    and self._shadowed(root.id)
                ):
                    self.wire.knob_refs.append(
                        KnobRef(self.mod, node, node.attr, "read")
                    )
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def build_wire_index(pindex) -> WireIndex:
    wire = WireIndex()
    for mname in sorted(pindex.modules):
        _collect_module_facts(pindex.modules[mname], wire)
    for mname in sorted(pindex.modules):
        _ModuleWalker(pindex, wire, pindex.modules[mname]).run()
    return wire
