"""RT4xx wire-contract rules over the :class:`~.extract.WireIndex`.

Shared stance on dynamic names (see docs/architecture.md): a name the
extractor cannot resolve to a literal or a static prefix produces no
table entry — it can neither be flagged nor satisfy another side of a
contract.  Precision over recall, same as rtflow/rtrace.
"""

from __future__ import annotations

from ray_tpu.devtools.proto.engine import ProtoRule


class UnknownRpcTarget(ProtoRule):
    id = "RT401"
    name = "unknown-rpc-target"
    description = (
        "A .call/.call_soon/.notify names an rpc that no handler "
        "anywhere in the program can dispatch."
    )
    hint = (
        "Check the method string against the rpc_* defs, "
        "register_rpc_handler sites, and dispatcher branches; a typo "
        "here fails only at runtime with a method-not-found error."
    )

    def check(self, index, wire) -> None:
        for call in wire.calls:
            if call.name is None:
                continue  # prefix/f-string targets are never flagged
            if call.name in wire.handlers:
                continue
            self.add(
                call.module,
                call.node,
                message=(
                    f"rpc target {call.name!r} has no handler anywhere "
                    f"in the scanned program"
                ),
            )


class RpcShapeMismatch(ProtoRule):
    id = "RT402"
    name = "rpc-shape-mismatch"
    description = (
        "A call site's payload dict is missing keys that every "
        "candidate handler for that rpc reads unconditionally."
    )
    hint = (
        "Add the missing key(s) to the payload, or read them with "
        ".get() in the handler if they are genuinely optional."
    )

    def check(self, index, wire) -> None:
        for call in wire.calls:
            if call.name is None or call.keys is None:
                continue  # dynamic target or opaque payload
            handlers = wire.handlers.get(call.name)
            if not handlers or any(h.opaque for h in handlers):
                continue
            # compatible with ANY candidate handler → fine; the call is
            # wrong only if every handler demands keys it doesn't send
            missing_per_handler = [
                sorted(h.required - call.keys) for h in handlers
            ]
            if all(missing_per_handler):
                missing = min(missing_per_handler, key=len)
                self.add(
                    call.module,
                    call.node,
                    message=(
                        f"payload for rpc {call.name!r} is missing "
                        f"key(s) {missing} that every handler reads "
                        f"unconditionally"
                    ),
                )


class OrphanHandler(ProtoRule):
    id = "RT403"
    name = "orphan-handler"
    description = (
        "A registered rpc handler that no call site and no string "
        "mention anywhere in the program refers to — dead wire surface."
    )
    hint = (
        "Delete the handler, or baseline it with an audit comment if "
        "it is a public entry point for out-of-package clients."
    )

    def check(self, index, wire) -> None:
        exact = wire.exact_call_names
        prefixes = wire.call_prefixes
        for name in sorted(wire.handlers):
            if name in exact:
                continue
            if any(name.startswith(p) for p in prefixes):
                continue
            handlers = wire.handlers[name]
            self_mentions = sum(h.self_mentions for h in handlers)
            if wire.mentions[name] - self_mentions > 0:
                # named somewhere else (tests, docs-by-string, dynamic
                # call assembly) — not provably dead
                continue
            for h in handlers:
                self.add(
                    h.module,
                    h.node,
                    message=(
                        f"handler for rpc {h.name!r} has no call site "
                        f"or string mention anywhere in the scanned "
                        f"program"
                    ),
                )


class UnknownChaosSite(ProtoRule):
    id = "RT404"
    name = "unknown-chaos-site"
    description = (
        "A FaultPlan (or plan-shaped dict) names a chaos site that no "
        "runtime fault_ctl.hit() guards, or a hit site drifts from the "
        "canonical faults.SITES registry."
    )
    hint = (
        "Site names are only meaningful where a runtime check exists; "
        "add the site to faults.SITES and guard it with hit(), or fix "
        "the plan's site string."
    )

    def check(self, index, wire) -> None:
        checked = wire.checked_site_names
        declared = wire.declared_site_names
        for ref in wire.plan_sites:
            if ref.name not in checked:
                self.add(
                    ref.module,
                    ref.node,
                    message=(
                        f"fault plan targets site {ref.name!r} but no "
                        f"runtime hit() check guards that name — the "
                        f"plan arms and never fires"
                    ),
                )
        for ref in wire.declared_sites:
            if ref.name not in checked:
                self.add(
                    ref.module,
                    ref.node,
                    message=(
                        f"registry declares site {ref.name!r} but no "
                        f"runtime hit() check guards it"
                    ),
                )
        if declared:
            for ref in wire.checked_sites:
                if ref.name not in declared:
                    self.add(
                        ref.module,
                        ref.node,
                        message=(
                            f"runtime check site {ref.name!r} is not "
                            f"in the canonical faults.SITES registry"
                        ),
                    )


class UnknownConfigKnob(ProtoRule):
    id = "RT405"
    name = "unknown-config-knob"
    description = (
        "An attribute read or string override() against the config "
        "singleton names a knob no _Config.define declares."
    )
    hint = (
        "The read raises AttributeError only when that code path runs; "
        "fix the knob name or add the missing define()."
    )

    def check(self, index, wire) -> None:
        if not wire.knob_defs:
            return  # no config plane in the scanned program
        for ref in wire.knob_refs:
            if ref.name in wire.knob_defs:
                continue
            what = (
                "override()" if ref.kind == "override"
                else "attribute read"
            )
            self.add(
                ref.module,
                ref.node,
                message=(
                    f"config {what} names knob {ref.name!r} but no "
                    f"_Config.define declares it"
                ),
            )


class PubsubTopicMismatch(ProtoRule):
    id = "RT406"
    name = "pubsub-topic-mismatch"
    description = (
        "A publish with no matching subscriber, or a subscribe with no "
        "matching publisher — one-sided topics are silent failures."
    )
    hint = (
        "Check the topic string on both sides; dynamic prefixes match "
        "by static prefix.  Baseline (with an audit comment) topics "
        "that are intentionally consumed outside the package."
    )

    @staticmethod
    def _matches(a, b) -> bool:
        """Can topic site *a* reach topic site *b*?  Exact names match
        exactly; a prefix site matches anything it prefixes (and vice
        versa); two prefixes match if either extends the other."""
        if a.exact is not None and b.exact is not None:
            return a.exact == b.exact
        if a.exact is not None and b.prefix is not None:
            return a.exact.startswith(b.prefix)
        if a.prefix is not None and b.exact is not None:
            return b.exact.startswith(a.prefix)
        if a.prefix is not None and b.prefix is not None:
            return a.prefix.startswith(b.prefix) or b.prefix.startswith(
                a.prefix
            )
        return False

    def check(self, index, wire) -> None:
        pubs = [t for t in wire.topics if t.role == "publish"]
        subs = [t for t in wire.topics if t.role == "subscribe"]
        # a fully-dynamic site on either side could name anything, so
        # it neither gets flagged nor vouches for the other side; the
        # GCS relay (publish(p["channel"], ...)) is exactly this case
        for pub in pubs:
            if pub.dynamic:
                continue
            if not any(self._matches(pub, s) for s in subs):
                topic = pub.exact if pub.exact is not None else (
                    pub.prefix + "*"
                )
                self.add(
                    pub.module,
                    pub.node,
                    message=(
                        f"publish to topic {topic!r} has no subscriber "
                        f"anywhere in the scanned program"
                    ),
                )
        for sub in subs:
            if sub.dynamic:
                continue
            if not any(self._matches(sub, p) for p in pubs):
                topic = sub.exact if sub.exact is not None else (
                    sub.prefix + "*"
                )
                self.add(
                    sub.module,
                    sub.node,
                    message=(
                        f"subscribe to topic {topic!r} has no "
                        f"publisher anywhere in the scanned program"
                    ),
                )
