"""rtproto: the wire-contract analysis tier (RT4xx).

The reference framework's control plane is contract-checked at compile
time by protoc; ours is deliberately string-keyed — an rpc is
``conn.call("drain_node", {...})`` meeting ``def rpc_drain_node``, a
pubsub topic is a literal like ``"serve:routes"``, a chaos site is
``"raylet.lease.grant"``, a config knob resolves through
``_Config.__getattr__``.  The same drift class protoc rejects at build
time here fails only at runtime, or silently.  This fourth tier closes
that gap: an extraction pass builds both sides of every wire surface
(handler/call/topic/site/knob tables) and six rules check them against
each other.

- RT401 unknown-rpc-target: a call names an rpc no handler dispatches.
- RT402 rpc-shape-mismatch: a payload dict is missing keys every
  candidate handler reads unconditionally (``**kwargs``/opaque
  handlers exempt).
- RT403 orphan-handler: dead wire surface — a handler nothing calls or
  even names (baseline-able for public entry points).
- RT404 unknown-chaos-site: a fault plan names a site no runtime
  ``hit()`` guards, or a hit site drifts from ``faults.SITES``.
- RT405 unknown-config-knob: a config-singleton read or ``override``
  names a knob no ``_Config.define`` declares.
- RT406 pubsub-topic-mismatch: a publish with no subscriber or
  subscribe with no publisher (dynamic prefixes match by prefix).

Findings ride the same ``Finding`` type, suppression comments, and
baseline machinery as the other tiers; run everything with::

    python -m ray_tpu.devtools.lint --all ray_tpu
"""

from ray_tpu.devtools.proto.engine import (  # noqa: F401
    DEFAULT_PROTO_BASELINE,
    ProtoReport,
    all_proto_rules,
    analyze_paths,
    analyze_sources,
    proto_rule_ids,
)
from ray_tpu.devtools.proto.extract import (  # noqa: F401
    WireIndex,
    build_wire_index,
)
