"""rtlint: AST-based distributed-correctness static analysis for ray_tpu.

Walks the package's Python sources and reports findings from the rule
classes in ``ray_tpu.devtools.rules`` — each one targets a bug family
this codebase has actually shipped (event-loop blocking, non-atomic
persists, impure traced functions, ...).  Findings carry ``file:line``,
a stable rule id, and a fix hint.

Four tiers share this CLI: the per-file rules below (RT1xx); the
whole-program ``rtflow`` tier (RT2xx, ``ray_tpu.devtools.flow``) which
indexes the full package into a call graph and runs interprocedural
rules (actor deadlock cycles, ObjectRef leaks, unserializable captures,
rank-divergent collectives); the concurrency ``rtrace`` tier
(RT3xx, ``ray_tpu.devtools.trace``) which classifies functions by
execution plane (io loop / executor threads / caller threads), checks
cross-plane state hand-offs, and runs a lock-order checker over the
native ``_native/*.cc`` sources; and the wire-contract ``rtproto``
tier (RT4xx, ``ray_tpu.devtools.proto``) which extracts both sides of
every string-keyed wire surface (rpc handlers vs. call sites, pubsub
topics, chaos sites, config knobs) and checks them against each other.
``--flow`` / ``--trace`` / ``--proto`` add a tier; ``--all`` runs
every tier.

CLI::

    python -m ray_tpu.devtools.lint ray_tpu            # text report
    python -m ray_tpu.devtools.lint --flow ray_tpu     # + RT2xx tier
    python -m ray_tpu.devtools.lint --trace ray_tpu    # + RT3xx tier
    python -m ray_tpu.devtools.lint --proto ray_tpu    # + RT4xx tier
    python -m ray_tpu.devtools.lint --all ray_tpu      # every tier
    python -m ray_tpu.devtools.lint ray_tpu --format json
    python -m ray_tpu.devtools.lint ray_tpu --format sarif  # CI annotations
    python -m ray_tpu.devtools.lint --all ray_tpu --changed-only
    python -m ray_tpu.devtools.lint --list-rules
    python -m ray_tpu.devtools.lint ray_tpu --write-baseline

Suppression (same line, or the line above with ``disable-next``)::

    time.sleep(0.1)  # rtlint: disable=RT101
    # rtlint: disable-next=RT101,RT104
    rt.get(ref)
    # rtlint: disable-file=RT103          (anywhere in the file)

Baseline: grandfathered findings live in ``lint_baseline.json`` next to
this module (override with ``--baseline``).  A finding is keyed by
(path, rule, hash of the stripped source line) so unrelated edits don't
invalidate it; ``--write-baseline`` regenerates the file from the
current tree.  Exit code 0 = clean (or fully baselined), 1 = new
findings, 2 = usage/internal error.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import hashlib
import json
import os
import re
import sys
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ray_tpu.devtools.astutil import ImportMap

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "lint_baseline.json"
)

_SUPPRESS_RE = re.compile(
    r"#\s*rtlint:\s*(disable|disable-next|disable-file)\s*=\s*"
    r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*|all)"
)


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str
    hint: str
    line_text: str = ""

    def fingerprint(self) -> str:
        digest = hashlib.sha1(
            self.line_text.strip().encode("utf-8", "replace")
        ).hexdigest()[:12]
        return f"{self.path}:{self.rule}:{digest}"

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "rule": self.rule,
            "message": self.message,
            "hint": self.hint,
            "fingerprint": self.fingerprint(),
        }

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"{self.message} (hint: {self.hint})"
        )


class Rule:
    """Base class: subclasses set the metadata and a ``visitor_cls``
    (an ``astutil.ScopedVisitor`` taking ``(rule, ctx)``)."""

    id: str = ""
    name: str = ""
    description: str = ""
    hint: str = ""
    # substrings matched against the posix path; empty = every file
    path_markers: Tuple[str, ...] = ()
    visitor_cls = None

    def applies_to(self, path: str) -> bool:
        if not self.path_markers:
            return True
        return any(m in path for m in self.path_markers)

    def check(self, ctx: "ModuleContext") -> None:
        self.visitor_cls(self, ctx).visit(ctx.tree)


class ModuleContext:
    """Everything a rule needs about one source file."""

    def __init__(self, path: str, source: str, tree: ast.AST):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.imports = ImportMap(tree)
        self.findings: List[Finding] = []

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def add(
        self,
        rule: Rule,
        node: ast.AST,
        message: Optional[str] = None,
        hint: Optional[str] = None,
    ) -> None:
        lineno = getattr(node, "lineno", 1)
        self.findings.append(
            Finding(
                path=self.path,
                line=lineno,
                col=getattr(node, "col_offset", 0) + 1,
                rule=rule.id,
                message=message or rule.description,
                hint=hint or rule.hint,
                line_text=self.line_text(lineno),
            )
        )


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------


def _iter_comment_lines(source: str):
    """(lineno, comment_text) for real COMMENT tokens only — a
    directive quoted inside a string literal or docstring (e.g. docs
    describing the syntax) must NOT arm a suppression."""
    import io
    import tokenize

    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return  # unparseable tail: no suppressions past this point


def _parse_suppressions(source: str):
    """(line -> set(ids), next_line -> set(ids), file-wide set(ids));
    the id set may contain 'all'."""
    per_line: Dict[int, set] = {}
    per_next: Dict[int, set] = {}
    file_wide: set = set()
    for i, text in _iter_comment_lines(source):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        kind, ids_text = m.group(1), m.group(2)
        ids = {s.strip() for s in ids_text.split(",")}
        if kind == "disable":
            per_line.setdefault(i, set()).update(ids)
        elif kind == "disable-next":
            per_next.setdefault(i + 1, set()).update(ids)
        else:
            file_wide.update(ids)
    return per_line, per_next, file_wide


def _apply_suppressions(ctx: ModuleContext) -> List[Finding]:
    per_line, per_next, file_wide = _parse_suppressions(ctx.source)
    kept = []
    for f in ctx.findings:
        ids = (
            per_line.get(f.line, set())
            | per_next.get(f.line, set())
            | file_wide
        )
        if f.rule in ids or "all" in ids:
            continue
        kept.append(f)
    return kept


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


def all_rules() -> List[Rule]:
    from ray_tpu.devtools.rules import ALL_RULES

    return [cls() for cls in ALL_RULES]


def _select_rules(only: Optional[Sequence[str]]) -> List[Rule]:
    rules = all_rules()
    if only:
        wanted = set(only)
        unknown = wanted - {r.id for r in rules}
        if unknown:
            raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
        rules = [r for r in rules if r.id in wanted]
    return rules


def lint_source(
    source: str,
    path: str = "<string>.py",
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Lint a source string (the fixture-test entry point).  ``path``
    participates in rule path scoping, so fixtures pass paths like
    ``pkg/train/ckpt.py`` to arm path-scoped rules."""
    path = path.replace(os.sep, "/")
    tree = ast.parse(source, filename=path)
    ctx = ModuleContext(path, source, tree)
    for rule in _select_rules(rules):
        if rule.applies_to(path):
            rule.check(ctx)
    ctx.findings = _apply_suppressions(ctx)
    ctx.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return ctx.findings


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out = []
    for p in paths:
        if not os.path.exists(p):
            # a typo'd path must not report "0 files, clean, exit 0"
            raise ValueError(f"path does not exist: {p}")
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if not d.startswith(".") and d != "__pycache__"
            )
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(root, f))
    return out


@dataclasses.dataclass
class LintReport:
    findings: List[Finding]
    files_scanned: int
    parse_errors: List[str]


def lint_paths(
    paths: Sequence[str],
    rules: Optional[Sequence[str]] = None,
    file_filter: Optional[set] = None,
) -> LintReport:
    """``file_filter``, when given, is a set of ABSOLUTE paths to keep
    (the ``--changed-only`` edit-loop mode); other files are skipped
    entirely."""
    selected = _select_rules(rules)
    findings: List[Finding] = []
    errors: List[str] = []
    files = iter_py_files(paths)
    if file_filter is not None:
        files = [f for f in files if os.path.abspath(f) in file_filter]
    for fpath in files:
        # Canonicalize to a cwd-relative path when the file is under the
        # cwd: `lint ray_tpu` (CLI) and `lint_paths([/abs/pkg])` (the
        # test gate) must produce identical finding paths, or baseline
        # fingerprints written by one invocation never match the other.
        rel = fpath
        if os.path.isabs(fpath):
            candidate = os.path.relpath(fpath)
            if not candidate.startswith(".."):
                rel = candidate
        rel = rel.replace(os.sep, "/")
        try:
            with open(fpath, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=rel)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            # An unparseable file is itself a finding (RT000): it means
            # the module cannot even be imported on this interpreter.
            errors.append(f"{rel}: {e}")
            findings.append(Finding(
                path=rel,
                line=getattr(e, "lineno", None) or 1,
                col=getattr(e, "offset", None) or 1,
                rule="RT000",
                message=f"file does not parse: {e}",
                hint="fix the syntax for the supported interpreter",
                line_text=str(e),
            ))
            continue
        ctx = ModuleContext(rel, source, tree)
        for rule in selected:
            if rule.applies_to(rel):
                rule.check(ctx)
        findings.extend(_apply_suppressions(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintReport(findings, len(files), errors)


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> Counter:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return Counter()
    return Counter(data.get("findings", {}))


def split_baselined(
    findings: List[Finding], baseline: Counter
) -> Tuple[List[Finding], List[Finding]]:
    """(new, grandfathered); each baseline fingerprint absorbs up to its
    recorded count of identical findings."""
    budget = Counter(baseline)
    new, old = [], []
    for f in findings:
        fp = f.fingerprint()
        if budget[fp] > 0:
            budget[fp] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


def write_baseline(findings: List[Finding], path: str) -> None:
    counts = Counter(f.fingerprint() for f in findings)
    payload = {
        "comment": (
            "rtlint grandfathered findings; regenerate with "
            "python -m ray_tpu.devtools.lint <paths> --write-baseline"
        ),
        "version": 1,
        "findings": dict(sorted(counts.items())),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=False)
        fh.write("\n")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


_LINTABLE_SUFFIXES = (".py", ".cc", ".cpp", ".cxx", ".h", ".hpp")


def git_changed_files() -> Optional[set]:
    """Absolute paths of lintable files (.py plus the native C++
    suffixes the trace tier checks) that are dirty (``git diff
    --name-only HEAD``) or untracked (``git ls-files --others
    --exclude-standard`` — a brand-new module is the MOST important
    file in the edit loop), or None when git (or a repo) is
    unavailable — callers fall back to the whole package so the mode
    degrades safely."""
    import subprocess

    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=10,
        )
        if top.returncode != 0:
            return None
        root = top.stdout.strip()
        out: set = set()
        for cmd in (
            ["git", "diff", "--name-only", "HEAD"],
            ["git", "ls-files", "--others", "--exclude-standard"],
        ):
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=10,
            )
            if proc.returncode != 0:
                return None
            out.update(
                os.path.abspath(os.path.join(root, line.strip()))
                for line in proc.stdout.splitlines()
                if line.strip().endswith(_LINTABLE_SUFFIXES)
            )
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint",
        description="rtlint: distributed-correctness static analysis",
    )
    parser.add_argument("paths", nargs="*", default=None,
                        help="files or directories (default: ray_tpu)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run")
    parser.add_argument("--flow", action="store_true",
                        help="also run the whole-program rtflow tier "
                             "(RT2xx interprocedural rules)")
    parser.add_argument("--trace", action="store_true",
                        help="also run the rtrace concurrency tier "
                             "(RT3xx plane/race rules plus the native "
                             "lock-order checker over _native/*.cc)")
    parser.add_argument("--proto", action="store_true",
                        help="also run the rtproto wire-contract tier "
                             "(RT4xx rules over the string-keyed rpc/"
                             "pubsub/chaos/config surfaces)")
    parser.add_argument("--all", action="store_true", dest="all_tiers",
                        help="run every tier (equivalent to --flow "
                             "--trace --proto)")
    parser.add_argument("--changed-only", action="store_true",
                        help="report only on files dirty per `git diff "
                             "--name-only HEAD` (flow/trace still index "
                             "the whole tree for cross-module edges); "
                             "falls back to everything when git is "
                             "unavailable")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline JSON path (RT1xx tier)")
    parser.add_argument("--flow-baseline", default=None,
                        help="baseline JSON path for the flow tier "
                             "(default: flow/flow_baseline.json)")
    parser.add_argument("--trace-baseline", default=None,
                        help="baseline JSON path for the trace tier "
                             "(default: trace/trace_baseline.json)")
    parser.add_argument("--proto-baseline", default=None,
                        help="baseline JSON path for the proto tier "
                             "(default: proto/proto_baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline file(s)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="regenerate the baseline(s) from this run")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)
    if args.all_tiers:
        args.flow = True
        args.trace = True
        args.proto = True

    flow_mod = None
    trace_mod = None
    proto_mod = None
    if args.flow or args.list_rules:
        from ray_tpu.devtools import flow as flow_mod  # lazy: index cost
    if args.trace or args.list_rules:
        from ray_tpu.devtools import trace as trace_mod
    if args.proto or args.list_rules:
        from ray_tpu.devtools import proto as proto_mod

    if args.list_rules:
        for rule in all_rules():
            scope = ", ".join(rule.path_markers) or "all files"
            print(f"{rule.id}  {rule.name}  [{scope}]")
            print(f"    {rule.description}")
            print(f"    hint: {rule.hint}")
        for rule in flow_mod.all_flow_rules():
            print(f"{rule.id}  {rule.name}  [whole-program, --flow]")
            print(f"    {rule.description}")
            print(f"    hint: {rule.hint}")
        for rule in trace_mod.all_trace_rules():
            scope = (
                "native, --trace" if rule.kind == "native"
                else "whole-program, --trace"
            )
            print(f"{rule.id}  {rule.name}  [{scope}]")
            print(f"    {rule.description}")
            print(f"    hint: {rule.hint}")
        for rule in proto_mod.all_proto_rules():
            print(f"{rule.id}  {rule.name}  [whole-program, --proto]")
            print(f"    {rule.description}")
            print(f"    hint: {rule.hint}")
        return 0

    paths = args.paths or ["ray_tpu"]
    only = args.rules.split(",") if args.rules else None
    if args.write_baseline and only:
        # a subset-rule run would overwrite (and drop) every other
        # rule's grandfathered fingerprints
        print(
            "rtlint: --write-baseline cannot be combined with --rules "
            "(it would discard baselined findings of unselected rules)",
            file=sys.stderr,
        )
        return 2
    if args.write_baseline and args.changed_only:
        print(
            "rtlint: --write-baseline cannot be combined with "
            "--changed-only (it would discard findings of unchanged "
            "files)",
            file=sys.stderr,
        )
        return 2

    file_filter = None
    if args.changed_only:
        file_filter = git_changed_files()
        if file_filter is None:
            print(
                "rtlint: --changed-only: git unavailable, scanning "
                "everything", file=sys.stderr,
            )

    # partition --rules between the active tiers
    only_file = only
    only_flow = None
    only_trace = None
    only_proto = None
    if args.flow or args.trace or args.proto:
        flow_ids = set(flow_mod.flow_rule_ids()) if args.flow else set()
        trace_ids = (
            set(trace_mod.trace_rule_ids()) if args.trace else set()
        )
        proto_ids = (
            set(proto_mod.proto_rule_ids()) if args.proto else set()
        )
        if only is not None:
            only_file = [
                r for r in only
                if r not in flow_ids and r not in trace_ids
                and r not in proto_ids
            ]
            only_flow = [r for r in only if r in flow_ids]
            only_trace = [r for r in only if r in trace_ids]
            only_proto = [r for r in only if r in proto_ids]

    findings: List[Finding] = []
    files_scanned = 0
    parse_errors: List[str] = []

    run_file_tier = only is None or only_file
    try:
        if run_file_tier:
            report = lint_paths(
                paths, rules=only_file, file_filter=file_filter
            )
            findings.extend(report.findings)
            files_scanned = report.files_scanned
            parse_errors.extend(report.parse_errors)
        if args.flow and (only is None or only_flow):
            flow_report = flow_mod.analyze_paths(paths, rules=only_flow)
            flow_findings = flow_report.findings
            if file_filter is not None:
                # the index stays whole-program (edges need every
                # module); only the *reporting* narrows to dirty files
                flow_findings = [
                    f for f in flow_findings
                    if os.path.abspath(f.path) in file_filter
                ]
            findings.extend(flow_findings)
            files_scanned = max(files_scanned, flow_report.files_indexed)
            parse_errors.extend(
                e for e in flow_report.parse_errors
                if e not in parse_errors
            )
        if args.trace and (only is None or only_trace):
            trace_report = trace_mod.analyze_paths(
                paths, rules=only_trace
            )
            trace_findings = trace_report.findings
            if file_filter is not None:
                # same narrowing as flow: planes need the whole index,
                # reporting narrows to dirty files
                trace_findings = [
                    f for f in trace_findings
                    if os.path.abspath(f.path) in file_filter
                ]
            findings.extend(trace_findings)
            files_scanned = max(
                files_scanned, trace_report.files_indexed
            )
            parse_errors.extend(
                e for e in trace_report.parse_errors
                if e not in parse_errors
            )
        if args.proto and (only is None or only_proto):
            proto_report = proto_mod.analyze_paths(
                paths, rules=only_proto
            )
            proto_findings = proto_report.findings
            if file_filter is not None:
                # same narrowing as flow/trace: the wire tables need
                # the whole index, reporting narrows to dirty files
                proto_findings = [
                    f for f in proto_findings
                    if os.path.abspath(f.path) in file_filter
                ]
            findings.extend(proto_findings)
            files_scanned = max(
                files_scanned, proto_report.files_indexed
            )
            parse_errors.extend(
                e for e in proto_report.parse_errors
                if e not in parse_errors
            )
    except ValueError as e:
        print(f"rtlint: {e}", file=sys.stderr)
        return 2
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    flow_baseline_path = args.flow_baseline
    if flow_baseline_path is None and args.flow:
        flow_baseline_path = flow_mod.DEFAULT_FLOW_BASELINE
    trace_baseline_path = args.trace_baseline
    if trace_baseline_path is None and args.trace:
        trace_baseline_path = trace_mod.DEFAULT_TRACE_BASELINE
    proto_baseline_path = args.proto_baseline
    if proto_baseline_path is None and args.proto:
        proto_baseline_path = proto_mod.DEFAULT_PROTO_BASELINE

    if args.write_baseline:
        # each tier owns its own baseline file, keyed by rule-id prefix
        file_findings = [
            f for f in findings
            if not f.rule.startswith(("RT2", "RT3", "RT4"))
        ]
        wrote = []
        write_baseline(file_findings, args.baseline)
        wrote.append(f"{len(file_findings)} finding(s) to {args.baseline}")
        if args.flow:
            flow_findings = [
                f for f in findings if f.rule.startswith("RT2")
            ]
            write_baseline(flow_findings, flow_baseline_path)
            wrote.append(f"{len(flow_findings)} to {flow_baseline_path}")
        if args.trace:
            trace_findings = [
                f for f in findings if f.rule.startswith("RT3")
            ]
            write_baseline(trace_findings, trace_baseline_path)
            wrote.append(
                f"{len(trace_findings)} to {trace_baseline_path}"
            )
        if args.proto:
            proto_findings = [
                f for f in findings if f.rule.startswith("RT4")
            ]
            write_baseline(proto_findings, proto_baseline_path)
            wrote.append(
                f"{len(proto_findings)} to {proto_baseline_path}"
            )
        print("rtlint: wrote " + " and ".join(wrote))
        return 0

    baseline: Counter = Counter()
    if not args.no_baseline:
        baseline += load_baseline(args.baseline)
        if args.flow:
            baseline += load_baseline(flow_baseline_path)
        if args.trace:
            baseline += load_baseline(trace_baseline_path)
        if args.proto:
            baseline += load_baseline(proto_baseline_path)
    new, grandfathered = split_baselined(findings, baseline)

    if args.format == "json":
        print(json.dumps(
            {
                "files_scanned": files_scanned,
                "parse_errors": parse_errors,
                "new_findings": [f.to_dict() for f in new],
                "baselined_findings": [
                    f.to_dict() for f in grandfathered
                ],
                "counts": dict(Counter(f.rule for f in new)),
            },
            indent=2,
        ))
    elif args.format == "sarif":
        from ray_tpu.devtools.sarif import render_sarif

        rules_meta = list(all_rules())
        if args.flow:
            rules_meta.extend(flow_mod.all_flow_rules())
        if args.trace:
            rules_meta.extend(trace_mod.all_trace_rules())
        if args.proto:
            rules_meta.extend(proto_mod.all_proto_rules())
        print(json.dumps(
            render_sarif(new, grandfathered, rules_meta), indent=2,
        ))
    else:
        for f in new:
            print(f.render())
        summary = (
            f"rtlint: {files_scanned} files, "
            f"{len(new)} new finding(s), "
            f"{len(grandfathered)} baselined"
        )
        if parse_errors:
            summary += f", {len(parse_errors)} unparseable"
            for e in parse_errors:
                print(f"rtlint: parse error: {e}", file=sys.stderr)
        print(summary)

    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
