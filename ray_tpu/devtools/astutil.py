"""Shared AST helpers for rtlint rules.

The rules work on *resolved qualified names*: ``from time import sleep``
and ``import time as t`` both resolve a call site to ``time.sleep``, so
pattern tables stay small and alias-proof.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple


class ImportMap:
    """Maps local binding names to the dotted path they were imported as.

    ``import numpy as np``      → ``np -> numpy``
    ``import os.path``          → ``os -> os``
    ``from time import sleep``  → ``sleep -> time.sleep``
    ``from . import rpc``       → ``rpc -> .rpc`` (relative kept as-is)
    """

    def __init__(self, tree: ast.AST):
        self.aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.aliases[a.asname] = a.name
                    else:
                        top = a.name.split(".")[0]
                        self.aliases[top] = top
            elif isinstance(node, ast.ImportFrom):
                module = ("." * node.level) + (node.module or "")
                for a in node.names:
                    if a.name == "*":
                        continue
                    bound = a.asname or a.name
                    self.aliases[bound] = (
                        f"{module}.{a.name}" if module else a.name
                    )

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Resolved dotted name for a Name/Attribute chain, or None when
        the chain is not rooted in a plain name (call results, subscripts).
        """
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        base = self.aliases.get(parts[0])
        if base is not None:
            parts[0] = base
        return ".".join(parts)


def dotted_text(node: ast.AST) -> Optional[str]:
    """The literal dotted text of a Name/Attribute chain (unresolved)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def decorator_callable(dec: ast.AST) -> ast.AST:
    """The callable expression of a decorator, unwrapping one call level:
    ``@ray_tpu.remote(num_cpus=1)`` → the ``ray_tpu.remote`` node."""
    return dec.func if isinstance(dec, ast.Call) else dec


def resolved_decorators(
    node: ast.AST, imports: ImportMap
) -> List[Tuple[str, ast.AST]]:
    """[(resolved_name, decorator_node)] for each decorator, skipping ones
    that do not resolve to a dotted name."""
    out = []
    for dec in getattr(node, "decorator_list", []):
        name = imports.resolve(decorator_callable(dec))
        if name is not None:
            out.append((name, dec))
    return out


def has_decorator(
    node: ast.AST, imports: ImportMap, names: Sequence[str],
    suffixes: Sequence[str] = (),
) -> bool:
    for resolved, _dec in resolved_decorators(node, imports):
        if resolved in names:
            return True
        if any(resolved.endswith(s) for s in suffixes):
            return True
    return False


def is_remote_decorated(node: ast.AST, imports: ImportMap) -> bool:
    """``@ray_tpu.remote`` / ``@remote`` / ``@rt.remote(...)`` shapes."""
    for resolved, _dec in resolved_decorators(node, imports):
        if resolved == "remote" or resolved.endswith(".remote"):
            return True
    return False


_LOCKISH = ("lock", "mutex", "cond", "sem")


def is_lockish(expr: ast.AST) -> bool:
    """Heuristic: a with-context expression that names a lock
    (``self._lock``, ``_init_lock``, ``cls._mu.acquire()``...)."""
    node = expr.func if isinstance(expr, ast.Call) else expr
    text = dotted_text(node)
    if text is None:
        return False
    return any(k in text.lower() for k in _LOCKISH)


class ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor that tracks the enclosing function/class stack and the
    set of ``with``-acquired lock contexts, so rules can ask "am I inside
    an async def?", "what class owns this method?", "is a lock held?".
    """

    def __init__(self):
        self.func_stack: List[ast.AST] = []
        self.class_stack: List[ast.ClassDef] = []
        self.with_lock_depth = 0

    # -- stack queries ---------------------------------------------------
    @property
    def current_function(self) -> Optional[ast.AST]:
        return self.func_stack[-1] if self.func_stack else None

    @property
    def current_class(self) -> Optional[ast.ClassDef]:
        return self.class_stack[-1] if self.class_stack else None

    @property
    def in_async_function(self) -> bool:
        """Nearest-enclosing-function semantics: a sync ``def`` nested
        inside an ``async def`` is NOT "in async" — those helpers are
        conventionally shipped to executor threads (run_in_executor,
        to_thread), where blocking is fine."""
        return bool(self.func_stack) and isinstance(
            self.func_stack[-1], ast.AsyncFunctionDef
        )

    @property
    def lock_held(self) -> bool:
        return self.with_lock_depth > 0

    # -- traversal -------------------------------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef):
        self.func_stack.append(node)
        self.enter_function(node)
        self.generic_visit(node)
        self.func_stack.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self.func_stack.append(node)
        self.enter_function(node)
        self.generic_visit(node)
        self.func_stack.pop()

    def visit_ClassDef(self, node: ast.ClassDef):
        self.class_stack.append(node)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_with(self, node):
        locked = any(is_lockish(item.context_expr) for item in node.items)
        if locked:
            self.with_lock_depth += 1
        self.generic_visit(node)
        if locked:
            self.with_lock_depth -= 1

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def enter_function(self, node: ast.AST):  # hook for subclasses
        pass


def call_name(call: ast.Call, imports: ImportMap) -> Optional[str]:
    return imports.resolve(call.func)


def body_contains_call(body: List[ast.stmt], imports: ImportMap,
                       names: Sequence[str],
                       suffixes: Sequence[str] = ()) -> bool:
    """Any call in the statement list (recursively) resolving to one of
    ``names`` (exact) or ``suffixes`` (endswith)?"""
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                resolved = imports.resolve(node.func)
                if resolved is None:
                    continue
                if resolved in names:
                    return True
                if any(resolved.endswith(s) for s in suffixes):
                    return True
    return False


def body_contains_raise(body: List[ast.stmt]) -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Raise):
                return True
    return False
