"""rtflow engine: builds the program index over a path set / source
dict, runs the RT2xx rules, and funnels findings through the SAME
suppression + fingerprint machinery as the per-file tier, so
``# rtlint: disable-next=RT201`` comments and baseline entries behave
identically across both tiers.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

from ray_tpu.devtools.lint import (
    Finding,
    _apply_suppressions,
    iter_py_files,
)

DEFAULT_FLOW_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "flow_baseline.json"
)


class FlowRule:
    """Whole-program rule: ``check(index)`` walks the index and reports
    through ``add`` into the owning module's context (so per-module
    suppression comments apply)."""

    id: str = ""
    name: str = ""
    description: str = ""
    hint: str = ""

    def check(self, index) -> None:
        raise NotImplementedError

    def add(self, module, node, message=None, hint=None) -> None:
        module.ctx.add(self, node, message=message, hint=hint)


def all_flow_rules() -> List[FlowRule]:
    # imported here: the rule modules import FlowRule from this module
    from ray_tpu.devtools.flow.capture import UnserializableCapture
    from ray_tpu.devtools.flow.collective import RankDivergentCollective
    from ray_tpu.devtools.flow.deadlock import ActorDeadlock
    from ray_tpu.devtools.flow.refleak import ObjectRefLeak

    return [
        ActorDeadlock(),
        ObjectRefLeak(),
        UnserializableCapture(),
        RankDivergentCollective(),
    ]


def flow_rule_ids() -> Tuple[str, ...]:
    return tuple(r.id for r in all_flow_rules())


@dataclasses.dataclass
class FlowReport:
    findings: List[Finding]
    files_indexed: int
    parse_errors: List[str]


def _select(rules: Optional[Sequence[str]]) -> List[FlowRule]:
    selected = all_flow_rules()
    if rules is not None:
        wanted = set(rules)
        unknown = wanted - {r.id for r in selected}
        if unknown:
            raise ValueError(f"unknown flow rule id(s): {sorted(unknown)}")
        selected = [r for r in selected if r.id in wanted]
    return selected


def analyze_index(index, rules: Optional[Sequence[str]] = None) -> List[Finding]:
    for rule in _select(rules):
        rule.check(index)
    findings: List[Finding] = []
    for mname in sorted(index.modules):
        findings.extend(_apply_suppressions(index.modules[mname].ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_sources(
    files: Dict[str, str], rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Fixture/test entry point: ``files`` maps package-relative paths
    (``pkg/mod.py``) to sources; paths double as module names."""
    from ray_tpu.devtools.flow.index import (
        build_index,
        module_name_from_relpath,
    )

    entries = []
    for path in sorted(files):
        norm = path.replace(os.sep, "/")
        tree = ast.parse(files[path], filename=norm)
        entries.append(
            (norm, module_name_from_relpath(norm), files[path], tree)
        )
    index = build_index(entries)
    return analyze_index(index, rules=rules)


def _package_base(path: str) -> str:
    """Walk up from a scanned root past every ``__init__.py``-bearing
    directory, so ``lint --flow ray_tpu/rllib`` (or a single
    ``ray_tpu/rllib/impala.py``) still derives the real
    ``ray_tpu.rllib.*`` module names — anything else breaks qualnames
    and relative-import resolution and the tier silently under-reports."""
    base = os.path.dirname(os.path.abspath(path))
    while base and os.path.isfile(os.path.join(base, "__init__.py")):
        parent = os.path.dirname(base)
        if parent == base:
            break
        base = parent
    return base


def _collect_entries(paths: Sequence[str]):
    """(finding_path, module_name, fs_path) per .py file.  Module names
    are derived relative to each scanned root's enclosing package base,
    so ``lint ray_tpu`` from the repo root yields real ``ray_tpu.*``
    names and a tmp-dir package yields ``pkg.*`` names."""
    out = []
    seen = set()
    for p in paths:
        base = _package_base(p)
        for fpath in iter_py_files([p]):
            apath = os.path.abspath(fpath)
            if apath in seen:
                continue
            seen.add(apath)
            rel_for_name = os.path.relpath(apath, base)
            finding_path = fpath
            if os.path.isabs(fpath):
                candidate = os.path.relpath(fpath)
                if not candidate.startswith(".."):
                    finding_path = candidate
            finding_path = finding_path.replace(os.sep, "/")
            out.append((finding_path, rel_for_name, apath))
    return out


def analyze_paths(
    paths: Sequence[str], rules: Optional[Sequence[str]] = None
) -> FlowReport:
    from ray_tpu.devtools.flow.index import (
        build_index,
        module_name_from_relpath,
    )

    entries = []
    errors: List[str] = []
    for finding_path, rel_for_name, apath in _collect_entries(paths):
        try:
            with open(apath, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=finding_path)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            # RT000 is the per-file tier's finding; the flow tier just
            # indexes what parses and reports the rest as errors
            errors.append(f"{finding_path}: {e}")
            continue
        entries.append((
            finding_path,
            module_name_from_relpath(rel_for_name),
            source,
            tree,
        ))
    index = build_index(entries)
    findings = analyze_index(index, rules=rules)
    return FlowReport(findings, len(entries), errors)
