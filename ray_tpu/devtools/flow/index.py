"""Whole-program symbol table + call graph for the rtflow tier.

The index models the package's *remote surface* rather than full Python
semantics: which classes are actors, which functions are remote, what
every ``X.remote(...)`` / ``get()`` / collective call site resolves to,
and the (cheap, flow-insensitive) types of actor handles held in locals,
parameters, and ``self`` attributes.  Rules consume these facts instead
of re-deriving AST shapes.

Known soundness limits (documented in docs/architecture.md): dynamic
dispatch through ``getattr``/dicts of handles, handles returned from
un-annotated factories, and re-exports deeper than four hops are not
resolved — an unresolved site produces *no* edge (precision over
recall, same contract as the RT1xx tier).
"""

from __future__ import annotations

import ast
import builtins
import os
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from ray_tpu.devtools import astutil
from ray_tpu.devtools.lint import ModuleContext


def module_name_from_relpath(rel: str) -> str:
    """``pkg/sub/mod.py`` -> ``pkg.sub.mod``; ``pkg/__init__.py`` -> ``pkg``."""
    rel = rel.replace(os.sep, "/")
    if rel.endswith(".py"):
        rel = rel[:-3]
    parts = [p for p in rel.split("/") if p and p != "."]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def iter_nodes_skip_nested(body: Sequence[ast.stmt]) -> Iterator[ast.AST]:
    """Preorder, source-ordered walk of a function body that yields (but
    does not descend into) nested function/class definitions — their
    bodies are separate scopes and must not contribute facts to the
    enclosing function."""
    stack: List[ast.AST] = list(reversed(list(body)))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        stack.extend(reversed(list(ast.iter_child_nodes(node))))


def free_names(fn_node: ast.AST) -> Set[str]:
    """Names the function body loads but never binds (params, assigns,
    imports, defs, ``except .. as``, comprehension targets all bind).
    Over-approximates bindings across nested scopes, so the result
    under-reports rather than false-positives."""
    bound: Set[str] = set()
    loads: Set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            params = (
                list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
            )
            if a.vararg:
                params.append(a.vararg)
            if a.kwarg:
                params.append(a.kwarg)
            for arg in params:
                bound.add(arg.arg)
            bound.add(node.name)
        elif isinstance(node, ast.ClassDef):
            bound.add(node.name)
        elif isinstance(node, ast.Name):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                bound.add(node.id)
            else:
                loads.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                bound.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
    return {n for n in loads - bound if not hasattr(builtins, n)}


def has_bounded_timeout(call: ast.Call) -> bool:
    """Same contract as RT104: an explicit non-None ``timeout=``
    degrades a potential deadlock to latency."""
    for kw in call.keywords:
        if kw.arg == "timeout":
            return not (
                isinstance(kw.value, ast.Constant)
                and kw.value.value is None
            )
    return False


_BLOCKING_GET = {"ray_tpu.get", "ray_tpu.wait"}
_RUNTIME_RECEIVERS = {"rt"}


class ModuleInfo:
    """One source file plus its resolution environment."""

    def __init__(
        self,
        name: str,
        path: str,
        source: str,
        tree: ast.AST,
        is_package: bool,
    ):
        self.name = name
        self.path = path
        self.source = source
        self.tree = tree
        self.is_package = is_package
        self.ctx = ModuleContext(path, source, tree)
        self.imports = self.ctx.imports
        # module-level simple assignments + defined names, for global
        # provenance (RT202/RT203) and local-symbol qualification
        self.top_assigns: Dict[str, ast.expr] = {}
        self.top_defs: Set[str] = set()
        for stmt in tree.body:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                self.top_defs.add(stmt.name)
            elif isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.top_assigns[t.id] = stmt.value
                        self.top_defs.add(t.id)
            elif isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                if stmt.value is not None:
                    self.top_assigns[stmt.target.id] = stmt.value
                self.top_defs.add(stmt.target.id)

    def resolve_relative(self, raw: str) -> str:
        """``.rpc`` seen from ``pkg.core.worker`` -> ``pkg.core.rpc``."""
        level = len(raw) - len(raw.lstrip("."))
        rest = raw[level:]
        parts = self.name.split(".")
        if not self.is_package:
            parts = parts[:-1]
        if level > 1:
            parts = parts[: max(0, len(parts) - (level - 1))]
        base = ".".join(parts)
        if not base:
            return rest
        return f"{base}.{rest}" if rest else base

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Absolute dotted name of a Name/Attribute chain: import-alias
        substitution, relative-import normalization, and qualification
        of module-local top-level symbols."""
        raw = self.imports.resolve(node)
        if raw is None:
            return None
        if raw.startswith("."):
            return self.resolve_relative(raw)
        head = raw.split(".", 1)[0]
        if head in self.top_defs and head not in self.imports.aliases:
            return f"{self.name}.{raw}"
        return raw


class FunctionInfo:
    def __init__(
        self,
        qualname: str,
        module: ModuleInfo,
        node: ast.AST,
        owner: Optional["ClassInfo"] = None,
    ):
        self.qualname = qualname
        self.module = module
        self.node = node
        self.owner = owner
        self.name = node.name
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        self.is_remote = astutil.is_remote_decorated(
            node, module.imports
        ) or (owner is not None and owner.is_actor)

    @property
    def short(self) -> str:
        if self.owner is not None:
            return f"{self.owner.short}.{self.name}"
        return self.name


class ClassInfo:
    def __init__(self, qualname: str, module: ModuleInfo, node: ast.ClassDef):
        self.qualname = qualname
        self.module = module
        self.node = node
        self.name = node.name
        self.is_actor = astutil.is_remote_decorated(node, module.imports)
        self.methods: Dict[str, FunctionInfo] = {}
        self._attr_types: Optional[Dict[str, str]] = None

    @property
    def short(self) -> str:
        return self.name


class GetSite:
    """A blocking ``get``/``wait`` call site inside one function."""

    def __init__(self, node: ast.Call, bounded: bool):
        self.node = node
        self.bounded = bounded


class FunctionFacts:
    """Flow-insensitive facts for one function body (nested defs
    excluded — they are separate scopes / separate index entries)."""

    def __init__(self):
        # var -> actor class qualname (a held handle)
        self.env: Dict[str, str] = {}
        # var -> ('ref-actor', clsqual, meth) | ('ref-task', fnqual)
        #      | ('ref-unknown',)
        self.ref_targets: Dict[str, tuple] = {}
        self.gets: List[GetSite] = []
        # (call node, target tuple) for every ref-producing .remote()
        self.remote_calls: List[Tuple[ast.Call, tuple]] = []
        self.nested_defs: List[ast.AST] = []
        # var -> last simple-assignment value expr (RT203 provenance)
        self.local_assigns: Dict[str, ast.expr] = {}


class ProgramIndex:
    """Symbol table + remote-surface facts for a set of modules."""

    def __init__(self):
        self.modules: Dict[str, ModuleInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self._facts: Dict[str, FunctionFacts] = {}

    # -- construction ----------------------------------------------------

    def add_module(
        self, name: str, path: str, source: str, tree: ast.AST
    ) -> ModuleInfo:
        is_package = path.replace(os.sep, "/").endswith("/__init__.py")
        mod = ModuleInfo(name, path, source, tree, is_package)
        self.modules[name] = mod
        return mod

    def finalize(self) -> None:
        """Register every top-level class/function after all modules are
        added, so cross-module resolution sees the full table."""
        for mname in sorted(self.modules):
            mod = self.modules[mname]
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.ClassDef):
                    cls = ClassInfo(f"{mname}.{stmt.name}", mod, stmt)
                    self.classes[cls.qualname] = cls
                    for item in stmt.body:
                        if isinstance(
                            item, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            fi = FunctionInfo(
                                f"{cls.qualname}.{item.name}",
                                mod, item, owner=cls,
                            )
                            cls.methods[item.name] = fi
                            self.functions[fi.qualname] = fi
                elif isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    fi = FunctionInfo(f"{mname}.{stmt.name}", mod, stmt)
                    self.functions[fi.qualname] = fi

    # -- resolution ------------------------------------------------------

    def canonical(self, dotted: Optional[str]) -> Optional[str]:
        """Chase re-exports (``from impl import Worker`` in a package
        ``__init__``) up to four hops to the defining module's name."""
        if dotted is None:
            return None
        for _hop in range(4):
            if dotted in self.classes or dotted in self.functions:
                return dotted
            parts = dotted.split(".")
            rewritten = False
            for i in range(len(parts) - 1, 0, -1):
                prefix = ".".join(parts[:i])
                mod = self.modules.get(prefix)
                if mod is None:
                    continue
                alias = mod.imports.aliases.get(parts[i])
                if alias is not None:
                    if alias.startswith("."):
                        alias = mod.resolve_relative(alias)
                    dotted = ".".join([alias] + parts[i + 1:])
                    rewritten = True
                break
            if not rewritten:
                return dotted
        return dotted

    def resolve_name(
        self, module: ModuleInfo, node: ast.AST
    ) -> Optional[str]:
        return self.canonical(module.resolve(node))

    def class_from_string(
        self, module: ModuleInfo, s: str
    ) -> Optional[ClassInfo]:
        s = s.strip()
        if not s or not all(p.isidentifier() for p in s.split(".")):
            return None
        if "." not in s:
            local = self.classes.get(f"{module.name}.{s}")
            if local is not None:
                return local
        else:
            # already fully qualified ("pkg.b.Beta" in a string ann)
            direct = self.classes.get(self.canonical(s))
            if direct is not None:
                return direct
        head, _, rest = s.partition(".")
        base = module.imports.aliases.get(head)
        if base is None:
            dotted = f"{module.name}.{s}"
        else:
            if base.startswith("."):
                base = module.resolve_relative(base)
            dotted = f"{base}.{rest}" if rest else base
        return self.classes.get(self.canonical(dotted))

    def class_from_annotation(
        self, module: ModuleInfo, ann: Optional[ast.AST]
    ) -> Optional[ClassInfo]:
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            return self.class_from_string(module, ann.value)
        if isinstance(ann, ast.Subscript):
            base = module.resolve(ann.value)
            if base in ("typing.Optional", "Optional", "typing.Union"):
                sl = ann.slice
                elts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
                for e in elts:
                    cls = self.class_from_annotation(module, e)
                    if cls is not None:
                        return cls
            return None
        dotted = self.resolve_name(module, ann)
        return self.classes.get(dotted) if dotted else None

    # -- handle / ref typing ---------------------------------------------

    def param_types(self, fn: FunctionInfo) -> Dict[str, str]:
        out: Dict[str, str] = {}
        a = fn.node.args
        for arg in list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs):
            cls = self.class_from_annotation(fn.module, arg.annotation)
            if cls is not None:
                out[arg.arg] = cls.qualname
        return out

    def attr_types(self, cls: ClassInfo) -> Dict[str, str]:
        """``self.<attr>`` -> actor class qualname, gathered across all
        methods from ``self.x = <annotated param>``, ``self.x =
        Cls.remote(...)``, and annotated ``self.x: Cls`` assigns."""
        if cls._attr_types is not None:
            return cls._attr_types
        cls._attr_types = out = {}
        for mname in sorted(cls.methods):
            meth = cls.methods[mname]
            params = self.param_types(meth)
            for node in ast.walk(meth.node):
                target = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    t = node.target
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        c = self.class_from_annotation(
                            cls.module, node.annotation
                        )
                        if c is not None:
                            out.setdefault(t.attr, c.qualname)
                    continue
                else:
                    continue
                if not (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    continue
                if isinstance(value, ast.Name) and value.id in params:
                    out.setdefault(target.attr, params[value.id])
                else:
                    t2 = self.remote_target(cls.module, value, None, cls)
                    if t2 is not None and t2[0] == "handle":
                        out.setdefault(target.attr, t2[1])
        return out

    def receiver_type(
        self,
        module: ModuleInfo,
        expr: ast.AST,
        env: Optional[Dict[str, str]],
        cls: Optional[ClassInfo],
    ) -> Optional[str]:
        if isinstance(expr, ast.Name):
            if env is not None:
                return env.get(expr.id)
            return None
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and cls is not None
        ):
            return self.attr_types(cls).get(expr.attr)
        return None

    def remote_target(
        self,
        module: ModuleInfo,
        expr: ast.AST,
        env: Optional[Dict[str, str]],
        cls: Optional[ClassInfo],
    ) -> Optional[tuple]:
        """Classify a ``....remote(...)`` expression.

        Returns ``('handle', clsqual)`` for actor construction,
        ``('ref-actor', clsqual, meth)`` for a resolved actor-method
        submission, ``('ref-task', fnqual)`` for a remote-function
        submission, ``('ref-unknown',)`` for an unresolvable submission,
        or None when ``expr`` is not a remote submission at all."""
        if not (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "remote"
        ):
            return None
        base = expr.func.value
        if (
            isinstance(base, ast.Call)
            and isinstance(base.func, ast.Attribute)
            and base.func.attr == "options"
        ):
            base = base.func.value
        dotted = self.resolve_name(module, base)
        if dotted is not None:
            if dotted in self.classes:
                return ("handle", dotted)
            if dotted in self.functions:
                return ("ref-task", dotted)
        if isinstance(base, ast.Attribute):
            recv = self.receiver_type(module, base.value, env, cls)
            if recv is not None:
                return ("ref-actor", recv, base.attr)
            return ("ref-unknown",)
        if isinstance(base, ast.Name) and env is not None:
            recv = env.get(base.id)
            if recv is not None:
                # a bare handle called .remote() — actor __call__;
                # treat as a submission into that actor
                return ("ref-actor", recv, "__call__")
        return ("ref-unknown",)

    def _is_blocking_get(
        self, module: ModuleInfo, call: ast.Call
    ) -> bool:
        resolved = self.resolve_name(module, call.func)
        if resolved in _BLOCKING_GET:
            return True
        return (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in ("get", "wait")
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id in _RUNTIME_RECEIVERS
        )

    # -- per-function facts ----------------------------------------------

    def facts(self, fn: FunctionInfo) -> FunctionFacts:
        cached = self._facts.get(fn.qualname)
        if cached is not None:
            return cached
        f = FunctionFacts()
        module, cls = fn.module, fn.owner
        f.env.update(self.param_types(fn))
        for node in iter_nodes_skip_nested(fn.node.body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                f.nested_defs.append(node)
                continue
            if isinstance(node, ast.ClassDef):
                continue
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                name, value = node.targets[0].id, node.value
                f.local_assigns[name] = value
                target = self.remote_target(module, value, f.env, cls)
                if target is not None:
                    if target[0] == "handle":
                        f.env[name] = target[1]
                    else:
                        f.ref_targets[name] = target
                elif isinstance(value, ast.Name):
                    if value.id in f.env:
                        f.env[name] = f.env[value.id]
                    if value.id in f.ref_targets:
                        f.ref_targets[name] = f.ref_targets[value.id]
                elif (
                    isinstance(value, ast.Attribute)
                    and isinstance(value.value, ast.Name)
                    and value.value.id == "self"
                    and cls is not None
                ):
                    at = self.attr_types(cls).get(value.attr)
                    if at is not None:
                        f.env[name] = at
                else:
                    ct = self.container_ref_target(module, value, f.env, cls)
                    if ct is not None:
                        f.ref_targets[name] = ct
            elif isinstance(node, ast.Call):
                if self._is_blocking_get(module, node):
                    f.gets.append(
                        GetSite(node, has_bounded_timeout(node))
                    )
                else:
                    target = self.remote_target(module, node, f.env, cls)
                    if target is not None and target[0] != "handle":
                        f.remote_calls.append((node, target))
        self._facts[fn.qualname] = f
        return f

    def container_ref_target(
        self,
        module: ModuleInfo,
        expr: ast.AST,
        env: Optional[Dict[str, str]],
        cls: Optional[ClassInfo],
    ) -> Optional[tuple]:
        """First ref target produced anywhere inside a container
        expression (list/dict/set literal or comprehension) — used to
        give ``refs = [h.m.remote() for ...]`` a ref provenance."""
        if not isinstance(
            expr,
            (ast.List, ast.Tuple, ast.Set, ast.Dict,
             ast.ListComp, ast.SetComp, ast.DictComp,
             ast.GeneratorExp),
        ):
            return None
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                target = self.remote_target(module, sub, env, cls)
                if target is not None and target[0] != "handle":
                    return target
        return None

    def is_ref_expr(
        self,
        module: ModuleInfo,
        expr: ast.AST,
        facts: FunctionFacts,
        cls: Optional[ClassInfo],
    ) -> bool:
        """Does this expression produce (or contain) an ObjectRef?"""
        if isinstance(expr, ast.Name):
            return expr.id in facts.ref_targets
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                target = self.remote_target(module, sub, facts.env, cls)
                if target is not None and target[0] != "handle":
                    return True
            elif (
                isinstance(sub, ast.Name)
                and isinstance(sub.ctx, ast.Load)
                and sub.id in facts.ref_targets
            ):
                return True
        return False


def build_index(
    entries: Sequence[Tuple[str, str, str, ast.AST]]
) -> ProgramIndex:
    """entries: (finding_path, module_name, source, tree)."""
    index = ProgramIndex()
    for path, modname, source, tree in entries:
        index.add_module(modname, path, source, tree)
    index.finalize()
    return index
