"""RT204 rank-divergent-collective: collective sequences that differ
across rank-conditional branches.

Symmetric collectives (allreduce / allgather / reducescatter /
broadcast / broadcast_object / barrier and their ``*_async`` twins)
require every rank of the group to make the SAME sequence of calls.  A
rank-guarded branch that performs one more (or one fewer) collective
than its sibling leaves the other ranks parked in a ring step that
never completes — the mismatched-allreduce hang, which surfaces as a
collective timeout minutes later with no pointer at the guilty branch.

The comparison is interprocedural: each branch's collective sequence is
computed through helper calls using memoized whole-function summaries
over the call graph (cycle-safe), so ``if rank == 0: _report()`` is
flagged when ``_report`` transitively allreduces.  Point-to-point
``send``/``recv`` are intentionally rank-divergent (the PS pattern) and
never counted.  Nested rank-conditionals are flagged at their own
level, not re-reported by enclosing comparisons.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ray_tpu.devtools.flow.engine import FlowRule
from ray_tpu.devtools.flow.index import (
    FunctionInfo,
    ProgramIndex,
    iter_nodes_skip_nested,
)

_COLLECTIVE_PKG = "ray_tpu.util.collective"
_SYMMETRIC_OPS = {
    "allreduce", "allgather", "reducescatter", "broadcast",
    "broadcast_object", "barrier",
}

# a branch whose op sequence is data-dependent (a nested NON-rank
# conditional diverges internally): participates in branch comparison
# as an ordinary token, so `if rank == 0: (if debug: barrier())` still
# compares unequal to the empty else-branch, while two symmetric
# data-dependent branches compare equal and stay silent
_UNKNOWN = "?"

# a nested RANK-conditional diverged: that If gets its own finding, so
# enclosing comparisons skip instead of double-reporting
_REPORTED = "!"

# call-graph expansion bound: summaries deeper than this contribute
# nothing (keeps pathological 500-deep helper chains out of the Python
# recursion limit; real divergence sits within a few hops of the rank
# conditional)
_MAX_DEPTH = 16


def _op_of(resolved: Optional[str]) -> Optional[str]:
    if not resolved or not resolved.startswith(_COLLECTIVE_PKG + "."):
        return None
    op = resolved.rsplit(".", 1)[1]
    if op.endswith("_async"):
        op = op[: -len("_async")]
    return op if op in _SYMMETRIC_OPS else None


def _is_rank_conditional(test: ast.AST, module, index) -> bool:
    """The branch condition depends on the caller's rank: an identifier
    mentioning ``rank`` or a ``get_rank()`` / ``process_index()`` call."""
    for node in ast.walk(test):
        ident = None
        if isinstance(node, ast.Name):
            ident = node.id
        elif isinstance(node, ast.Attribute):
            ident = node.attr
        if ident is not None:
            low = ident.lower()
            if "rank" in low or low == "process_index":
                return True
    return False


class RankDivergentCollective(FlowRule):
    id = "RT204"
    name = "rank-divergent-collective"
    description = (
        "symmetric collective op sequence differs across a "
        "rank-conditional branch — non-participating ranks hang"
    )
    hint = (
        "make every rank issue the same collective sequence (hoist the "
        "op out of the branch, or use broadcast with src= in both arms)"
    )

    def check(self, index: ProgramIndex) -> None:
        self._summaries: Dict[str, Tuple[str, ...]] = {}
        self._in_progress: Set[str] = set()
        self._index = index
        for fq in sorted(index.functions):
            fn = index.functions[fq]
            for node in iter_nodes_skip_nested(fn.node.body):
                if not isinstance(node, ast.If):
                    continue
                if not _is_rank_conditional(node.test, fn.module, index):
                    continue
                body_seq = self._seq(fn, node.body, 0)
                else_seq = self._seq(fn, node.orelse, 0)
                if _REPORTED in body_seq or _REPORTED in else_seq:
                    continue  # nested rank-divergence has its own finding
                if body_seq == else_seq:
                    continue
                self.add(
                    fn.module, node,
                    message=(
                        f"rank-divergent-collective: in `{fn.short}` "
                        f"the rank-conditional branches issue different "
                        f"collective sequences "
                        f"([{', '.join(body_seq) or 'none'}] vs "
                        f"[{', '.join(else_seq) or 'none'}]) — the "
                        f"ranks taking the poorer branch hang the group"
                    ),
                )

    # -- sequence computation --------------------------------------------

    def _summary(self, fn: FunctionInfo, depth: int) -> Tuple[str, ...]:
        cached = self._summaries.get(fn.qualname)
        if cached is not None:
            return cached
        if depth > _MAX_DEPTH:
            return ()  # over the expansion bound: uncached, contribute nothing
        if fn.qualname in self._in_progress:
            return ()  # recursion: contribute nothing (cycle-safe)
        self._in_progress.add(fn.qualname)
        try:
            seq = self._seq(fn, fn.node.body, depth)
        finally:
            self._in_progress.discard(fn.qualname)
        self._summaries[fn.qualname] = seq
        return seq

    def _seq(
        self, fn: FunctionInfo, stmts: Sequence[ast.stmt], depth: int
    ) -> Tuple[str, ...]:
        out: List[str] = []
        for stmt in stmts:
            if isinstance(
                stmt,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue
            if isinstance(stmt, ast.If):
                a = self._seq(fn, stmt.body, depth)
                b = self._seq(fn, stmt.orelse, depth)
                if a == b:
                    out.extend(a)
                elif a or b:
                    out.append(
                        _REPORTED
                        if _is_rank_conditional(
                            stmt.test, fn.module, self._index
                        )
                        else _UNKNOWN
                    )
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                out.extend(self._expr_ops(fn, getattr(stmt, "iter", None)
                                          or getattr(stmt, "test", None),
                                          depth))
                out.extend(self._seq(fn, stmt.body, depth))
                out.extend(self._seq(fn, stmt.orelse, depth))
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    out.extend(
                        self._expr_ops(fn, item.context_expr, depth)
                    )
                out.extend(self._seq(fn, stmt.body, depth))
                continue
            if isinstance(stmt, ast.Try):
                out.extend(self._seq(fn, stmt.body, depth))
                for handler in stmt.handlers:
                    out.extend(self._seq(fn, handler.body, depth))
                out.extend(self._seq(fn, stmt.orelse, depth))
                out.extend(self._seq(fn, stmt.finalbody, depth))
                continue
            out.extend(self._expr_ops(fn, stmt, depth))
        return tuple(out)

    def _expr_ops(
        self, fn: FunctionInfo, node: Optional[ast.AST], depth: int
    ) -> Tuple[str, ...]:
        """Ops performed by the expressions of one simple statement,
        expanding calls to indexed functions via their summaries."""
        if node is None:
            return ()
        out: List[str] = []
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            resolved = self._index.resolve_name(fn.module, sub.func)
            op = _op_of(resolved)
            if op is not None:
                out.append(op)
                continue
            callee = self._callee(fn, sub, resolved)
            if callee is not None:
                out.extend(self._summary(callee, depth + 1))
        return tuple(out)

    def _callee(
        self, fn: FunctionInfo, call: ast.Call, resolved: Optional[str]
    ) -> Optional[FunctionInfo]:
        if resolved is not None:
            callee = self._index.functions.get(resolved)
            if callee is not None:
                return callee
        func = call.func
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
            and fn.owner is not None
        ):
            return fn.owner.methods.get(func.attr)
        return None
