"""RT203 unserializable-capture: remote closures over process-local
resources.

A remote function is cloudpickled at submission.  A closure (or
argument) that drags along a ``threading.Lock``, an event loop, an open
socket/file, an HTTP/grpc client, or a live jax Array either fails to
pickle outright (TypeError at submission — the lucky case) or pickles a
*copy* whose semantics are silently wrong on the worker: a copied lock
guards nothing across processes, a copied client reconnects per task,
a captured device Array pins device memory on the driver and ships a
stale snapshot.

Three capture channels are checked:

- module-level globals constructed from a known process-local ctor and
  read (free-variable) inside a ``@remote`` function or actor method;
- locals of an enclosing function captured by a *nested* ``@remote``
  definition (true closure cells — always serialized);
- values with process-local provenance passed as arguments to a
  ``.remote(...)`` submission (jax Arrays are exempt here: passing an
  array as an argument is the supported path, it is the *closure*
  capture that pins the device buffer).
"""

from __future__ import annotations

import ast
from typing import Dict, Optional, Tuple

from ray_tpu.devtools import astutil
from ray_tpu.devtools.flow.engine import FlowRule
from ray_tpu.devtools.flow.index import ProgramIndex, free_names

# resolved ctor -> category label
_BAD_CTORS: Dict[str, str] = {
    "threading.Lock": "lock",
    "threading.RLock": "lock",
    "threading.Condition": "lock",
    "threading.Semaphore": "lock",
    "threading.BoundedSemaphore": "lock",
    "threading.Event": "lock",
    "threading.Barrier": "lock",
    "threading.local": "thread-local state",
    "multiprocessing.Lock": "lock",
    "multiprocessing.RLock": "lock",
    "asyncio.Lock": "asyncio primitive",
    "asyncio.Event": "asyncio primitive",
    "asyncio.Condition": "asyncio primitive",
    "asyncio.Semaphore": "asyncio primitive",
    "asyncio.Queue": "asyncio primitive",
    "asyncio.get_event_loop": "event loop",
    "asyncio.new_event_loop": "event loop",
    "asyncio.get_running_loop": "event loop",
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "open": "open file handle",
    "io.open": "open file handle",
    "grpc.insecure_channel": "grpc channel",
    "grpc.secure_channel": "grpc channel",
    "requests.Session": "http client",
    "httpx.Client": "http client",
    "httpx.AsyncClient": "http client",
    "jax.device_put": "live jax Array",
    "jax.numpy.array": "live jax Array",
    "jax.numpy.asarray": "live jax Array",
    "jax.numpy.zeros": "live jax Array",
    "jax.numpy.ones": "live jax Array",
    "jax.numpy.full": "live jax Array",
    "jax.numpy.arange": "live jax Array",
    "jax.random.PRNGKey": "live jax Array",
}

# categories that are fine as *arguments* (serialized via the object
# store by design) but not as closure captures
_ARG_EXEMPT_CATEGORIES = {"live jax Array"}


class UnserializableCapture(FlowRule):
    id = "RT203"
    name = "unserializable-capture"
    description = (
        "remote closure captures (or remote call ships) a process-local "
        "resource: lock, event loop, socket, open file, client, or "
        "live jax Array"
    )
    hint = (
        "construct the resource inside the remote body (or in the "
        "actor's __init__ on the worker); pass plain data across the "
        "boundary"
    )

    def _classify(
        self, module, expr: Optional[ast.AST]
    ) -> Optional[Tuple[str, str]]:
        """(category, ctor name) when the expr constructs a known
        process-local resource."""
        if not isinstance(expr, ast.Call):
            return None
        resolved = module.resolve(expr.func)
        if resolved is None:
            return None
        cat = _BAD_CTORS.get(resolved)
        if cat is None:
            return None
        return cat, resolved

    def check(self, index: ProgramIndex) -> None:
        for fq in sorted(index.functions):
            fn = index.functions[fq]
            module = fn.module

            # channel 1: module-global resources read from remote bodies
            if fn.is_remote:
                self._check_captures(
                    index, module, fn.node, module.top_assigns,
                    where=f"remote `{fn.short}`",
                )

            facts = index.facts(fn)

            # channel 2: nested @remote defs closing over enclosing
            # locals (true closure cells)
            for nested in facts.nested_defs:
                if not isinstance(
                    nested, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if not astutil.is_remote_decorated(
                    nested, module.imports
                ):
                    continue
                enclosing: Dict[str, ast.expr] = dict(module.top_assigns)
                enclosing.update(facts.local_assigns)
                self._check_captures(
                    index, module, nested, enclosing,
                    where=f"nested remote `{nested.name}`",
                )

            # channel 3: process-local values shipped as .remote() args
            for call, _target in facts.remote_calls:
                args = list(call.args) + [
                    kw.value for kw in call.keywords
                ]
                for arg in args:
                    if not isinstance(arg, ast.Name):
                        continue
                    prov = facts.local_assigns.get(arg.id)
                    if prov is None:
                        prov = module.top_assigns.get(arg.id)
                    hit = self._classify(module, prov)
                    if hit is None:
                        continue
                    cat, ctor = hit
                    if cat in _ARG_EXEMPT_CATEGORIES:
                        continue
                    self.add(
                        module, call,
                        message=(
                            f"unserializable-capture: `{arg.id}` "
                            f"(a {cat} from `{ctor}(...)`) is shipped "
                            f"as a `.remote()` argument — it either "
                            f"fails to pickle or arrives as a useless "
                            f"process-local copy"
                        ),
                    )

    def _check_captures(
        self, index, module, fn_node, provenance, where: str
    ) -> None:
        for name in sorted(free_names(fn_node)):
            hit = self._classify(module, provenance.get(name))
            if hit is None:
                continue
            cat, ctor = hit
            self.add(
                module, fn_node,
                message=(
                    f"unserializable-capture: {where} captures "
                    f"`{name}` (a {cat} from `{ctor}(...)`) — "
                    f"cloudpickle ships a process-local copy whose "
                    f"semantics are wrong on the worker"
                ),
            )
