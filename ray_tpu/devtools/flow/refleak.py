"""RT202 objectref-leak: refs stored into long-lived containers that no
reachable code ever drains.

Every live ObjectRef pins its object in the shm arena (and its lineage
in the GCS).  A ref appended into ``self._pending`` that no method of
the class ever gets / waits / pops / clears / returns is permanently
pinned — arena capacity shrinks monotonically until puts start
spilling, which surfaces hours later as a throughput cliff on an
unrelated workload.

Store sites recognized: mutator calls (``self.x.append(ref)``,
``.add``, ``.extend``, ``.insert``, ``.setdefault``, ``.update``),
subscript stores (``self.x[k] = ref``), and whole-container assigns
whose value contains ref-producing ``.remote()`` calls.  Actor *handle*
pools are exempt — handles are legitimately long-lived.

A stored attribute counts as consumed if ANY non-store load of the same
attribute name exists anywhere in the indexed program (drain loops,
``ray_tpu.get(self.x)``, ``.pop()``, iteration, returns, ``len``...).
That program-wide check is deliberately conservative: rebinding through
another alias still suppresses the finding, so the rule only fires on
attributes that are write-only everywhere.  Module-level globals get
the same treatment with module-local name loads.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.flow.engine import FlowRule
from ray_tpu.devtools.flow.index import ProgramIndex

_MUTATORS = {
    "append", "add", "appendleft", "extend", "insert", "setdefault",
    "update",
}

_CONTAINER_LITERALS = (
    ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
    ast.SetComp,
)


def _self_attr(expr: ast.AST) -> Optional[str]:
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return expr.attr
    return None


def _collect_store_receivers(tree: ast.AST) -> Set[int]:
    """ids of Attribute/Name nodes that are *receivers of a store
    shape* (mutator-call receiver, subscript-assign base) — such loads
    must not count as consumption."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATORS
        ):
            out.add(id(node.func.value))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Subscript):
                    out.add(id(t.value))
    return out


def _consumed_names(index: ProgramIndex) -> Tuple[Set[str], Dict[str, Set[str]]]:
    """(attr names loaded outside store shapes anywhere in the program,
    module name -> plain names loaded outside store shapes)."""
    attrs: Set[str] = set()
    mod_names: Dict[str, Set[str]] = {}
    for mname in sorted(index.modules):
        mod = index.modules[mname]
        skip = _collect_store_receivers(mod.tree)
        loads: Set[str] = set()
        for node in ast.walk(mod.tree):
            if id(node) in skip:
                continue
            if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                attrs.add(node.attr)
            elif isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ):
                loads.add(node.id)
        mod_names[mname] = loads
    return attrs, mod_names


class ObjectRefLeak(FlowRule):
    id = "RT202"
    name = "objectref-leak"
    description = (
        "ObjectRef stored into a container/attribute that nothing ever "
        "drains — pins shm arena capacity forever"
    )
    hint = (
        "drain the container somewhere (get/wait then pop/clear), or "
        "don't retain the ref at all"
    )

    def check(self, index: ProgramIndex) -> None:
        consumed_attrs, consumed_mod_names = _consumed_names(index)

        for cq in sorted(index.classes):
            cls = index.classes[cq]
            for store_attr, node, detail in self._class_stores(index, cls):
                if store_attr in consumed_attrs:
                    continue
                self.add(
                    cls.module, node,
                    message=(
                        f"objectref-leak: {detail} into "
                        f"`self.{store_attr}` but no code ever reads or "
                        f"drains `.{store_attr}` — every stored ref "
                        f"stays pinned in the shm arena"
                    ),
                )

        for mname in sorted(index.modules):
            mod = index.modules[mname]
            container_globals = {
                name for name, value in mod.top_assigns.items()
                if isinstance(value, _CONTAINER_LITERALS)
                or (
                    isinstance(value, ast.Call)
                    and mod.resolve(value.func) in (
                        "dict", "list", "set", "collections.deque",
                        "collections.defaultdict",
                        "collections.OrderedDict",
                    )
                )
            }
            if not container_globals:
                continue
            loads = consumed_mod_names[mname]
            for name, node, detail in self._global_stores(
                index, mod, container_globals
            ):
                # the global name read anywhere in this module (outside
                # store shapes), or accessed as `mod.<name>` elsewhere
                if name in loads or name in consumed_attrs:
                    continue
                self.add(
                    mod, node,
                    message=(
                        f"objectref-leak: {detail} into module global "
                        f"`{name}` but nothing ever reads or drains it "
                        f"— every stored ref stays pinned in the shm "
                        f"arena"
                    ),
                )

    # -- store-site discovery --------------------------------------------

    def _class_stores(self, index: ProgramIndex, cls):
        """Yields (attr, node, detail) ref-store sites across methods."""
        for mname in sorted(cls.methods):
            fn = cls.methods[mname]
            facts = index.facts(fn)
            for node, attr, value in self._stores_in(
                fn.node, lambda e: _self_attr(e)
            ):
                if self._stored_value_is_ref(index, fn, facts, value):
                    yield attr, node, self._detail(node)

    def _global_stores(self, index: ProgramIndex, mod, names):
        def global_name(expr: ast.AST) -> Optional[str]:
            if isinstance(expr, ast.Name) and expr.id in names:
                return expr.id
            return None

        for stmt in mod.tree.body:
            if not isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            owner = None
            fns: List = []
            if isinstance(stmt, ast.ClassDef):
                qual = f"{mod.name}.{stmt.name}"
                owner = index.classes.get(qual)
                if owner is not None:
                    fns = [
                        owner.methods[m] for m in sorted(owner.methods)
                    ]
            else:
                fn = index.functions.get(f"{mod.name}.{stmt.name}")
                if fn is not None:
                    fns = [fn]
            for fn in fns:
                facts = index.facts(fn)
                for node, name, value in self._stores_in(
                    fn.node, global_name
                ):
                    if self._stored_value_is_ref(index, fn, facts, value):
                        yield name, node, self._detail(node)

    def _stores_in(self, fn_node, key_of):
        """(node, key, stored-value-expr) for every store shape whose
        receiver matches ``key_of`` (self-attr or module-global)."""
        for node in ast.walk(fn_node):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
            ):
                key = key_of(node.func.value)
                if key is not None and node.args:
                    for arg in node.args:
                        yield node, key, arg
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        key = key_of(t.value)
                        if key is not None:
                            yield node, key, node.value
                            # dict stores can hold the ref as the KEY
                            # (ref -> metadata maps)
                            yield node, key, t.slice
                    else:
                        key = key_of(t)
                        if key is not None and isinstance(
                            node.value, _CONTAINER_LITERALS
                        ):
                            yield node, key, node.value

    def _stored_value_is_ref(self, index, fn, facts, value) -> bool:
        return index.is_ref_expr(fn.module, value, facts, fn.owner)

    @staticmethod
    def _detail(node: ast.AST) -> str:
        if isinstance(node, ast.Call):
            return "`.remote()` ref appended"
        return "`.remote()` ref stored"
