"""RT201 actor-deadlock: cycles over blocking remote-call edges.

An actor processes one message at a time.  If a method of actor A
blocking-``get()``s a ref submitted into actor B, A's mailbox is frozen
until B replies; if B (transitively) blocking-waits on a submission
back into A, both mailboxes are frozen forever — the classic
distributed deadlock, which at runtime looks like a silent hang until a
lease or collective timeout fires minutes later.

The rule builds an actor-level digraph: an edge A -> B for every
*unbounded* blocking get in a method of A whose argument's provenance
is a resolved ``<B-handle>.<meth>.remote(...)`` submission.  Every edge
inside a strongly connected component (including self-loops — an actor
blocking on a submission into itself can never serve it) is flagged at
its get site.  Bounded ``timeout=`` waits degrade deadlock to latency
and are exempt, matching RT104.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ray_tpu.devtools.flow.engine import FlowRule
from ray_tpu.devtools.flow.index import FunctionFacts, ProgramIndex


def _arg_ref_targets(
    index: ProgramIndex, fn, facts: FunctionFacts, call: ast.Call
) -> List[tuple]:
    """Ref targets flowing into a get/wait call's arguments."""
    out: List[tuple] = []
    exprs = list(call.args) + [kw.value for kw in call.keywords]
    flat: List[ast.AST] = []
    for e in exprs:
        if isinstance(e, (ast.List, ast.Tuple, ast.Set)):
            flat.extend(e.elts)
        else:
            flat.append(e)
    for e in flat:
        if isinstance(e, ast.Name):
            t = facts.ref_targets.get(e.id)
            if t is not None:
                out.append(t)
            continue
        t = index.remote_target(fn.module, e, facts.env, fn.owner)
        if t is not None and t[0] != "handle":
            out.append(t)
            continue
        t = index.container_ref_target(fn.module, e, facts.env, fn.owner)
        if t is not None:
            out.append(t)
    return out


def _sccs(nodes: List[str], adj: Dict[str, List[str]]) -> Dict[str, int]:
    """Iterative Tarjan; returns node -> component id."""
    index_of: Dict[str, int] = {}
    low: Dict[str, int] = {}
    comp: Dict[str, int] = {}
    on_stack: Dict[str, bool] = {}
    stack: List[str] = []
    counter = [0]
    comp_id = [0]

    for root in nodes:
        if root in index_of:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, ei = work[-1]
            if ei == 0:
                index_of[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack[node] = True
            succs = adj.get(node, [])
            advanced = False
            while ei < len(succs):
                succ = succs[ei]
                ei += 1
                if succ not in index_of:
                    work[-1] = (node, ei)
                    work.append((succ, 0))
                    advanced = True
                    break
                if on_stack.get(succ):
                    low[node] = min(low[node], index_of[succ])
            if advanced:
                continue
            work[-1] = (node, ei)
            if ei >= len(succs):
                work.pop()
                if low[node] == index_of[node]:
                    while True:
                        top = stack.pop()
                        on_stack[top] = False
                        comp[top] = comp_id[0]
                        if top == node:
                            break
                    comp_id[0] += 1
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
    return comp


def _cycle_path(
    src: str, dst: str, adj: Dict[str, List[str]]
) -> List[str]:
    """Shortest dst -> src walk (BFS) to render the cycle back-edge."""
    if dst == src:
        return [dst, src]
    frontier = [dst]
    came: Dict[str, str] = {dst: ""}
    while frontier:
        nxt: List[str] = []
        for node in frontier:
            for succ in adj.get(node, []):
                if succ in came:
                    continue
                came[succ] = node
                if succ == src:
                    path = [succ]
                    while path[-1] != dst:
                        path.append(came[path[-1]])
                    return list(reversed(path))
                nxt.append(succ)
        frontier = nxt
    return [dst, src]


class ActorDeadlock(FlowRule):
    id = "RT201"
    name = "actor-deadlock"
    description = (
        "blocking get of a remote call that can cycle back through the "
        "same actor"
    )
    hint = (
        "break the wait cycle: make one side async (await the ref), "
        "pass refs through as task arguments, or bound the wait with "
        "timeout="
    )

    def check(self, index: ProgramIndex) -> None:
        # actor qualname -> actor qualname -> [(fn, get node, target)]
        edges: Dict[str, Dict[str, list]] = {}
        for cq in sorted(index.classes):
            cls = index.classes[cq]
            if not cls.is_actor:
                continue
            for mname in sorted(cls.methods):
                fn = cls.methods[mname]
                facts = index.facts(fn)
                for site in facts.gets:
                    if site.bounded:
                        continue
                    for t in _arg_ref_targets(index, fn, facts, site.node):
                        if t[0] != "ref-actor":
                            continue
                        callee = index.classes.get(t[1])
                        if callee is None or not callee.is_actor:
                            continue
                        edges.setdefault(cq, {}).setdefault(
                            t[1], []
                        ).append((fn, site.node, t))

        nodes = sorted(
            set(edges) | {d for dsts in edges.values() for d in dsts}
        )
        adj = {n: sorted(edges.get(n, {})) for n in nodes}
        comp = _sccs(nodes, adj)
        scc_sizes: Dict[int, int] = {}
        for node in nodes:
            scc_sizes[comp[node]] = scc_sizes.get(comp[node], 0) + 1

        for src in sorted(edges):
            for dst in sorted(edges[src]):
                if comp[src] != comp[dst]:
                    continue
                if src == dst:
                    cyclic = True  # self-loop edge
                else:
                    cyclic = scc_sizes[comp[src]] > 1
                if not cyclic:
                    continue
                path = _cycle_path(src, dst, adj)
                shorts = [index.classes[n].short for n in path]
                for fn, node, t in edges[src][dst]:
                    callee = index.classes[t[1]]
                    if src == dst:
                        msg = (
                            f"actor-deadlock: `{fn.short}` blocking-gets "
                            f"`{callee.short}.{t[2]}.remote()` on its own "
                            f"actor class — the single-threaded actor "
                            f"can never serve the call it is waiting on"
                        )
                    else:
                        msg = (
                            f"actor-deadlock: `{fn.short}` blocking-gets "
                            f"`{callee.short}.{t[2]}.remote()` and the "
                            f"callee can block back into "
                            f"`{index.classes[src].short}` (cycle: "
                            + " -> ".join(
                                [index.classes[src].short] + shorts
                            )
                            + ")"
                        )
                    self.add(fn.module, node, message=msg)
