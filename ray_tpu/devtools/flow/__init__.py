"""rtflow: whole-program call-graph + actor-boundary dataflow analysis.

The per-file tier (``ray_tpu.devtools.lint``, RT1xx) catches bug
families visible inside one module.  This package is the second tier:
it indexes an entire package into a symbol table + call graph that
models the framework's remote surface — ``@ray_tpu.remote`` functions
and actor classes, ``.remote()`` submissions, ``get()``/``wait()``
blocking edges, ``util.collective`` op sites, ObjectRef-producing and
-consuming expressions — and runs interprocedural rules on top:

- RT201 actor-deadlock: cycles over blocking remote-call edges between
  actors (including self-calls).
- RT202 objectref-leak: refs stored into long-lived containers or
  attributes that no reachable code ever drains — they pin shm arena
  capacity forever.
- RT203 unserializable-capture: remote closures capturing locks, event
  loops, sockets/clients, open files, or live jax Arrays.
- RT204 rank-divergent-collective: symmetric collective op sequences
  that differ across rank-conditional branches (the mismatched
  allreduce hang), resolved through helper calls.

Findings ride the same ``Finding`` type, suppression comments, and
baseline machinery as the per-file tier; run both with::

    python -m ray_tpu.devtools.lint --flow ray_tpu
"""

from ray_tpu.devtools.flow.engine import (  # noqa: F401
    DEFAULT_FLOW_BASELINE,
    FlowReport,
    all_flow_rules,
    analyze_paths,
    analyze_sources,
    flow_rule_ids,
)
from ray_tpu.devtools.flow.index import ProgramIndex  # noqa: F401
