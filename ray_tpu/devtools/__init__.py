"""Developer tooling for ray_tpu.

``ray_tpu.devtools.lint`` (rtlint) is an AST-based static analyzer for
the distributed-correctness bug families this codebase has actually hit:
event-loop blocking, non-atomic persists, impure traced functions,
nested blocking gets, dropped coroutines/refs, mutable defaults on
remote surfaces, swallowed cancellation, and unlocked lazy init.

Run it with::

    python -m ray_tpu.devtools.lint ray_tpu [--format json]

See ``docs/architecture.md`` ("Static analysis (rtlint)") for rule ids,
suppression syntax, and the baseline workflow.
"""
