"""SARIF 2.1.0 output for rtlint/rtflow/rtrace/rtproto findings.

SARIF is the interchange format CI systems (GitHub code scanning,
Azure, Gitlab) render as inline PR annotations.  One run object carries
every active tier (per-file RT1xx, whole-program RT2xx, concurrency
RT3xx — including the native C++ lock-order findings — and
wire-contract RT4xx); baselined
findings are included but marked with an ``external`` suppression so
dashboards show them as accepted debt instead of new violations.
"""

from __future__ import annotations

from typing import Iterable, List, Sequence

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _rule_entry(rule) -> dict:
    return {
        "id": rule.id,
        "name": rule.name,
        "shortDescription": {"text": rule.description},
        "help": {"text": rule.hint},
        "defaultConfiguration": {"level": "warning"},
    }


def _result(finding, suppressed: bool) -> dict:
    out = {
        "ruleId": finding.rule,
        "level": "warning",
        "message": {"text": f"{finding.message} (hint: {finding.hint})"},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": finding.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": finding.line,
                        "startColumn": finding.col,
                    },
                }
            }
        ],
        "partialFingerprints": {
            "rtlint/v1": finding.fingerprint(),
        },
    }
    if suppressed:
        out["suppressions"] = [
            {"kind": "external", "justification": "rtlint baseline"}
        ]
    return out


def render_sarif(
    new: Sequence, baselined: Sequence, rules: Iterable
) -> dict:
    """Build the SARIF document for one lint invocation.  ``rules`` is
    every rule object that COULD have fired (every active tier, e.g.
    all three under --all) so rule metadata stays stable across
    runs."""
    results: List[dict] = []
    for f in new:
        results.append(_result(f, suppressed=False))
    for f in baselined:
        results.append(_result(f, suppressed=True))
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "rtlint",
                        "informationUri": (
                            "https://github.com/ray_tpu/ray_tpu"
                        ),
                        "rules": sorted(
                            (_rule_entry(r) for r in rules),
                            key=lambda r: r["id"],
                        ),
                    }
                },
                "columnKind": "unicodeCodePoints",
                "originalUriBaseIds": {
                    "SRCROOT": {"description": {
                        "text": "lint invocation working directory"
                    }}
                },
                "results": results,
            }
        ],
    }
