"""Model zoo: TPU-first JAX implementations used by train/serve/rllib."""

from ray_tpu.models import gpt2  # noqa: F401
