"""RLlib model catalog: obs space → module family.

Role-equivalent of ray: rllib/models/catalog.py (ModelCatalog) +
rllib/models/torch/visionnet.py — the CNN family for image observations
and the dispatch that picks MLP vs CNN from the obs shape.  TPU-first:
convolutions are NHWC jax.lax.conv_general_dilated calls XLA maps onto
the MXU; the module is functional (params in, (logits, value) out) so
the identical code runs CPU inference in EnvRunners and pjit'd training
in Learners.

Modules accept FLAT observations (B, prod(obs_shape)) and reshape
internally using the static config — rollout fragments stay flat
through buffers and minibatching, and the reshape is free under XLA.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from ray_tpu.rllib import core


@dataclasses.dataclass(frozen=True)
class CNNModuleConfig:
    obs_shape: Tuple[int, int, int]  # (H, W, C)
    num_actions: int
    # (out_channels, kernel, stride) per conv layer (reference default
    # vision-net filters, scaled down)
    conv_filters: Tuple[Tuple[int, int, int], ...] = (
        (16, 8, 4),
        (32, 4, 2),
    )
    hidden: Tuple[int, ...] = (256,)


def _conv_out_hw(h: int, w: int,
                 filters: Tuple[Tuple[int, int, int], ...]) -> Tuple[int, int]:
    for _, k, s in filters:
        h = (h - k) // s + 1
        w = (w - k) // s + 1
    return h, w


def cnn_init(rng, cfg: CNNModuleConfig) -> core.Params:
    H, W, C = cfg.obs_shape
    keys = jax.random.split(rng, len(cfg.conv_filters) + len(cfg.hidden) + 2)
    params: core.Params = {"conv": [], "layers": []}
    cin = C
    for i, (cout, k, _s) in enumerate(cfg.conv_filters):
        fan_in = k * k * cin
        params["conv"].append({
            "w": jax.random.normal(keys[i], (k, k, cin, cout))
            * jnp.sqrt(2.0 / fan_in),
            "b": jnp.zeros((cout,)),
        })
        cin = cout
    oh, ow = _conv_out_hw(H, W, cfg.conv_filters)
    din = oh * ow * cin
    base = len(cfg.conv_filters)
    for j, dout in enumerate(cfg.hidden):
        params["layers"].append({
            "w": jax.random.normal(keys[base + j], (din, dout))
            * jnp.sqrt(2.0 / din),
            "b": jnp.zeros((dout,)),
        })
        din = dout
    params["pi"] = {
        "w": jax.random.normal(keys[-2], (din, cfg.num_actions)) * 0.01,
        "b": jnp.zeros((cfg.num_actions,)),
    }
    params["vf"] = {
        "w": jax.random.normal(keys[-1], (din, 1)),
        "b": jnp.zeros((1,)),
    }
    return params


def cnn_make_forward(cfg: CNNModuleConfig):
    H, W, C = cfg.obs_shape
    strides = [s for _, _, s in cfg.conv_filters]

    def fwd(params: core.Params, obs):
        x = obs.reshape((-1, H, W, C)).astype(jnp.float32)
        for layer, s in zip(params["conv"], strides):
            x = jax.lax.conv_general_dilated(
                x, layer["w"], window_strides=(s, s), padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            ) + layer["b"]
            x = jax.nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        for layer in params["layers"]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        logits = x @ params["pi"]["w"] + params["pi"]["b"]
        value = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
        return logits, value

    return fwd


core.register_module_family(CNNModuleConfig, cnn_init, cnn_make_forward)


def get_module_config(obs_shape, num_actions: int, model_config=None):
    """Pick a module family from the obs shape (ray: ModelCatalog
    get_model_v2 dispatch): rank-3 obs → CNN, else MLP."""
    model_config = model_config or {}
    if len(obs_shape) == 3:
        return CNNModuleConfig(
            obs_shape=tuple(obs_shape),
            num_actions=num_actions,
            conv_filters=tuple(
                tuple(f) for f in model_config.get(
                    "conv_filters", ((16, 8, 4), (32, 4, 2))
                )
            ),
            hidden=tuple(model_config.get("hidden", (256,))),
        )
    import numpy as np

    return core.MLPModuleConfig(
        obs_dim=int(np.prod(obs_shape)),
        num_actions=num_actions,
        hidden=tuple(model_config.get("hidden", (64, 64))),
    )
