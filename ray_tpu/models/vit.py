"""Vision Transformer: image classification on the shared sharding rules.

A non-LM model family for the train/data path (role parity: the
reference's libraries are model-agnostic hosts — `ray:
train/examples/pytorch/torch_fashion_mnist_example.py`,
`rllib/models/torch/visionnet.py` are its vision touchpoints; here the
family is first-class and TPU-native).  Design:

- patchify = one einsum over non-overlapping patches (an MXU matmul,
  not a conv — identical math for stride == kernel),
- encoder blocks: pre-LN, BIDIRECTIONAL attention (no causal mask),
  GELU MLP — parameters use the same logical axes as gpt2
  ("embed"/"heads"/"kv"/"mlp"), so `parallel.sharding`'s rule table
  shards it over dp/fsdp/tp with no new rules,
- mean-pool over patch tokens → linear head (classes pad to 128 for
  the MXU, like gpt2's vocab padding).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.common import layernorm as _layernorm

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1024  # pad to a multiple of 128 for the MXU
    channels: int = 3
    num_layers: int = 12
    num_heads: int = 12
    embed_dim: int = 768
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.channels * self.patch_size ** 2

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def mlp_dim(self) -> int:
        return self.mlp_ratio * self.embed_dim

    @staticmethod
    def vit_b16(**kw) -> "ViTConfig":
        return ViTConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "ViTConfig":
        kw.setdefault("image_size", 32)
        kw.setdefault("patch_size", 8)
        kw.setdefault("num_classes", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("embed_dim", 64)
        return ViTConfig(**kw)


def param_logical_axes(config: ViTConfig) -> Params:
    """Same logical vocabulary as gpt2 → same sharding rule table."""
    blk = {
        "ln1_scale": ("layers", "embed"),
        "ln1_bias": ("layers", "embed"),
        "qkv_kernel": ("layers", "embed", "heads", "kv"),
        "qkv_bias": ("layers", "heads", "kv"),
        "proj_kernel": ("layers", "heads", "kv", "embed"),
        "proj_bias": ("layers", "embed"),
        "ln2_scale": ("layers", "embed"),
        "ln2_bias": ("layers", "embed"),
        "fc_kernel": ("layers", "embed", "mlp"),
        "fc_bias": ("layers", "mlp"),
        "out_kernel": ("layers", "mlp", "embed"),
        "out_bias": ("layers", "embed"),
    }
    return {
        "patch_kernel": (None, "embed"),  # (patch_dim, E)
        "patch_bias": ("embed",),
        "pos_embed": (None, "embed"),  # (num_patches, E)
        "blocks": blk,
        "lnf_scale": ("embed",),
        "lnf_bias": ("embed",),
        "head_kernel": ("embed", "vocab"),
        "head_bias": ("vocab",),
    }


def init(rng, config: ViTConfig) -> Params:
    c = config
    dt = c.param_dtype
    k = jax.random.split(rng, 8)
    std = 0.02
    resid_std = std / math.sqrt(2 * c.num_layers)
    L, E, H, D, M = (c.num_layers, c.embed_dim, c.num_heads, c.head_dim,
                     c.mlp_dim)

    def norm(key, shape, s):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(dt)

    blocks = {
        "ln1_scale": jnp.ones((L, E), dt),
        "ln1_bias": jnp.zeros((L, E), dt),
        "qkv_kernel": norm(k[0], (L, E, 3 * H, D), std),
        "qkv_bias": jnp.zeros((L, 3 * H, D), dt),
        "proj_kernel": norm(k[1], (L, H, D, E), resid_std),
        "proj_bias": jnp.zeros((L, E), dt),
        "ln2_scale": jnp.ones((L, E), dt),
        "ln2_bias": jnp.zeros((L, E), dt),
        "fc_kernel": norm(k[2], (L, E, M), std),
        "fc_bias": jnp.zeros((L, M), dt),
        "out_kernel": norm(k[3], (L, M, E), resid_std),
        "out_bias": jnp.zeros((L, E), dt),
    }
    return {
        "patch_kernel": norm(k[4], (c.patch_dim, E), std),
        "patch_bias": jnp.zeros((E,), dt),
        "pos_embed": norm(k[5], (c.num_patches, E), 0.01),
        "blocks": blocks,
        "lnf_scale": jnp.ones((E,), dt),
        "lnf_bias": jnp.zeros((E,), dt),
        "head_kernel": norm(k[6], (E, c.num_classes), std),
        "head_bias": jnp.zeros((c.num_classes,), dt),
    }




def patchify(images, config: ViTConfig):
    """(B, H, W, C) → (B, num_patches, patch_dim): non-overlapping
    patches, flattened — the subsequent matmul IS the patch-embed conv."""
    B = images.shape[0]
    P, S = config.patch_size, config.image_size
    n = S // P
    x = images.reshape(B, n, P, n, P, config.channels)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # (B, n, n, P, P, C)
    return x.reshape(B, n * n, config.patch_dim)


def _block(x, p, config: ViTConfig):
    c = config
    B, S, E = x.shape
    H, D = c.num_heads, c.head_dim
    h = _layernorm(x, p["ln1_scale"], p["ln1_bias"])
    qkv = (
        jnp.einsum("bse,ehd->bshd", h, p["qkv_kernel"].astype(c.dtype))
        + p["qkv_bias"].astype(c.dtype)
    )
    q, k, v = jnp.split(qkv, 3, axis=2)  # (B, S, H, D) each

    # bidirectional attention: every patch attends to every patch
    q = q.transpose(0, 2, 1, 3) * (1.0 / math.sqrt(D))
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    probs = jax.nn.softmax(scores, axis=-1).astype(c.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", probs, v).transpose(0, 2, 1, 3)

    x = x + (
        jnp.einsum("bshd,hde->bse", o, p["proj_kernel"].astype(c.dtype))
        + p["proj_bias"].astype(c.dtype)
    )
    h = _layernorm(x, p["ln2_scale"], p["ln2_bias"])
    h = jax.nn.gelu(
        jnp.einsum("bse,em->bsm", h, p["fc_kernel"].astype(c.dtype))
        + p["fc_bias"].astype(c.dtype),
        approximate=True,
    )
    x = x + (
        jnp.einsum("bsm,me->bse", h, p["out_kernel"].astype(c.dtype))
        + p["out_bias"].astype(c.dtype)
    )
    return x


def forward(params: Params, images, config: ViTConfig):
    """images (B, H, W, C) float → logits (B, num_classes)."""
    c = config
    x = patchify(images.astype(c.dtype), c)
    x = (
        jnp.einsum("bsp,pe->bse", x, params["patch_kernel"].astype(c.dtype))
        + params["patch_bias"].astype(c.dtype)
        + params["pos_embed"].astype(c.dtype)[None]
    )

    blk = _block
    if c.remat:
        blk = jax.checkpoint(_block, static_argnums=(2,))

    def body(carry, layer_params):
        return blk(carry, layer_params, c), None

    x, _ = lax.scan(body, x, params["blocks"])
    x = _layernorm(x, params["lnf_scale"], params["lnf_bias"])
    pooled = x.mean(axis=1)  # mean-pool patch tokens
    logits = (
        pooled.astype(jnp.float32)
        @ params["head_kernel"].astype(jnp.float32)
        + params["head_bias"].astype(jnp.float32)
    )
    return logits


def loss_fn(params: Params, batch, config: ViTConfig):
    """Scalar cross-entropy (the spmd.compile_train_step contract).
    batch: {"images": (B,H,W,C), "labels": (B,)}."""
    logits = forward(params, batch["images"], config)
    labels = batch["labels"].astype(jnp.int32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0].mean()


def accuracy(params: Params, batch, config: ViTConfig):
    logits = forward(params, batch["images"], config)
    return (
        jnp.argmax(logits, axis=-1) == batch["labels"].astype(jnp.int32)
    ).mean()


def num_params(config: ViTConfig) -> int:
    shapes = jax.eval_shape(
        lambda rng: init(rng, config), jax.random.key(0)
    )
    return sum(
        math.prod(v.shape) for v in jax.tree_util.tree_leaves(shapes)
    )
