"""Shared chunked cross-entropy for LM heads.

One implementation of the numerically-sensitive chunked head+softmax
(used by models/gpt2.py and models/llama.py): the lm_head einsum and
logsumexp run per sequence chunk under jax.checkpoint, so each chunk's
(B, C, V) f32 logits are recomputed in the backward pass instead of
living through the whole step — peak logits memory drops from
O(B·S·V) to O(B·chunk·V).  Same lse − target_logit formulation as the
dense paths; loss and grads agree to bf16 rounding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.sharding import constrain


def chunked_xent(x, head_weight, targets, mask, chunk: int, dtype):
    """Mean negative log-likelihood with a chunked head.

    x: (B, S, E) features; head_weight: (V, E); targets: (B, S) int32;
    mask: optional (B, S); chunk must divide S.
    """
    B, S = targets.shape
    nc = S // chunk
    w = head_weight.astype(dtype)
    xs = x.reshape(B, nc, chunk, -1).transpose(1, 0, 2, 3)  # (nc,B,C,E)
    ts = targets.reshape(B, nc, chunk).transpose(1, 0, 2)
    ms = (
        mask.reshape(B, nc, chunk).transpose(1, 0, 2).astype(jnp.float32)
        if mask is not None
        else None
    )

    @jax.checkpoint
    def chunk_ll(xc, tc):
        logits = jnp.einsum(
            "bce,ve->bcv", xc, w, preferred_element_type=jnp.float32
        )
        logits = constrain(logits, ("batch", None, "vocab"))
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
        return tl - lse  # (B, C)

    def body(carry, xtm):
        ll_sum, m_sum = carry
        if ms is None:
            xc, tc = xtm
            ll = chunk_ll(xc, tc)
            return (ll_sum + ll.sum(), m_sum + ll.size), None
        xc, tc, mc = xtm
        ll = chunk_ll(xc, tc)
        return (ll_sum + (ll * mc).sum(), m_sum + mc.sum()), None

    xtm = (xs, ts) if ms is None else (xs, ts, ms)
    (ll_sum, m_sum), _ = lax.scan(
        body, (jnp.float32(0.0), jnp.float32(0.0)), xtm
    )
    return -ll_sum / jnp.maximum(m_sum, 1.0)
