"""Pipeline-parallel composition for the transformer families.

Cuts the scan-stacked GPT-2 / Llama blocks into `pp` stages.  The cut
itself — which params belong to a stage, what the per-stage step
functions are — is expressed ONCE, as a :class:`ModelPartition`, and
consumed by BOTH pipeline schedules:

- the in-program schedule here (`gpt2_pp_train_step` /
  `llama_pp_train_step`): stages run on the shared 6-axis mesh
  (parallel/mesh.py) driven by
  `parallel.pipeline.tailed_pipeline_train_step` — the embedding prelude
  runs replicated on every stage, each stage scans its slice of layers,
  activations `lax.ppermute` to the next stage per microbatch, and the
  final norm + lm head + cross-entropy evaluate on the last stage.  The
  whole schedule (fwd+bwd+update) is ONE compiled program — the
  TPU-native form of the reference's pipeline execution over
  actors/NCCL (ray: compiled DAG NCCL channels, python/ray/dag/) with
  the compiler deriving the backward pipeline through the permutes.

- the MPMD schedule (`ray_tpu.train.pipeline`): each stage is a
  long-lived actor gang, micro-batch activations/grads hand between
  stages as shm objects, and a 1F1B schedule drives the per-stage
  fwd/bwd programs built from the SAME partition
  (train/pipeline/partition.py) — so the two schedules can never drift
  on what a "stage" means.

Composable with the other axes: shard_map is manual over `pp` only
(partial-auto), so dp batch sharding and tp/fsdp parameter shardings
propagate through GSPMD as usual.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import gpt2 as gpt2_mod
from ray_tpu.models import llama as llama_mod
from ray_tpu.parallel.mesh import PP_AXIS
from ray_tpu.parallel.pipeline import tailed_pipeline_train_step

Params = Any


# -- stage splitting ---------------------------------------------------------


def split_stacked(blocks: Params, n_stages: int) -> Params:
    """(L, ...) stacked layer params → (n_stages, L // n_stages, ...)."""

    def reshape(leaf):
        L = leaf.shape[0]
        if L % n_stages:
            raise ValueError(
                f"{L} layers not divisible into {n_stages} pipeline stages"
            )
        return leaf.reshape((n_stages, L // n_stages) + leaf.shape[1:])

    return jax.tree.map(reshape, blocks)


def merge_stacked(stages: Params) -> Params:
    """Inverse of split_stacked (for checkpoint export / parity tests)."""
    return jax.tree.map(
        lambda leaf: leaf.reshape((-1,) + leaf.shape[2:]), stages
    )


def pp_params_sharding(mesh: Mesh, pp_params: Params) -> Params:
    """NamedShardings: stages split over pp, tail replicated (tp/fsdp
    refinements can be layered on by passing these through the rule
    table first)."""
    return {
        "stages": jax.tree.map(
            lambda _: NamedSharding(mesh, P(PP_AXIS)), pp_params["stages"]
        ),
        "tail": jax.tree.map(
            lambda _: NamedSharding(mesh, P()), pp_params["tail"]
        ),
    }


# -- the reusable partition --------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ModelPartition:
    """One model family's pipeline cut, schedule-agnostic.

    ``prelude(tail, tokens) -> h`` embeds a microbatch (runs on the
    FIRST stage under MPMD, replicated on every stage in-program);
    ``stage_fn(stage_blocks, h) -> h`` runs one stage's layer slice;
    ``loss_tail(tail, outs, targets) -> scalar`` evaluates final norm +
    head + cross-entropy on the LAST stage's outputs, where ``outs`` is
    ``(n_micro, mb, S, E)`` and ``targets`` ``(n_micro, mb, S)``.
    ``to_pp(params, n_stages)`` / ``from_pp(pp_params)`` cut and merge
    the parameter pytree ({"stages": stacked, "tail": rest});
    ``init(rng)`` builds the family's fresh full-model params (the
    partition carries ALL model-family knowledge, so registering a new
    family here is sufficient for train.pipeline to drive it).
    """

    name: str
    config: Any
    prelude: Callable[[Params, jax.Array], jax.Array]
    stage_fn: Callable[[Params, jax.Array], jax.Array]
    loss_tail: Callable[[Params, jax.Array, jax.Array], jax.Array]
    to_pp: Callable[[Params, int], Params]
    from_pp: Callable[[Params], Params]
    init: Callable[[Any], Params]

    def micro_loss(self, tail: Params, h: jax.Array,
                   targets: jax.Array) -> jax.Array:
        """Per-microbatch loss: ``loss_tail`` over a single microbatch
        (``h`` (mb, S, E), ``targets`` (mb, S)).  The mean over one
        leading micro-axis entry equals the per-micro mean, so both
        schedules share one loss definition."""
        return self.loss_tail(tail, h[None], targets[None])


# -- GPT-2 -------------------------------------------------------------------


def gpt2_to_pp(params: Params, n_stages: int) -> Params:
    tail = {k: v for k, v in params.items() if k != "blocks"}
    return {"stages": split_stacked(params["blocks"], n_stages),
            "tail": tail}


def gpt2_from_pp(pp_params: Params) -> Params:
    out = dict(pp_params["tail"])
    out["blocks"] = merge_stacked(pp_params["stages"])
    return out


def gpt2_partition(config) -> ModelPartition:
    """The GPT-2 pipeline cut: embedding prelude, scanned block slices,
    tied-head cross-entropy tail."""
    c = config

    def prelude(tail, tokens):
        S = tokens.shape[-1]
        wte = tail["wte"].astype(c.dtype)
        x = wte[tokens] + tail["wpe"].astype(c.dtype)[:S]
        return x

    def stage_fn(stage_blocks, h):
        def body(x, layer_params):
            x2, _aux = gpt2_mod._block(x, layer_params, c, None)
            return x2, None

        h2, _ = lax.scan(body, h, stage_blocks)
        return h2

    def loss_tail(tail, outs, targets):
        x = gpt2_mod._layernorm(outs, tail["lnf_scale"], tail["lnf_bias"])
        logits = jnp.einsum(
            "nbse,ve->nbsv", x, tail["wte"].astype(c.dtype),
            preferred_element_type=jnp.float32,
        )
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        return -(tl - lse).mean()

    return ModelPartition(
        name="gpt2", config=c, prelude=prelude, stage_fn=stage_fn,
        loss_tail=loss_tail, to_pp=gpt2_to_pp, from_pp=gpt2_from_pp,
        init=lambda rng: gpt2_mod.init(rng, c),
    )


def gpt2_pp_train_step(
    config, mesh: Mesh, optimizer, *, n_micro: int,
    _check_vma: bool = False,
):
    """Pipelined GPT-2 train step over the mesh's pp axis.

    step(pp_params, opt_state, tokens, targets) -> (pp_params, opt_state,
    loss); tokens/targets are (n_micro, mb, S) int32 microbatches.
    """
    p = gpt2_partition(config)
    return tailed_pipeline_train_step(
        p.stage_fn, p.prelude, p.loss_tail, optimizer, mesh,
        n_micro=n_micro, _check_vma=_check_vma,
    )


# -- Llama -------------------------------------------------------------------


def llama_to_pp(params: Params, n_stages: int) -> Params:
    tail = {k: v for k, v in params.items() if k != "blocks"}
    return {"stages": split_stacked(params["blocks"], n_stages),
            "tail": tail}


def llama_from_pp(pp_params: Params) -> Params:
    out = dict(pp_params["tail"])
    out["blocks"] = merge_stacked(pp_params["stages"])
    return out


def llama_partition(config) -> ModelPartition:
    """The Llama pipeline cut (GQA blocks, RMSNorm tail, tied or untied
    head)."""
    c = config

    def prelude(tail, tokens):
        emb = tail["tok_embed"].astype(c.dtype)
        return emb[tokens]

    def stage_fn(stage_blocks, h):
        B, S = h.shape[0], h.shape[1]
        positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))

        def body(x, layer_params):
            return llama_mod._block(x, layer_params, positions, c), None

        h2, _ = lax.scan(body, h, stage_blocks)
        return h2

    def loss_tail(tail, outs, targets):
        x = llama_mod._rmsnorm(outs, tail["final_norm"], c.rms_eps)
        head = (
            tail["tok_embed"] if c.tie_embeddings else tail["lm_head"]
        ).astype(c.dtype)
        logits = jnp.einsum(
            "nbse,ve->nbsv", x, head, preferred_element_type=jnp.float32
        )
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        return -(tl - lse).mean()

    return ModelPartition(
        name="llama", config=c, prelude=prelude, stage_fn=stage_fn,
        loss_tail=loss_tail, to_pp=llama_to_pp, from_pp=llama_from_pp,
        init=lambda rng: llama_mod.init(rng, c),
    )


def llama_pp_train_step(
    config, mesh: Mesh, optimizer, *, n_micro: int,
    _check_vma: bool = False,
):
    """Pipelined Llama train step over the mesh's pp axis."""
    p = llama_partition(config)
    return tailed_pipeline_train_step(
        p.stage_fn, p.prelude, p.loss_tail, optimizer, mesh,
        n_micro=n_micro, _check_vma=_check_vma,
    )


# -- registry (train.pipeline resolves model families by name) ---------------

PARTITIONS: Dict[str, Callable[[Any], ModelPartition]] = {
    "gpt2": gpt2_partition,
    "llama": llama_partition,
}


def get_partition(model: str, config) -> ModelPartition:
    try:
        factory = PARTITIONS[model]
    except KeyError:
        raise ValueError(
            f"unknown pipeline model family {model!r} "
            f"(registered: {sorted(PARTITIONS)})"
        ) from None
    return factory(config)
