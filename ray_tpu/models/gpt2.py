"""GPT-2 decoder-only transformer, TPU-first.

The flagship model for the framework's Train path (SURVEY.md §7 config 3:
GPT-2-124M with FSDP-style sharding).  Design choices for the MXU/XLA:

- bf16 activations & matmuls, f32 params and softmax/layernorm numerics;
- layers stacked into one pytree and iterated with `lax.scan` (one
  compiled block body, O(1) HLO size in depth);
- every weight and activation carries a logical axis name so the same
  model runs pure-DP, FSDP, TP, SP or any combination via the rule table
  in ray_tpu.parallel.sharding;
- attention is pluggable ("dense" einsum or "ring" over the sp axis).

Functional API (params in, arrays out) — no Module system, so the whole
step is a single traced function for pjit.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.ops.attention import dense_attention as _dense_attention
from ray_tpu.parallel.sharding import constrain

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304  # 50257 padded to a multiple of 128 for the MXU
    max_seq_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    embed_dim: int = 768
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16  # activation/matmul dtype
    param_dtype: Any = jnp.float32
    attention_impl: str = "dense"  # "dense" | "ring" (sp-sharded)
    remat: bool = True  # rematerialize each block in the backward pass
    # chunked cross-entropy: apply the lm_head + logsumexp per sequence
    # chunk of this many tokens (0 = dense).  Caps the largest activation
    # at O(B·chunk·V) instead of O(B·S·V) — what lets B=16+ fit in HBM.
    xent_chunk: int = 0
    # layer-scan unroll factor (1 = rolled loop).  Fully unrolling (set
    # to num_layers) removes the XLA while-loop overhead and lets the
    # scheduler overlap across layer boundaries: measured 99.5 → 80.4
    # ms/step (MFU 0.358 → 0.442) on v5e at B=8, S=1024.  Rolled stays
    # the default for compile-time and for remat-heavy configs.
    scan_unroll: int = 1
    # Mixture-of-Experts: num_experts > 0 replaces every block's dense
    # MLP with a top-1 (switch) MoE — experts shard over the mesh's ep
    # axis ("expert" logical axis), token dispatch/combine compile to
    # all_to_all over ICI.  GShard-style dense one-hot dispatch with a
    # per-expert capacity; overflow tokens pass through the residual.
    num_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_coeff: float = 0.01  # Switch load-balancing aux loss weight

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def mlp_dim(self) -> int:
        return self.mlp_ratio * self.embed_dim

    @staticmethod
    def gpt2_124m(**kw) -> "GPTConfig":
        return GPTConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "GPTConfig":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("embed_dim", 64)
        return GPTConfig(**kw)


def param_logical_axes(config: GPTConfig) -> Params:
    """Logical axis names for every param (see parallel.sharding rules).

    Block params carry a leading "layers" axis (scan-stacked).
    """
    blk = {
        "ln1_scale": ("layers", "embed"),
        "ln1_bias": ("layers", "embed"),
        "qkv_kernel": ("layers", "embed", "heads", "kv"),
        "qkv_bias": ("layers", "heads", "kv"),
        "proj_kernel": ("layers", "heads", "kv", "embed"),
        "proj_bias": ("layers", "embed"),
        "ln2_scale": ("layers", "embed"),
        "ln2_bias": ("layers", "embed"),
    }
    if config.num_experts > 0:
        blk.update({
            "router": ("layers", "embed", "expert"),
            "moe_in": ("layers", "expert", "embed", "mlp"),
            "moe_out": ("layers", "expert", "mlp", "embed"),
        })
    else:
        blk.update({
            "fc_kernel": ("layers", "embed", "mlp"),
            "fc_bias": ("layers", "mlp"),
            "out_kernel": ("layers", "mlp", "embed"),
            "out_bias": ("layers", "embed"),
        })
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "blocks": blk,
        "lnf_scale": ("embed",),
        "lnf_bias": ("embed",),
    }


def init(rng, config: GPTConfig) -> Params:
    """GPT-2 initialization: N(0, 0.02), residual projections scaled by
    1/sqrt(2*num_layers)."""
    c = config
    dt = c.param_dtype
    k = jax.random.split(rng, 8)
    std = 0.02
    resid_std = std / math.sqrt(2 * c.num_layers)
    L, E, H, D, M = c.num_layers, c.embed_dim, c.num_heads, c.head_dim, c.mlp_dim

    def norm(key, shape, s):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(dt)

    blocks = {
        "ln1_scale": jnp.ones((L, E), dt),
        "ln1_bias": jnp.zeros((L, E), dt),
        "qkv_kernel": norm(k[0], (L, E, 3 * H, D), std),
        "qkv_bias": jnp.zeros((L, 3 * H, D), dt),
        "proj_kernel": norm(k[1], (L, H, D, E), resid_std),
        "proj_bias": jnp.zeros((L, E), dt),
        "ln2_scale": jnp.ones((L, E), dt),
        "ln2_bias": jnp.zeros((L, E), dt),
    }
    if c.num_experts > 0:
        X = c.num_experts
        blocks.update({
            "router": norm(k[6], (L, E, X), std),
            "moe_in": norm(k[2], (L, X, E, M), std),
            "moe_out": norm(k[3], (L, X, M, E), resid_std),
        })
    else:
        blocks.update({
            "fc_kernel": norm(k[2], (L, E, M), std),
            "fc_bias": jnp.zeros((L, M), dt),
            "out_kernel": norm(k[3], (L, M, E), resid_std),
            "out_bias": jnp.zeros((L, E), dt),
        })
    return {
        "wte": norm(k[4], (c.vocab_size, E), std),
        "wpe": norm(k[5], (c.max_seq_len, E), 0.01),
        "blocks": blocks,
        "lnf_scale": jnp.ones((E,), dt),
        "lnf_bias": jnp.zeros((E,), dt),
    }


from ray_tpu.models.common import layernorm as _layernorm  # noqa: E402


def _attention(q, k, v, config: GPTConfig):
    if config.attention_impl == "ring":
        from ray_tpu.ops.ring_attention import ring_attention

        return ring_attention(q, k, v)
    return _dense_attention(q, k, v)


def _moe_mlp(h, p, config: GPTConfig, mask=None):
    """Top-1 (switch) MoE MLP.  h (B, S, E) post-norm → (delta, aux).

    GShard-style dense dispatch, GROUPED BY BATCH ROW: each row routes
    its S tokens independently with per-row expert capacity
    C = ceil(cap_factor · S / X), so the one-hot dispatch tensor is
    (B, S, X, C) — O(B·S²·cap/X·X) = O(B·S²·cap) memory instead of the
    O((B·S)²) a globally-flattened dispatch costs, and the routing
    cumsum runs along S (no serialization across the dp-sharded batch
    axis).  Expert FFN weights shard over ep ("expert" logical axis);
    under pjit the dispatch/combine einsums compile to all_to_all over
    ICI.  Tokens past capacity pass through the residual (standard
    switch behavior).  aux is the Switch load-balancing loss
    X·Σ f_i·P_i (1.0 at perfect balance).

    `mask` (B, S) zeroes padding tokens out of routing entirely: they
    consume no expert capacity and the aux statistics count only real
    tokens."""
    c = config
    B, S, E = h.shape
    X = c.num_experts
    C = max(1, math.ceil(c.moe_capacity_factor * S / X))
    router_logits = jnp.einsum(
        "bse,ex->bsx", h.astype(jnp.float32),
        p["router"].astype(jnp.float32),
    )
    probs = jax.nn.softmax(router_logits, axis=-1)  # (B, S, X) f32
    gate = probs.max(axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    onehot = jax.nn.one_hot(expert, X, dtype=jnp.float32)  # (B, S, X)
    if mask is not None:
        onehot = onehot * mask[..., None].astype(jnp.float32)
    # position of each token within its row's expert capacity buffer
    pos = jnp.cumsum(onehot, axis=1) * onehot - 1.0
    disp = jnp.where((pos >= 0) & (pos < C), onehot, 0.0)
    pos_idx = jnp.clip(pos, 0, C - 1).astype(jnp.int32)
    disp_bsxc = disp[..., None] * jax.nn.one_hot(pos_idx, C,
                                                 dtype=jnp.float32)
    expert_in = jnp.einsum(
        "bsxc,bse->bxce", disp_bsxc, h.astype(jnp.float32)
    ).astype(c.dtype)
    expert_in = constrain(expert_in, ("batch", "expert", None, "embed"))
    hmid = jax.nn.gelu(jnp.einsum(
        "bxce,xem->bxcm", expert_in, p["moe_in"].astype(c.dtype)
    ))
    hmid = constrain(hmid, ("batch", "expert", None, "mlp"))
    expert_out = jnp.einsum(
        "bxcm,xme->bxce", hmid, p["moe_out"].astype(c.dtype)
    )
    expert_out = constrain(expert_out, ("batch", "expert", None, "embed"))
    combine = (disp_bsxc * gate[..., None, None]).astype(c.dtype)
    out = jnp.einsum("bsxc,bxce->bse", combine, expert_out)
    if mask is None:
        f = onehot.mean(axis=(0, 1))
        P = probs.mean(axis=(0, 1))
    else:
        m = mask[..., None].astype(jnp.float32)
        denom = jnp.maximum(m.sum(), 1.0)
        f = onehot.sum(axis=(0, 1)) / denom
        P = (probs * m).sum(axis=(0, 1)) / denom
    aux = (X * jnp.sum(f * P)).astype(jnp.float32)
    return out, aux


def _block(x, p, config: GPTConfig, mask=None):
    """One transformer block. x: (B, S, E); p: per-layer param slice.
    Returns (x, moe_aux) — aux is 0.0 for dense-MLP blocks."""
    c = config
    S = x.shape[1]
    h = _layernorm(x, p["ln1_scale"], p["ln1_bias"])
    if c.attention_impl == "flash" and S % 128 == 0:
        # Kernel-native (B, H, S, D) layout: the qkv/proj einsums emit and
        # consume it directly, so no transposes surround the pallas call.
        # Non-128-multiple S falls through to the dense path below — the
        # kernel requires block-divisible sequence lengths.
        from ray_tpu.ops.flash_attention import sharded_flash_attention_bhsd

        qkv = jnp.einsum(
            "bse,ehd->bhsd", h, p["qkv_kernel"].astype(c.dtype)
        ) + p["qkv_bias"].astype(c.dtype)[None, :, None, :]
        q, k, v = jnp.split(qkv, 3, axis=1)
        q = constrain(q, ("batch", "heads", "seq", None))
        k = constrain(k, ("batch", "heads", "seq", None))
        v = constrain(v, ("batch", "heads", "seq", None))
        attn = sharded_flash_attention_bhsd(q, k, v)
        x = x + jnp.einsum(
            "bhsd,hde->bse", attn, p["proj_kernel"].astype(c.dtype)
        ) + p["proj_bias"].astype(c.dtype)
    else:
        qkv = (
            jnp.einsum("bse,ehd->bshd", h, p["qkv_kernel"].astype(c.dtype))
            + p["qkv_bias"].astype(c.dtype)
        )
        q, k, v = jnp.split(qkv, 3, axis=2)
        q = constrain(q, ("batch", "seq", "heads", None))
        k = constrain(k, ("batch", "seq", "heads", None))
        v = constrain(v, ("batch", "seq", "heads", None))
        attn = _attention(q, k, v, c)
        x = x + jnp.einsum(
            "bshd,hde->bse", attn, p["proj_kernel"].astype(c.dtype)
        ) + p["proj_bias"].astype(c.dtype)
    x = constrain(x, ("batch", "seq", "embed"))
    h = _layernorm(x, p["ln2_scale"], p["ln2_bias"])
    if "moe_in" in p:
        delta, aux = _moe_mlp(h, p, c, mask)
        x = x + delta
    else:
        h = jnp.einsum("bse,em->bsm", h, p["fc_kernel"].astype(c.dtype))
        h = jax.nn.gelu(h + p["fc_bias"].astype(c.dtype))
        h = constrain(h, ("batch", "seq", "mlp"))
        x = x + jnp.einsum(
            "bsm,me->bse", h, p["out_kernel"].astype(c.dtype)
        ) + p["out_bias"].astype(c.dtype)
        aux = jnp.float32(0.0)
    return constrain(x, ("batch", "seq", "embed")), aux


def _features_aux(params: Params, tokens, config: GPTConfig, mask=None):
    """tokens (B, S) int32 → (final-layernorm features (B, S, E),
    summed MoE aux loss).

    The pre-head backbone, split out so the chunked cross-entropy can
    apply the lm_head per sequence chunk instead of materializing the
    full (B, S, vocab) f32 logits (the single largest activation — 3.3
    GB at B=16, S=1024, V=50304)."""
    c = config
    B, S = tokens.shape
    # Explicitly all-gather the embedding table for the lookup: a gather
    # from the (vocab/tp, embed/fsdp)-sharded table forces SPMD into
    # "involuntary full rematerialization" (replicate + repartition every
    # step).  Constraining the operand replicated makes the all-gather a
    # deliberate, one-per-step collective and lets the gather partition
    # cleanly along the tokens' batch/seq sharding.  The lm_head einsum
    # below still consumes the sharded table.
    wte_lookup = constrain(params["wte"], (None, None)).astype(c.dtype)
    x = wte_lookup[tokens]
    x = x + params["wpe"].astype(c.dtype)[:S]
    x = constrain(x, ("batch", "seq", "embed"))

    def body(carry, layer_params):
        xx, aux_sum = carry
        fn = _block
        if c.remat:
            fn = jax.checkpoint(_block, static_argnums=(2,))
        xx, aux = fn(xx, layer_params, c, mask)
        return (xx, aux_sum + aux), None

    (x, aux), _ = lax.scan(
        body, (x, jnp.float32(0.0)), params["blocks"],
        unroll=max(1, c.scan_unroll),
    )
    return _layernorm(x, params["lnf_scale"], params["lnf_bias"]), aux


def features(params: Params, tokens, config: GPTConfig):
    """tokens (B, S) int32 → final-layernorm features (B, S, E)."""
    return _features_aux(params, tokens, config)[0]


def _head(params: Params, x, config: GPTConfig):
    """Tied lm_head: features (B, S, E) → logits (B, S, V) f32."""
    logits = jnp.einsum(
        "bse,ve->bsv",
        x,
        params["wte"].astype(config.dtype),
        preferred_element_type=jnp.float32,
    )
    return constrain(logits, ("batch", "seq", "vocab"))


def forward(params: Params, tokens, config: GPTConfig):
    """tokens (B, S) int32 → logits (B, S, vocab) in f32."""
    return _head(params, features(params, tokens, config), config)


def loss_fn(params: Params, batch, config: GPTConfig):
    """Next-token cross-entropy.  batch: {"tokens": (B, S+1) int32} or
    {"inputs", "targets"} each (B, S).  With config.xent_chunk set (and
    S divisible by it) the lm_head+softmax runs per sequence chunk,
    capping peak logits memory."""
    if "tokens" in batch:
        inputs = batch["tokens"][:, :-1]
        targets = batch["tokens"][:, 1:]
    else:
        inputs, targets = batch["inputs"], batch["targets"]
    x, aux = _features_aux(params, inputs, config, batch.get("mask"))
    aux_term = (
        config.moe_aux_coeff * aux if config.num_experts > 0 else 0.0
    )
    if config.xent_chunk and inputs.shape[1] % config.xent_chunk == 0:
        from ray_tpu.models.xent import chunked_xent

        return chunked_xent(
            x, params["wte"], targets, batch.get("mask"),
            config.xent_chunk, config.dtype,
        ) + aux_term
    logits = _head(params, x, config)
    # lse − target_logit instead of log_softmax + gather: avoids writing a
    # second full (B, S, V) f32 array (1.6 GB at B=8, S=1024, V=50k).
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    tl = jnp.take_along_axis(
        logits.astype(jnp.float32), targets[..., None], axis=-1
    )[..., 0]
    ll = tl - lse
    mask = batch.get("mask")
    if mask is None:
        return -ll.mean() + aux_term
    mask = mask.astype(jnp.float32)
    return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0) + aux_term


def num_params(config: GPTConfig) -> int:
    shapes = jax.eval_shape(partial(init, config=config), jax.random.key(0))
    return sum(
        math.prod(a.shape) for a in jax.tree.leaves(shapes)
    )


def flops_per_token(config: GPTConfig, seq_len: Optional[int] = None) -> float:
    """Approximate training FLOPs/token: 6N + attention term.

    N excludes the position table but keeps wte — the lm_head is tied to
    it, so its matmul is real executed compute (nanoGPT estimate_mfu
    convention; under-counting it would overstate MFU headroom).
    """
    c = config
    s = seq_len or c.max_seq_len
    n = num_params(c) - c.max_seq_len * c.embed_dim  # minus wpe only
    if c.num_experts > 1:
        # top-1 routing executes ONE expert FFN per token: count 1/X of
        # the expert-FFN params as active compute (else MoE MFU would be
        # overstated ~X-fold)
        moe_ffn = 2 * c.num_layers * c.num_experts * c.embed_dim * c.mlp_dim
        n = n - moe_ffn + moe_ffn // c.num_experts
    return 6 * n + 12 * c.num_layers * c.embed_dim * s
