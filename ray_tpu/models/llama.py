"""Llama-family decoder: RMSNorm + RoPE + GQA + SwiGLU, TPU-first.

Second LM family beside GPT-2 (models/gpt2.py), matching the serving
workload the reference's release tests target (ray:
release/serve_tests Llama configs; doc/source/serve LLM examples).
Same design language as gpt2.py: stacked-layer params (one pytree leaf
per parameter kind, lax.scan-friendly), logical-axis sharding
annotations compiled by pjit (parallel/sharding.py rule table), bf16
matmuls with f32 layernorms/softmax, optional ring attention for
sequence parallelism, and a chunked cross-entropy for HBM-sized logits.

Grouped-query attention: num_kv_heads < num_heads shares each KV head
across num_heads // num_kv_heads query heads (Llama-2-70B/Llama-3
layout; num_kv_heads == num_heads gives classic MHA).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.sharding import constrain

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_seq_len: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    embed_dim: int = 4096
    mlp_dim: int = 11008
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attention_impl: str = "dense"  # "dense" | "ring"
    # sliding-window attention (Mistral-style): > 0 limits every query
    # to the last `sliding_window` keys, in training AND in the cached
    # decode paths.  0 = full causal.
    sliding_window: int = 0
    remat: bool = True
    xent_chunk: int = 0
    scan_unroll: int = 1
    tie_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads

    @staticmethod
    def llama2_7b(**kw) -> "LlamaConfig":
        return LlamaConfig(**kw)

    @staticmethod
    def mistral_7b(**kw) -> "LlamaConfig":
        """Mistral-7B shape: GQA (8 KV heads) + 4096-token sliding
        window over a 32k context."""
        kw.setdefault("vocab_size", 32000)
        kw.setdefault("max_seq_len", 32768)
        kw.setdefault("num_layers", 32)
        kw.setdefault("num_heads", 32)
        kw.setdefault("num_kv_heads", 8)
        kw.setdefault("embed_dim", 4096)
        kw.setdefault("mlp_dim", 14336)
        kw.setdefault("sliding_window", 4096)
        return LlamaConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        defaults = dict(
            vocab_size=256, max_seq_len=128, num_layers=2, num_heads=4,
            num_kv_heads=2, embed_dim=64, mlp_dim=160,
            dtype=jnp.float32, remat=False,
        )
        defaults.update(kw)
        return LlamaConfig(**defaults)


def param_logical_axes(config: LlamaConfig) -> Dict[str, Any]:
    """Per-parameter logical axis names (parallel/sharding.py specs)."""
    blk = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads", None),
        "wk": ("layers", "embed", "kv", None),
        "wv": ("layers", "embed", "kv", None),
        "wo": ("layers", "heads", None, "embed"),
        "mlp_norm": ("layers", "embed"),
        "w_gate": ("layers", "embed", "mlp"),
        "w_up": ("layers", "embed", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
    }
    out = {
        "tok_embed": ("vocab", "embed"),
        "blocks": blk,
        "final_norm": ("embed",),
    }
    if not config.tie_embeddings:
        out["lm_head"] = ("vocab", "embed")
    return out


def init(rng, config: LlamaConfig) -> Params:
    c = config
    dt = c.param_dtype
    L, E, H, KV, D, M = (
        c.num_layers, c.embed_dim, c.num_heads, c.num_kv_heads,
        c.head_dim, c.mlp_dim,
    )
    k = jax.random.split(rng, 8)
    std = 0.02
    resid_std = std / math.sqrt(2 * L)

    def norm(key, shape, s):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(dt)

    params: Params = {
        "tok_embed": norm(k[0], (c.vocab_size, E), std),
        "blocks": {
            "attn_norm": jnp.ones((L, E), dt),
            "wq": norm(k[1], (L, E, H, D), std),
            "wk": norm(k[2], (L, E, KV, D), std),
            "wv": norm(k[3], (L, E, KV, D), std),
            "wo": norm(k[4], (L, H, D, E), resid_std),
            "mlp_norm": jnp.ones((L, E), dt),
            "w_gate": norm(k[5], (L, E, M), std),
            "w_up": norm(k[6], (L, E, M), std),
            "w_down": norm(k[7], (L, M, E), resid_std),
        },
        "final_norm": jnp.ones((E,), dt),
    }
    if not c.tie_embeddings:
        params["lm_head"] = norm(
            jax.random.fold_in(k[0], 1), (c.vocab_size, E), std
        )
    return params


def _rmsnorm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    y = x32 * lax.rsqrt((x32 * x32).mean(-1, keepdims=True) + eps)
    return (y * scale.astype(jnp.float32)).astype(x.dtype)


def _rope(x, positions, theta):
    """Rotary embedding over the last dim.  x: (B, S, H, D)."""
    D = x.shape[-1]
    half = D // 2
    freqs = jnp.exp(
        -math.log(theta) * jnp.arange(0, half, dtype=jnp.float32) / half
    )
    angles = positions[:, :, None].astype(jnp.float32) * freqs[None, None, :]
    cos = jnp.cos(angles)[:, :, None, :]  # (B, S, 1, half)
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(x.dtype)


def _attention(q, k, v, config: LlamaConfig):
    if config.attention_impl == "ring":
        if config.sliding_window:
            raise NotImplementedError(
                "sliding_window with ring attention: window the KV ring "
                "instead (sp shards already bound the lookback)"
            )
        from ray_tpu.ops.ring_attention import ring_attention

        return ring_attention(q, k, v)
    from ray_tpu.ops.attention import dense_attention

    return dense_attention(q, k, v, window=config.sliding_window)


def _block(x, p, positions, config: LlamaConfig):
    c = config
    h = _rmsnorm(x, p["attn_norm"], c.rms_eps)
    q = jnp.einsum("bse,ehd->bshd", h, p["wq"].astype(c.dtype))
    kk = jnp.einsum("bse,ekd->bskd", h, p["wk"].astype(c.dtype))
    vv = jnp.einsum("bse,ekd->bskd", h, p["wv"].astype(c.dtype))
    q = _rope(q, positions, c.rope_theta)
    kk = _rope(kk, positions, c.rope_theta)
    # GQA: repeat each KV head across its query group
    if c.q_per_kv > 1:
        kk = jnp.repeat(kk, c.q_per_kv, axis=2)
        vv = jnp.repeat(vv, c.q_per_kv, axis=2)
    q = constrain(q, ("batch", "seq", "heads", None))
    kk = constrain(kk, ("batch", "seq", "heads", None))
    vv = constrain(vv, ("batch", "seq", "heads", None))
    attn = _attention(q, kk, vv, c)
    x = x + jnp.einsum("bshd,hde->bse", attn, p["wo"].astype(c.dtype))
    x = constrain(x, ("batch", "seq", "embed"))
    h = _rmsnorm(x, p["mlp_norm"], c.rms_eps)
    gate = jnp.einsum("bse,em->bsm", h, p["w_gate"].astype(c.dtype))
    up = jnp.einsum("bse,em->bsm", h, p["w_up"].astype(c.dtype))
    h = jax.nn.silu(gate) * up
    h = constrain(h, ("batch", "seq", "mlp"))
    x = x + jnp.einsum("bsm,me->bse", h, p["w_down"].astype(c.dtype))
    return constrain(x, ("batch", "seq", "embed"))


def features(params: Params, tokens, config: LlamaConfig):
    """tokens (B, S) int32 → final-RMSNorm features (B, S, E)."""
    c = config
    B, S = tokens.shape
    emb = constrain(params["tok_embed"], (None, None)).astype(c.dtype)
    x = emb[tokens]
    x = constrain(x, ("batch", "seq", "embed"))
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))

    def body(carry, layer_params):
        fn = _block
        if c.remat:
            fn = jax.checkpoint(_block, static_argnums=(3,))
        return fn(carry, layer_params, positions, c), None

    x, _ = lax.scan(
        body, x, params["blocks"], unroll=max(1, c.scan_unroll)
    )
    return _rmsnorm(x, params["final_norm"], c.rms_eps)


def _head_weight(params: Params, config: LlamaConfig):
    return params["tok_embed"] if config.tie_embeddings else params["lm_head"]


def forward(params: Params, tokens, config: LlamaConfig):
    """tokens (B, S) int32 → logits (B, S, vocab) f32."""
    x = features(params, tokens, config)
    logits = jnp.einsum(
        "bse,ve->bsv",
        x,
        _head_weight(params, config).astype(config.dtype),
        preferred_element_type=jnp.float32,
    )
    return constrain(logits, ("batch", "seq", "vocab"))


def loss_fn(params: Params, batch, config: LlamaConfig):
    """Next-token cross-entropy; same contract as gpt2.loss_fn
    (tokens | inputs/targets, optional mask, optional chunked head)."""
    if "tokens" in batch:
        inputs = batch["tokens"][:, :-1]
        targets = batch["tokens"][:, 1:]
    else:
        inputs, targets = batch["inputs"], batch["targets"]
    mask = batch.get("mask")
    c = config
    if c.xent_chunk and inputs.shape[1] % c.xent_chunk == 0:
        from ray_tpu.models.xent import chunked_xent

        x = features(params, inputs, config)
        return chunked_xent(
            x, _head_weight(params, c), targets, mask, c.xent_chunk,
            c.dtype,
        )
    logits = forward(params, inputs, config)
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    tl = jnp.take_along_axis(
        logits.astype(jnp.float32), targets[..., None], axis=-1
    )[..., 0]
    ll = tl - lse
    if mask is None:
        return -ll.mean()
    mask = mask.astype(jnp.float32)
    return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def num_params(config: LlamaConfig) -> int:
    shapes = jax.eval_shape(partial(init, config=config), jax.random.key(0))
    return sum(math.prod(a.shape) for a in jax.tree.leaves(shapes))


def flops_per_token(config: LlamaConfig, seq_len: Optional[int] = None) -> float:
    """fwd+bwd FLOPs per token: 6N + attention quadratic term."""
    c = config
    S = seq_len or c.max_seq_len
    n = num_params(c) - c.vocab_size * c.embed_dim * (
        0 if c.tie_embeddings else 1
    )
    attn = 12 * c.num_layers * c.embed_dim * S  # 2*2*3 * L * E * S
    return 6.0 * n + attn


def generate(params: Params, prompt, config: LlamaConfig, *,
             max_new_tokens: int = 32, temperature: float = 0.0,
             rng=None):
    """Greedy/sampled decode (B, S) → (B, S + max_new_tokens).

    The context is padded once to the fixed bucket S + max_new_tokens
    and the step function takes the current length as a traced index —
    ONE compiled executable serves every decode step (no per-token
    recompile).  Each step still recomputes the full context (O(S²)
    total; the KV-cache incremental decode is the planned serving fast
    path, see ops/attention.py dense_attention(start_pos=...)).
    temperature 0 is argmax; otherwise categorical sampling."""
    tokens = jnp.asarray(prompt, jnp.int32)
    B, S0 = tokens.shape
    if max_new_tokens <= 0:
        return tokens
    total = S0 + max_new_tokens
    padded = jnp.zeros((B, total), jnp.int32).at[:, :S0].set(tokens)
    temperature = float(temperature or 0.0)  # None == greedy
    key = rng if rng is not None else jax.random.key(0)
    for i in range(max_new_tokens):
        key, sub = jax.random.split(key)
        padded = _gen_step(params, padded, jnp.int32(S0 + i), sub,
                           config=config, temperature=temperature)
    return padded


@partial(jax.jit, static_argnames=("config", "temperature"))
def _gen_step(params, padded, length, key, *, config, temperature):
    """One full-recompute decode step — MODULE-LEVEL jit, so its cache
    is keyed by (config, shapes), not per-call closures: repeat
    generate() calls reuse one executable."""
    logits = forward(params, padded, config)  # (B, total, V)
    B = padded.shape[0]
    # causal attention: position length-1 only sees real tokens, so the
    # padding beyond it cannot leak into this readout
    last = jnp.take_along_axis(
        logits, (length - 1)[None, None, None].repeat(B, 0), axis=1
    )[:, 0, :]
    nxt = _pick_token(last, key, temperature=temperature)
    return lax.dynamic_update_slice(
        padded, nxt[:, None].astype(jnp.int32), (0, length)
    )


# ---------------------------------------------------------------------------
# KV-cache incremental decoding (the serving fast path)
# ---------------------------------------------------------------------------


def init_cache(config: LlamaConfig, batch_size: int, max_len: int) -> Params:
    """Fixed-bucket KV cache: (L, B, max_len, KV, D) per tensor, bf16.
    Static shapes — one compiled prefill + one compiled decode step
    serve any request up to max_len.

    With ``sliding_window`` the cache is a ROLLING buffer (slot =
    position mod max_len), so ``max_len`` can be as small as
    ``window + max_prefill_chunk - 1`` regardless of how long decoding
    runs — the Mistral memory win (8x at 32k context / 4k window).
    Positions older than the window are overwritten in place; the
    attention mask reconstructs each slot's position implicitly."""
    c = config
    shape = (c.num_layers, batch_size, max_len, c.num_kv_heads, c.head_dim)
    return {
        "k": jnp.zeros(shape, c.dtype),
        "v": jnp.zeros(shape, c.dtype),
    }


def rolling_cache_len(config: LlamaConfig, prefill_chunk: int) -> int:
    """Smallest safe rolling-cache length for unbounded windowed
    decoding: ``window + prefill_chunk - 1`` slots guarantees a wrapped
    write can only land on a position already outside every live
    query's window (the Mistral memory bound — independent of how long
    decoding runs)."""
    assert config.sliding_window > 0, "rolling caches need sliding_window"
    return config.sliding_window + max(1, prefill_chunk) - 1


def _rolling_mask(q_pos, t_idx, T: int, window: int):
    """Validity mask for rolling-buffer slots: slot s as seen by query
    position q holds position q - ((q - s) mod T) — the newest position
    <= q congruent to s.  Valid iff non-negative and inside the window.
    q_pos: (..., 1)-broadcastable positions; t_idx: (T,) slot indices.
    The ONE implementation both cached-attention paths share."""
    t_pos = q_pos - ((q_pos - t_idx) % T)
    return (t_pos >= 0) & (t_pos > q_pos - window)


def _cached_attention(q, k_cache, v_cache, pos, config: LlamaConfig):
    """q: (B, Sq, H, D) attends over cache[:, :T]; positions > pos are
    masked.  Works for prefill (Sq = prompt len, pos = len-1) and decode
    (Sq = 1)."""
    c = config
    B, Sq, H, D = q.shape
    T = k_cache.shape[1]
    if c.q_per_kv > 1:
        k_cache = jnp.repeat(k_cache, c.q_per_kv, axis=2)
        v_cache = jnp.repeat(v_cache, c.q_per_kv, axis=2)
    scores = jnp.einsum(
        "bqhd,bthd->bhqt", q, k_cache, preferred_element_type=jnp.float32
    ) / math.sqrt(D)
    # causal within the query block + bounded by pos overall
    q_pos = pos - (Sq - 1) + jnp.arange(Sq)  # absolute position per query
    t_idx = jnp.arange(T)
    if c.sliding_window:
        # rolling buffer (slot correctness needs T >= window + Sq - 1:
        # see rolling_cache_len / forward_cached)
        mask = _rolling_mask(
            q_pos[:, None], t_idx[None, :], T, c.sliding_window
        )
    else:
        mask = t_idx[None, :] <= q_pos[:, None]  # (Sq, T)
    scores = jnp.where(mask[None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(c.dtype)
    return jnp.einsum("bhqt,bthd->bqhd", probs, v_cache)


def _block_cached(x, p, cache_k, cache_v, start, config: LlamaConfig):
    """One block over Sq new tokens starting at absolute `start`;
    returns (x_out, new_cache_k, new_cache_v)."""
    c = config
    B, Sq, _ = x.shape
    h = _rmsnorm(x, p["attn_norm"], c.rms_eps)
    positions = (start + jnp.arange(Sq))[None, :].repeat(B, 0)
    q = _rope(
        jnp.einsum("bse,ehd->bshd", h, p["wq"].astype(c.dtype)),
        positions, c.rope_theta,
    )
    kk = _rope(
        jnp.einsum("bse,ekd->bskd", h, p["wk"].astype(c.dtype)),
        positions, c.rope_theta,
    )
    vv = jnp.einsum("bse,ekd->bskd", h, p["wv"].astype(c.dtype))
    if c.sliding_window:
        # rolling buffer: position t lives in slot t mod T
        slots = (start + jnp.arange(Sq)) % cache_k.shape[1]
        cache_k = cache_k.at[:, slots].set(kk.astype(c.dtype))
        cache_v = cache_v.at[:, slots].set(vv.astype(c.dtype))
    else:
        cache_k = lax.dynamic_update_slice(
            cache_k, kk.astype(c.dtype), (0, start, 0, 0)
        )
        cache_v = lax.dynamic_update_slice(
            cache_v, vv.astype(c.dtype), (0, start, 0, 0)
        )
    attn = _cached_attention(q, cache_k, cache_v, start + Sq - 1, c)
    x = x + jnp.einsum("bshd,hde->bse", attn, p["wo"].astype(c.dtype))
    h = _rmsnorm(x, p["mlp_norm"], c.rms_eps)
    gate = jnp.einsum("bse,em->bsm", h, p["w_gate"].astype(c.dtype))
    up = jnp.einsum("bse,em->bsm", h, p["w_up"].astype(c.dtype))
    x = x + jnp.einsum(
        "bsm,me->bse", jax.nn.silu(gate) * up, p["w_down"].astype(c.dtype)
    )
    return x, cache_k, cache_v


def forward_cached(params: Params, tokens, cache: Params, start,
                   config: LlamaConfig):
    """Run Sq new tokens through all layers, updating the cache.

    Returns (last_logits (B, V), new_cache).  `start` is the absolute
    position of tokens[:, 0] (0 for prefill; prompt_len + i in decode) —
    a traced scalar, so one compile covers every step."""
    c = config
    if c.sliding_window:
        T, Sq = cache["k"].shape[2], tokens.shape[1]
        # structural bound only: a chunk longer than the cache would
        # self-overwrite within one write-set.  Whether WRAPPING (a
        # position overwriting position-minus-T) is safe depends on how
        # far the caller decodes: positions < T never wrap (generate_kv
        # sizes exactly so), and truly rolling callers size via
        # rolling_cache_len() so wrapped slots are always out-of-window.
        assert Sq <= T, (
            f"prefill chunk {Sq} exceeds cache length {T}; prefill long "
            "prompts in chunks"
        )
    x = params["tok_embed"].astype(c.dtype)[tokens]

    def body(carry, layer):
        xx, _ = carry
        p, ck, cv = layer
        xx, ck, cv = _block_cached(xx, p, ck, cv, start, c)
        return (xx, None), (ck, cv)

    (x, _), (new_k, new_v) = lax.scan(
        body, (x, None), (params["blocks"], cache["k"], cache["v"])
    )
    x = _rmsnorm(x, params["final_norm"], c.rms_eps)
    logits = jnp.einsum(
        "be,ve->bv",
        x[:, -1, :],
        _head_weight(params, c).astype(c.dtype),
        preferred_element_type=jnp.float32,
    )
    return logits, {"k": new_k, "v": new_v}


def generate_kv(params: Params, prompt, config: LlamaConfig, *,
                max_new_tokens: int = 32, temperature: float = 0.0,
                rng=None):
    """KV-cache decode: prefill once, then one O(1)-per-token compiled
    step — the serving fast path (vs generate()'s full recompute)."""
    tokens = jnp.asarray(prompt, jnp.int32)
    B, S0 = tokens.shape
    if max_new_tokens <= 0:
        return tokens
    total = S0 + max_new_tokens
    cache = init_cache(config, B, total)
    temperature = float(temperature or 0.0)  # None == greedy

    logits, cache = _prefill_jit(params, tokens, cache, jnp.int32(0),
                                 config=config)
    key = rng if rng is not None else jax.random.key(0)
    key, sub = jax.random.split(key)
    nxt = _pick_token(logits, sub, temperature=temperature)
    out = [tokens, nxt[:, None]]
    for i in range(1, max_new_tokens):
        key, sub = jax.random.split(key)
        nxt, cache = _decode_step(
            params, nxt[:, None], cache, jnp.int32(S0 + i - 1), sub,
            config=config, temperature=temperature,
        )
        out.append(nxt[:, None])
    return jnp.concatenate(out, axis=1)


# module-level jits: caches keyed by (config, shapes, temperature) so
# repeated generate_kv calls — e.g. per serve request — reuse ONE
# compiled prefill and ONE compiled decode step.  The cache buffers are
# DONATED: the (L, B, max_len, KV, D) k/v arrays update in place instead
# of being copied every token (the copy would dominate decode bandwidth
# on a real config).
_prefill_jit = jax.jit(
    forward_cached, static_argnames="config", donate_argnames=("cache",)
)


@partial(jax.jit, static_argnames=("temperature",))
def _pick_token(logits, key, *, temperature):
    if temperature > 0.0:
        return jax.random.categorical(key, logits / temperature).astype(
            jnp.int32
        )
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


@partial(
    jax.jit,
    static_argnames=("config", "temperature"),
    donate_argnames=("cache",),
)
def _decode_step(params, tok, cache, start, key, *, config, temperature):
    logits, cache = forward_cached(params, tok, cache, start, config)
    return _pick_token(logits, key, temperature=temperature), cache


# -- continuous batching (row-wise positions) --------------------------------
# Serving batches sequences at DIFFERENT positions: each cache row b has
# its own length pos[b].  The decode step scatters the new K/V at
# [b, pos[b]] and masks attention per row — the primitive a continuous
# batcher needs (reference role: vLLM-on-ray / serve LLM replicas; here
# one fused XLA step for the whole slot batch).


def _block_decode_rowwise(x, p, cache_k, cache_v, pos, config: LlamaConfig):
    """One block for ONE new token per row.  x: (B, 1, E); pos: (B,)
    absolute position of the new token in each row."""
    c = config
    B = x.shape[0]
    h = _rmsnorm(x, p["attn_norm"], c.rms_eps)
    positions = pos[:, None]  # (B, 1)
    q = _rope(
        jnp.einsum("bse,ehd->bshd", h, p["wq"].astype(c.dtype)),
        positions, c.rope_theta,
    )
    kk = _rope(
        jnp.einsum("bse,ekd->bskd", h, p["wk"].astype(c.dtype)),
        positions, c.rope_theta,
    )
    vv = jnp.einsum("bse,ekd->bskd", h, p["wv"].astype(c.dtype))
    rows = jnp.arange(B)
    T = cache_k.shape[1]
    slot = pos % T if c.sliding_window else pos  # rolling buffer slots
    cache_k = cache_k.at[rows, slot].set(kk[:, 0].astype(c.dtype))
    cache_v = cache_v.at[rows, slot].set(vv[:, 0].astype(c.dtype))
    # attention over each row's own prefix [0, pos[b]]
    k_all, v_all = cache_k, cache_v
    if c.q_per_kv > 1:
        k_all = jnp.repeat(k_all, c.q_per_kv, axis=2)
        v_all = jnp.repeat(v_all, c.q_per_kv, axis=2)
    scores = jnp.einsum(
        "bqhd,bthd->bhqt", q, k_all, preferred_element_type=jnp.float32
    ) / math.sqrt(c.head_dim)
    t_idx = jnp.arange(T)
    if c.sliding_window:
        # rolling buffer: reconstruct each slot's position per row
        mask = _rolling_mask(
            pos[:, None], t_idx[None, :], T, c.sliding_window
        )
    else:
        mask = t_idx[None, :] <= pos[:, None]  # (B, T)
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(c.dtype)
    attn = jnp.einsum("bhqt,bthd->bqhd", probs, v_all)
    x = x + jnp.einsum("bshd,hde->bse", attn, p["wo"].astype(c.dtype))
    h = _rmsnorm(x, p["mlp_norm"], c.rms_eps)
    gate = jnp.einsum("bse,em->bsm", h, p["w_gate"].astype(c.dtype))
    up = jnp.einsum("bse,em->bsm", h, p["w_up"].astype(c.dtype))
    x = x + jnp.einsum(
        "bsm,me->bse", jax.nn.silu(gate) * up, p["w_down"].astype(c.dtype)
    )
    return x, cache_k, cache_v


@partial(jax.jit, static_argnames=("config",), donate_argnames=("cache",))
def decode_step_rowwise(params, tokens, cache, pos, config: LlamaConfig):
    """One token for every row at per-row positions.

    tokens: (B,) int32 last token per row; pos: (B,) its absolute
    position.  Returns (logits (B, V) f32, new cache).  Inactive rows
    simply keep decoding garbage into their own slots — the engine masks
    them out — so the compiled shape never changes."""
    c = config
    x = params["tok_embed"].astype(c.dtype)[tokens][:, None, :]

    def body(carry, layer):
        xx, _ = carry
        p, ck, cv = layer
        xx, ck, cv = _block_decode_rowwise(xx, p, ck, cv, pos, c)
        return (xx, None), (ck, cv)

    (x, _), (new_k, new_v) = lax.scan(
        body, (x, None), (params["blocks"], cache["k"], cache["v"])
    )
    x = _rmsnorm(x, params["final_norm"], c.rms_eps)
    logits = jnp.einsum(
        "be,ve->bv",
        x[:, -1, :],
        _head_weight(params, c).astype(c.dtype),
        preferred_element_type=jnp.float32,
    )
    return logits, {"k": new_k, "v": new_v}


@partial(jax.jit, static_argnames=("config",), donate_argnames=("cache",))
def prefill_into_slot(params, tokens, cache, slot, config: LlamaConfig):
    """Prefill ONE sequence into batched-cache row ``slot``.

    tokens: (1, S) prompt; cache: the engine's (L, B, T, KV, D) batch
    cache.  Returns (last-token logits (1, V), updated cache).  One
    compile per prompt-bucket length serves every slot (slot is traced).
    """
    sub = {
        "k": lax.dynamic_slice_in_dim(cache["k"], slot, 1, axis=1),
        "v": lax.dynamic_slice_in_dim(cache["v"], slot, 1, axis=1),
    }
    logits, sub = forward_cached(params, tokens, sub, jnp.int32(0), config)
    cache = {
        "k": lax.dynamic_update_slice_in_dim(cache["k"], sub["k"], slot,
                                             axis=1),
        "v": lax.dynamic_update_slice_in_dim(cache["v"], sub["v"], slot,
                                             axis=1),
    }
    return logits, cache
