"""Numerics shared across model families."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def layernorm(x, scale, bias, eps: float = 1e-5):
    """f32-accumulated LayerNorm returned in x.dtype (the single
    implementation gpt2 and vit share)."""
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    y = (x32 - mu) * lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(
        x.dtype
    )
