"""HuggingFace Transformers interop for the flagship GPT-2.

Role-equivalent of ray: python/ray/train/huggingface/ (Transformers
integration) — here the useful TPU form: convert a `transformers`
GPT2LMHeadModel's torch weights into this repo's stacked-layer jax
params (models/gpt2.py layout) so pretrained checkpoints train/serve on
the TPU stack.  The reverse of a "wrapper": weights move into the
TPU-native model rather than wrapping torch in actors.

Layout notes:
- HF Conv1D stores (in, out); our einsum kernels are (in, ...) too, so
  no transposes except the qkv head split.
- HF c_attn is (E, 3E) = [q|k|v]; ours is (E, 3H, D) with q heads at
  [0:H], k at [H:2H], v at [2H:3H] (models/gpt2.py _block split).
- Per-layer tensors stack into a leading L axis (lax.scan-friendly,
  one pytree leaf per parameter kind instead of L dicts).
- The vocab pads with zero rows to a multiple of 128 for MXU tiling
  (models/gpt2.py GPTConfig.vocab_size comment).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from ray_tpu.models.gpt2 import GPTConfig


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def config_from_hf(hf_config, *, pad_vocab_to: int = 128,
                   **overrides) -> GPTConfig:
    """Map a transformers GPT2Config onto GPTConfig."""
    import jax.numpy as jnp

    kwargs: Dict[str, Any] = dict(
        vocab_size=_round_up(hf_config.vocab_size, pad_vocab_to),
        max_seq_len=hf_config.n_positions,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        embed_dim=hf_config.n_embd,
        dtype=jnp.bfloat16,
    )
    kwargs.update(overrides)
    return GPTConfig(**kwargs)


def params_from_hf(model, *, pad_vocab_to: int = 128,
                   **config_overrides) -> Tuple[Dict[str, Any], GPTConfig]:
    """(params, config) from a transformers GPT2LMHeadModel instance.

    Works on any loaded checkpoint (`GPT2LMHeadModel.from_pretrained` or
    a fresh config-built model); no network access here.
    """
    import jax.numpy as jnp

    config = config_from_hf(
        model.config, pad_vocab_to=pad_vocab_to, **config_overrides
    )
    sd = {
        k: v.detach().cpu().numpy() for k, v in model.state_dict().items()
    }
    L, E, H = config.num_layers, config.embed_dim, config.num_heads
    D = config.head_dim
    dt = config.param_dtype

    def stacked(key_fmt: str) -> np.ndarray:
        return np.stack(
            [sd[key_fmt.format(i=i)] for i in range(L)], axis=0
        )

    # qkv: (L, E, 3E) -> (L, E, 3, H, D) -> (L, E, 3H, D)
    c_attn_w = stacked("transformer.h.{i}.attn.c_attn.weight")
    qkv_kernel = c_attn_w.reshape(L, E, 3, H, D).reshape(L, E, 3 * H, D)
    c_attn_b = stacked("transformer.h.{i}.attn.c_attn.bias")
    qkv_bias = c_attn_b.reshape(L, 3, H, D).reshape(L, 3 * H, D)
    # attn out proj: (L, E, E) -> (L, H, D, E)
    proj_kernel = stacked("transformer.h.{i}.attn.c_proj.weight").reshape(
        L, H, D, E
    )

    wte = sd["transformer.wte.weight"]
    if config.vocab_size > wte.shape[0]:
        pad = np.zeros(
            (config.vocab_size - wte.shape[0], E), wte.dtype
        )
        wte = np.concatenate([wte, pad], axis=0)

    j = lambda a: jnp.asarray(a, dt)  # noqa: E731
    params = {
        "wte": j(wte),
        "wpe": j(sd["transformer.wpe.weight"]),
        "blocks": {
            "ln1_scale": j(stacked("transformer.h.{i}.ln_1.weight")),
            "ln1_bias": j(stacked("transformer.h.{i}.ln_1.bias")),
            "qkv_kernel": j(qkv_kernel),
            "qkv_bias": j(qkv_bias),
            "proj_kernel": j(proj_kernel),
            "proj_bias": j(stacked("transformer.h.{i}.attn.c_proj.bias")),
            "ln2_scale": j(stacked("transformer.h.{i}.ln_2.weight")),
            "ln2_bias": j(stacked("transformer.h.{i}.ln_2.bias")),
            "fc_kernel": j(stacked("transformer.h.{i}.mlp.c_fc.weight")),
            "fc_bias": j(stacked("transformer.h.{i}.mlp.c_fc.bias")),
            "out_kernel": j(stacked("transformer.h.{i}.mlp.c_proj.weight")),
            "out_bias": j(stacked("transformer.h.{i}.mlp.c_proj.bias")),
        },
        "lnf_scale": j(sd["transformer.ln_f.weight"]),
        "lnf_bias": j(sd["transformer.ln_f.bias"]),
    }
    return params, config
