"""HuggingFace Transformers interop for the flagship GPT-2.

Role-equivalent of ray: python/ray/train/huggingface/ (Transformers
integration) — here the useful TPU form: convert a `transformers`
GPT2LMHeadModel's torch weights into this repo's stacked-layer jax
params (models/gpt2.py layout) so pretrained checkpoints train/serve on
the TPU stack.  The reverse of a "wrapper": weights move into the
TPU-native model rather than wrapping torch in actors.

Layout notes:
- HF Conv1D stores (in, out); our einsum kernels are (in, ...) too, so
  no transposes except the qkv head split.
- HF c_attn is (E, 3E) = [q|k|v]; ours is (E, 3H, D) with q heads at
  [0:H], k at [H:2H], v at [2H:3H] (models/gpt2.py _block split).
- Per-layer tensors stack into a leading L axis (lax.scan-friendly,
  one pytree leaf per parameter kind instead of L dicts).
- The vocab pads with zero rows to a multiple of 128 for MXU tiling
  (models/gpt2.py GPTConfig.vocab_size comment).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from ray_tpu.models.gpt2 import GPTConfig


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def config_from_hf(hf_config, *, pad_vocab_to: int = 128,
                   **overrides) -> GPTConfig:
    """Map a transformers GPT2Config onto GPTConfig."""
    import jax.numpy as jnp

    kwargs: Dict[str, Any] = dict(
        vocab_size=_round_up(hf_config.vocab_size, pad_vocab_to),
        max_seq_len=hf_config.n_positions,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        embed_dim=hf_config.n_embd,
        dtype=jnp.bfloat16,
    )
    kwargs.update(overrides)
    return GPTConfig(**kwargs)


def params_from_hf(model, *, pad_vocab_to: int = 128,
                   **config_overrides) -> Tuple[Dict[str, Any], GPTConfig]:
    """(params, config) from a transformers GPT2LMHeadModel instance.

    Works on any loaded checkpoint (`GPT2LMHeadModel.from_pretrained` or
    a fresh config-built model); no network access here.
    """
    import jax.numpy as jnp

    config = config_from_hf(
        model.config, pad_vocab_to=pad_vocab_to, **config_overrides
    )
    sd = {
        k: v.detach().cpu().numpy() for k, v in model.state_dict().items()
    }
    L, E, H = config.num_layers, config.embed_dim, config.num_heads
    D = config.head_dim
    dt = config.param_dtype

    def stacked(key_fmt: str) -> np.ndarray:
        return np.stack(
            [sd[key_fmt.format(i=i)] for i in range(L)], axis=0
        )

    # qkv: (L, E, 3E) -> (L, E, 3, H, D) -> (L, E, 3H, D)
    c_attn_w = stacked("transformer.h.{i}.attn.c_attn.weight")
    qkv_kernel = c_attn_w.reshape(L, E, 3, H, D).reshape(L, E, 3 * H, D)
    c_attn_b = stacked("transformer.h.{i}.attn.c_attn.bias")
    qkv_bias = c_attn_b.reshape(L, 3, H, D).reshape(L, 3 * H, D)
    # attn out proj: (L, E, E) -> (L, H, D, E)
    proj_kernel = stacked("transformer.h.{i}.attn.c_proj.weight").reshape(
        L, H, D, E
    )

    wte = sd["transformer.wte.weight"]
    if config.vocab_size > wte.shape[0]:
        pad = np.zeros(
            (config.vocab_size - wte.shape[0], E), wte.dtype
        )
        wte = np.concatenate([wte, pad], axis=0)

    j = lambda a: jnp.asarray(a, dt)  # noqa: E731
    params = {
        "wte": j(wte),
        "wpe": j(sd["transformer.wpe.weight"]),
        "blocks": {
            "ln1_scale": j(stacked("transformer.h.{i}.ln_1.weight")),
            "ln1_bias": j(stacked("transformer.h.{i}.ln_1.bias")),
            "qkv_kernel": j(qkv_kernel),
            "qkv_bias": j(qkv_bias),
            "proj_kernel": j(proj_kernel),
            "proj_bias": j(stacked("transformer.h.{i}.attn.c_proj.bias")),
            "ln2_scale": j(stacked("transformer.h.{i}.ln_2.weight")),
            "ln2_bias": j(stacked("transformer.h.{i}.ln_2.bias")),
            "fc_kernel": j(stacked("transformer.h.{i}.mlp.c_fc.weight")),
            "fc_bias": j(stacked("transformer.h.{i}.mlp.c_fc.bias")),
            "out_kernel": j(stacked("transformer.h.{i}.mlp.c_proj.weight")),
            "out_bias": j(stacked("transformer.h.{i}.mlp.c_proj.bias")),
        },
        "lnf_scale": j(sd["transformer.ln_f.weight"]),
        "lnf_bias": j(sd["transformer.ln_f.bias"]),
    }
    return params, config


# ---------------------------------------------------------------------------
# Llama family (models/llama.py layout)
# ---------------------------------------------------------------------------


def llama_config_from_hf(hf_config, **overrides):
    """Map a transformers LlamaConfig onto LlamaConfig."""
    import jax.numpy as jnp

    from ray_tpu.models.llama import LlamaConfig

    kwargs: Dict[str, Any] = dict(
        vocab_size=hf_config.vocab_size,
        max_seq_len=hf_config.max_position_embeddings,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(
            hf_config, "num_key_value_heads", hf_config.num_attention_heads
        ),
        embed_dim=hf_config.hidden_size,
        mlp_dim=hf_config.intermediate_size,
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        rms_eps=hf_config.rms_norm_eps,
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
        dtype=jnp.bfloat16,
    )
    kwargs.update(overrides)
    return LlamaConfig(**kwargs)


def llama_params_from_hf(model, **config_overrides):
    """(params, config) from a transformers LlamaForCausalLM instance.

    HF Linear weights are (out, in); our einsum kernels are (in, ...) so
    every projection transposes, and q/k/o reshape their flat head dim
    into (heads, head_dim).  HF checkpoints already use the rotate-half
    RoPE convention this model implements, so no head permutation is
    needed.
    """
    import jax.numpy as jnp

    config = llama_config_from_hf(model.config, **config_overrides)
    sd = {
        k: v.detach().cpu().numpy() for k, v in model.state_dict().items()
    }
    L, E, H, KV, D = (
        config.num_layers, config.embed_dim, config.num_heads,
        config.num_kv_heads, config.head_dim,
    )
    dt = config.param_dtype

    def stacked(fmt: str) -> np.ndarray:
        return np.stack([sd[fmt.format(i=i)] for i in range(L)], axis=0)

    j = lambda a: jnp.asarray(a, dt)  # noqa: E731
    wq = stacked("model.layers.{i}.self_attn.q_proj.weight")  # (L, H*D, E)
    wk = stacked("model.layers.{i}.self_attn.k_proj.weight")
    wv = stacked("model.layers.{i}.self_attn.v_proj.weight")
    wo = stacked("model.layers.{i}.self_attn.o_proj.weight")  # (L, E, H*D)
    params = {
        "tok_embed": j(sd["model.embed_tokens.weight"]),
        "blocks": {
            "attn_norm": j(
                stacked("model.layers.{i}.input_layernorm.weight")
            ),
            "wq": j(wq.transpose(0, 2, 1).reshape(L, E, H, D)),
            "wk": j(wk.transpose(0, 2, 1).reshape(L, E, KV, D)),
            "wv": j(wv.transpose(0, 2, 1).reshape(L, E, KV, D)),
            "wo": j(wo.transpose(0, 2, 1).reshape(L, H, D, E)),
            "mlp_norm": j(
                stacked("model.layers.{i}.post_attention_layernorm.weight")
            ),
            "w_gate": j(
                stacked("model.layers.{i}.mlp.gate_proj.weight")
                .transpose(0, 2, 1)
            ),
            "w_up": j(
                stacked("model.layers.{i}.mlp.up_proj.weight")
                .transpose(0, 2, 1)
            ),
            "w_down": j(
                stacked("model.layers.{i}.mlp.down_proj.weight")
                .transpose(0, 2, 1)
            ),
        },
        "final_norm": j(sd["model.norm.weight"]),
    }
    if not config.tie_embeddings:
        params["lm_head"] = j(sd["lm_head.weight"])
    return params, config
