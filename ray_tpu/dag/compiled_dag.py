"""Compiled DAGs: static actor-method graphs on reusable shm channels.

Role-equivalent of ray: python/ray/dag/compiled_dag_node.py:186
(CompiledDAG) + dag_node binding surface.  `method.bind(...)` builds a
lazy node graph; `experimental_compile()` allocates one mutable shm
channel per edge (ray_tpu/dag/channel.py) and parks a persistent exec
loop on every participating actor.  `execute()` then moves data purely
through channels — no per-call task submission, no GCS, no RPC — which
is what makes pipeline-shaped execution (capability 8 of SURVEY §2.4)
cheap enough to matter.

TPU-first notes:
- Channels are host-local (/dev/shm).  Cross-host pipeline parallelism
  on TPU rides ICI *inside* compiled XLA programs (collective_permute;
  ray_tpu/parallel/), so the reference's NCCL channel variant has no
  analogue here by design.
- Depth-1 SPSC channels give natural backpressure: `execute()` blocks
  on the input channel while every stage is busy, so a pipeline of K
  stages holds at most K items in flight — the reference bounds this
  with `_max_buffered_results` instead.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.common import serialization
from ray_tpu.dag.channel import (
    Channel,
    ChannelClosedError,
    ChannelTimeoutError,
    make_channel_name,
)

_DEFAULT_BUFFER = 4 * 1024 * 1024

_VAL = b"V"
_ERR = b"E"


class DAGExecutionError(RuntimeError):
    pass


def _pack(kind: bytes, obj: Any) -> bytes:
    return kind + serialization.serialize(obj).to_bytes()


def _unpack(data: bytes) -> Tuple[bytes, Any]:
    return data[:1], serialization.deserialize(memoryview(data)[1:])


# ---------------------------------------------------------------------------
# Node graph (lazy binding surface)
# ---------------------------------------------------------------------------


class DAGNode:
    def experimental_compile(
        self, buffer_size_bytes: int = _DEFAULT_BUFFER
    ) -> "CompiledDAG":
        return CompiledDAG(self, buffer_size_bytes)


class InputNode(DAGNode):
    """The driver-fed entry point; use as a context manager like the
    reference (`with InputNode() as inp: ...`)."""

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        return None


class ClassMethodNode(DAGNode):
    def __init__(self, actor_handle, method_name: str, args: tuple):
        self.actor = actor_handle
        self.method_name = method_name
        self.args = args

    def __repr__(self):
        return f"ClassMethodNode({self.method_name})"


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        self.outputs = list(outputs)


# ---------------------------------------------------------------------------
# Actor-side exec loop (runs via the __rt_apply__ dispatch)
# ---------------------------------------------------------------------------


def _actor_exec_loop(instance, stages: List[dict], capacity: int,
                     ready_name: str):
    """Run this actor's DAG stages forever until a channel closes.

    `stages` (in topological order) each carry:
      method:  method name on the actor instance
      inputs:  list of ("chan", name) | ("const", serialized bytes)
      outputs: list of channel names (one per consumer edge + driver edge)
    """
    chans: Dict[str, Channel] = {}

    def chan(name: str) -> Channel:
        c = chans.get(name)
        if c is None:
            c = chans[name] = Channel(name, capacity)
        return c

    consts: Dict[int, list] = {}
    for si, st in enumerate(stages):
        consts[si] = [
            serialization.deserialize(v) if kind == "const" else None
            for kind, v in st["inputs"]
        ]
    try:
        # readiness barrier: the driver's compile() blocks until every
        # loop has signalled, so execute()/get() timeouts never race a
        # cold actor start (worker spawn + preloaded-jax import can take
        # a minute on a loaded host).
        Channel(ready_name, 8).write(b"R")
        while True:
            # read-per-stage in topo order: an actor hosting a->b chains
            # consumes a's output through a local channel like any other
            # edge, keeping one code path (the reference specializes this).
            for si, st in enumerate(stages):
                args, err = [], None
                for ai, (kind, v) in enumerate(st["inputs"]):
                    if kind == "const":
                        args.append(consts[si][ai])
                    else:
                        k, obj = _unpack(chan(v).read())
                        if k == _ERR:
                            err = obj
                        args.append(obj)
                if err is None:
                    try:
                        out = _pack(
                            _VAL, getattr(instance, st["method"])(*args)
                        )
                    except Exception as e:  # noqa: BLE001 - forwarded
                        out = _pack(_ERR, e)
                else:
                    out = _pack(_ERR, err)
                for name in st["outputs"]:
                    chan(name).write(out)
    except ChannelClosedError:
        pass
    finally:
        for c in chans.values():
            c.close()
            c.detach()
    return "dag-loop-done"


# ---------------------------------------------------------------------------
# Compiler + driver-side execution
# ---------------------------------------------------------------------------


class CompiledDAGRef:
    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: Optional[float] = 120.0):
        return self._dag._get(self._seq, timeout)


class CompiledDAG:
    def __init__(self, root: DAGNode, buffer_size_bytes: int):
        self._capacity = int(buffer_size_bytes)
        # separate locks so an execute() blocked on a full pipeline never
        # prevents another thread's get() from draining the outputs
        self._exec_lock = threading.Lock()
        self._read_lock = threading.Lock()
        self._next_seq = 0
        self._results: Dict[int, Any] = {}
        self._next_read_seq = 0
        # outputs already drained for the iteration currently being read;
        # survives a ChannelTimeoutError so a retried get() resumes at the
        # first unread channel instead of re-reading channel 0 (which would
        # pair outputs from different iterations)
        self._partial_reads: List[Any] = []
        self._torn_down = False
        self._loop_refs: list = []
        self._compile(root)

    # -- graph analysis ----------------------------------------------

    def _compile(self, root: DAGNode) -> None:
        outputs = (
            root.outputs if isinstance(root, MultiOutputNode) else [root]
        )
        for o in outputs:
            if not isinstance(o, ClassMethodNode):
                raise TypeError(
                    "DAG outputs must be actor-method nodes, got "
                    f"{type(o).__name__}"
                )
        # topo-sort ClassMethodNodes reachable from the outputs
        order: List[ClassMethodNode] = []
        state: Dict[int, int] = {}  # id -> 0 visiting / 1 done
        self._input_node: Optional[InputNode] = None

        def visit(n: DAGNode):
            if isinstance(n, InputNode):
                if self._input_node is not None and self._input_node is not n:
                    raise ValueError("a DAG may have only one InputNode")
                self._input_node = n
                return
            if not isinstance(n, ClassMethodNode):
                return
            s = state.get(id(n))
            if s == 1:
                return
            if s == 0:
                raise ValueError("cycle detected in DAG")
            state[id(n)] = 0
            for a in n.args:
                visit(a)
            state[id(n)] = 1
            order.append(n)

        for o in outputs:
            visit(o)
        if self._input_node is None:
            raise ValueError(
                "DAG has no InputNode; bind at least one argument to it"
            )

        # one channel per (producer -> consumer-arg) edge
        self._input_channels: List[Channel] = []
        out_names: Dict[int, List[str]] = {id(n): [] for n in order}
        node_inputs: Dict[int, list] = {}
        for n in order:
            ins = []
            for a in n.args:
                if isinstance(a, InputNode):
                    name = make_channel_name()
                    self._input_channels.append(
                        Channel(name, self._capacity, create=True)
                    )
                    ins.append(("chan", name))
                elif isinstance(a, ClassMethodNode):
                    name = make_channel_name()
                    Channel(name, self._capacity, create=True).detach()
                    out_names[id(a)].append(name)
                    ins.append(("chan", name))
                else:
                    ins.append(
                        ("const", serialization.serialize(a).to_bytes())
                    )
            node_inputs[id(n)] = ins
        self._output_channels: List[Channel] = []
        for o in outputs:
            name = make_channel_name()
            self._output_channels.append(
                Channel(name, self._capacity, create=True)
            )
            out_names[id(o)].append(name)

        # group stages by actor, preserving topo order within each
        per_actor: Dict[Any, List[dict]] = {}
        self._actors = []
        for n in order:
            key = n.actor._actor_id
            if key not in per_actor:
                per_actor[key] = []
                self._actors.append(n.actor)
            per_actor[key].append(
                {
                    "method": n.method_name,
                    "inputs": node_inputs[id(n)],
                    "outputs": out_names[id(n)],
                }
            )
        self._all_channel_names = (
            [c.name for c in self._input_channels]
            + [c.name for c in self._output_channels]
            + [
                name
                for n in order
                for name in out_names[id(n)]
                if name not in {c.name for c in self._output_channels}
            ]
        )
        # park the exec loops (one long-running actor task per actor) and
        # wait for each to signal readiness through a one-shot channel
        ready_channels = []
        for actor in self._actors:
            stages = per_actor[actor._actor_id]
            ready_name = make_channel_name()
            ready_channels.append(Channel(ready_name, 8, create=True))
            ref = actor._apply(
                _actor_exec_loop, stages, self._capacity, ready_name
            )
            self._loop_refs.append(ref)
        for rc in ready_channels:
            rc.read(timeout=300.0, liveness=self._check_loops_alive)
            rc.unlink()

    # -- execution ----------------------------------------------------

    def _check_loops_alive(self) -> None:
        import ray_tpu

        done, _ = ray_tpu.wait(
            list(self._loop_refs), num_returns=len(self._loop_refs), timeout=0
        )
        for ref in done:
            # a finished loop before teardown means the actor died or the
            # loop crashed; surface it instead of spinning on the channel
            ray_tpu.get(ref)
            raise DAGExecutionError(
                "a DAG exec loop exited while the DAG was still active"
            )

    def execute(self, *args) -> CompiledDAGRef:
        if self._torn_down:
            raise DAGExecutionError("DAG has been torn down")
        if len(args) != 1:
            raise TypeError(
                "compiled DAG execute() takes exactly one input (the "
                "InputNode value)"
            )
        data = _pack(_VAL, args[0])
        with self._exec_lock:
            # two-phase publish: wait for EVERY input channel to drain,
            # then write them all — the writes cannot block (driver is
            # the sole writer), so a pipeline-full timeout raises with no
            # partial publish to desync stage iteration counts.
            try:
                for c in self._input_channels:
                    c.wait_empty(timeout=120.0,
                                 liveness=self._check_loops_alive)
            except ChannelTimeoutError as e:
                raise DAGExecutionError(
                    "pipeline is full and not draining — call .get() on "
                    "outstanding CompiledDAGRefs to free a slot"
                ) from e
            for c in self._input_channels:
                c.write(data)
            seq = self._next_seq
            self._next_seq += 1
        return CompiledDAGRef(self, seq)

    def _get(self, seq: int, timeout: Optional[float]):
        with self._read_lock:
            while seq not in self._results:
                if self._next_read_seq > seq:
                    # delivered and consumed: DAG results are single-use
                    # (matching the reference's one-get aDAG refs)
                    raise ValueError(
                        f"result for execution #{seq} was already consumed"
                    )
                vals = self._partial_reads
                for c in self._output_channels[len(vals):]:
                    vals.append(_unpack(
                        c.read(timeout=timeout,
                               liveness=self._check_loops_alive)
                    ))
                self._partial_reads = []
                err = None
                for k, obj in vals:
                    if k == _ERR and err is None:
                        err = obj
                vals = [obj for _, obj in vals]
                if err is not None:
                    self._results[self._next_read_seq] = ("err", err)
                else:
                    self._results[self._next_read_seq] = (
                        "val",
                        vals if len(vals) > 1 else vals[0],
                    )
                self._next_read_seq += 1
            kind, payload = self._results.pop(seq)
        if kind == "err":
            raise payload
        return payload

    # -- lifecycle ----------------------------------------------------

    def teardown(self, timeout: float = 30.0) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        import ray_tpu

        for c in self._input_channels + self._output_channels:
            c.close()
        # loops drain remaining work, hit CLOSED, and return
        try:
            ray_tpu.wait(
                list(self._loop_refs),
                num_returns=len(self._loop_refs),
                timeout=timeout,
            )
        except Exception:
            pass
        for c in self._input_channels + self._output_channels:
            c.unlink()
        import os

        for name in self._all_channel_names:
            try:
                os.unlink(os.path.join("/dev/shm", name))
            except FileNotFoundError:
                pass

    def __del__(self):
        try:
            if not self._torn_down:
                self.teardown(timeout=1.0)
        except Exception:
            pass
