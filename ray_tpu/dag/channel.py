"""Mutable shared-memory channels for compiled DAG execution.

Role-equivalent of the reference's experimental mutable-object channels
(ray: src/ray/core_worker/experimental_mutable_object_manager.cc,
python/ray/experimental/channel/shared_memory_channel.py): a reusable
fixed-capacity shm segment written and read in place every DAG
iteration, skipping the per-call task-submission path entirely.

Design differences from the reference (TPU-first, daemon-less):
- A channel is a plain file in ``/dev/shm`` mmapped by both ends — no
  raylet involvement, matching this repo's daemon-less shm arena design
  (`ray_tpu/_native/shm_store.cc`).
- Single-producer / single-consumer with a seqlock-style header; fan-out
  is expressed as one channel per consumer edge (the compiler allocates
  them), mirroring how the reference registers one reader ref per
  downstream actor.
- Cross-host pipelining is deliberately NOT done through channels: on
  TPU, cross-host pipeline parallelism belongs *inside* the XLA program
  (collective-permute over ICI; see ray_tpu/parallel/), so channels are
  host-local by design.

Wire format per slot::

    header (32 B): u32 state | u32 pad | u64 length | u64 seq | u64 cap
    payload (cap B)

state transitions: EMPTY -w-> FULL -r-> EMPTY; either side -> CLOSED.
"""

from __future__ import annotations

import mmap
import os
import struct
import time
import uuid
from typing import Optional

_HDR = struct.Struct("<IIQQQ")
HEADER_BYTES = _HDR.size  # 32

EMPTY, FULL, CLOSED = 0, 1, 2

_SHM_DIR = "/dev/shm"


class ChannelClosedError(RuntimeError):
    """The peer closed the channel (DAG teardown or actor death)."""


class ChannelTimeoutError(TimeoutError):
    pass


def _poll_sleep(i: int) -> None:
    # spin briefly, then back off to bounded sleeps: DAG iterations are
    # sub-millisecond when hot, but a blocked pipeline should not burn a
    # core indefinitely.
    if i < 200:
        time.sleep(0)
    elif i < 2000:
        time.sleep(50e-6)
    else:
        time.sleep(1e-3)


class Channel:
    """One SPSC mutable channel. Create once (driver), open anywhere."""

    def __init__(self, name: str, capacity: int, create: bool = False):
        self.name = name
        self.capacity = capacity
        self.path = os.path.join(_SHM_DIR, name)
        total = HEADER_BYTES + capacity
        if create:
            fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
            try:
                os.ftruncate(fd, total)
                self._mm = mmap.mmap(fd, total)
            finally:
                os.close(fd)
            _HDR.pack_into(self._mm, 0, EMPTY, 0, 0, 0, capacity)
        else:
            fd = os.open(self.path, os.O_RDWR)
            try:
                self._mm = mmap.mmap(fd, total)
            finally:
                os.close(fd)

    # -- header access ------------------------------------------------

    def _state(self) -> int:
        return _HDR.unpack_from(self._mm, 0)[0]

    def _set_state(self, s: int) -> None:
        struct.pack_into("<I", self._mm, 0, s)

    # -- data path ----------------------------------------------------

    def write(self, data: bytes, timeout: Optional[float] = None,
              liveness=None) -> None:
        """Block until the slot is EMPTY, then publish `data`.

        `liveness`, if given, is called periodically while blocked and may
        raise (used by the driver to surface a dead exec loop instead of
        hanging forever on a channel nobody will drain).
        """
        n = len(data)
        if n > self.capacity:
            raise ValueError(
                f"value of {n} bytes exceeds channel capacity "
                f"{self.capacity}; recompile the DAG with a larger "
                f"buffer_size_bytes"
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        i = 0
        while True:
            st = self._state()
            if st == CLOSED:
                raise ChannelClosedError(f"channel {self.name} is closed")
            if st == EMPTY:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"timed out writing channel {self.name}"
                )
            if liveness is not None and i and i % 4000 == 0:
                liveness()
            _poll_sleep(i)
            i += 1
        self._mm[HEADER_BYTES:HEADER_BYTES + n] = data
        _, _, _, seq, cap = _HDR.unpack_from(self._mm, 0)
        _HDR.pack_into(self._mm, 0, EMPTY, 0, n, seq + 1, cap)
        # state flips last: payload+length are in place before FULL is
        # visible (x86/ARM store ordering through a single mmap is enough
        # for this SPSC handoff under the GIL's sequential execution).
        self._set_state(FULL)

    def wait_empty(self, timeout: Optional[float] = None,
                   liveness=None) -> None:
        """Block until the slot is EMPTY.  Used by the driver to make a
        multi-channel publish atomic: once every input channel of a DAG
        is EMPTY, the subsequent writes cannot block (the driver is the
        only writer), so a timeout can never leave a partial publish."""
        deadline = None if timeout is None else time.monotonic() + timeout
        i = 0
        while True:
            st = self._state()
            if st == EMPTY:
                return
            if st == CLOSED:
                raise ChannelClosedError(f"channel {self.name} is closed")
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"timed out waiting for channel {self.name} to drain"
                )
            if liveness is not None and i and i % 4000 == 0:
                liveness()
            _poll_sleep(i)
            i += 1

    def read(self, timeout: Optional[float] = None, liveness=None) -> bytes:
        deadline = None if timeout is None else time.monotonic() + timeout
        i = 0
        while True:
            st = self._state()
            if st == FULL:
                break
            if st == CLOSED:
                raise ChannelClosedError(f"channel {self.name} is closed")
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"timed out reading channel {self.name}"
                )
            if liveness is not None and i and i % 4000 == 0:
                liveness()
            _poll_sleep(i)
            i += 1
        length = _HDR.unpack_from(self._mm, 0)[2]
        data = bytes(self._mm[HEADER_BYTES:HEADER_BYTES + length])
        self._set_state(EMPTY)
        return data

    # -- lifecycle ----------------------------------------------------

    def close(self) -> None:
        try:
            self._set_state(CLOSED)
        except ValueError:  # mmap already closed
            pass

    def detach(self) -> None:
        try:
            self._mm.close()
        except Exception:
            pass

    def unlink(self) -> None:
        self.detach()
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


def make_channel_name() -> str:
    return f"rtdag-{uuid.uuid4().hex[:16]}"
