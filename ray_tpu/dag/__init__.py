"""Compiled DAGs on mutable shm channels (ray: python/ray/dag/ +
src/ray/core_worker/experimental_mutable_object_manager.cc)."""

from ray_tpu.dag.channel import (  # noqa: F401
    Channel,
    ChannelClosedError,
    ChannelTimeoutError,
)
from ray_tpu.dag.compiled_dag import (  # noqa: F401
    CompiledDAG,
    CompiledDAGRef,
    DAGExecutionError,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "Channel",
    "ChannelClosedError",
    "ChannelTimeoutError",
    "CompiledDAG",
    "CompiledDAGRef",
    "DAGExecutionError",
    "InputNode",
    "MultiOutputNode",
]
