"""CLI: cluster lifecycle + state inspection.

Role-equivalent of ray: python/ray/scripts/scripts.py:568 (`ray start`,
`ray stop`, `ray status`) and the `ray list ...` state commands —
argparse instead of click (no extra deps) and a session file under
/tmp/ray_tpu instead of a process table.

    python -m ray_tpu start --head [--num-cpus N] [--num-tpus N]
    python -m ray_tpu start --address HOST:PORT   # join as a worker node
    python -m ray_tpu stop
    python -m ray_tpu status [--address HOST:PORT]
    python -m ray_tpu list actors|nodes|tasks|objects|workers|pgs
    python -m ray_tpu metrics
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

_SESSION_FILE = "/tmp/ray_tpu/latest_cli_session.json"


def _save_session(info: dict) -> None:
    os.makedirs(os.path.dirname(_SESSION_FILE), exist_ok=True)
    with open(_SESSION_FILE, "w") as f:
        json.dump(info, f)


def _load_session() -> Optional[dict]:
    try:
        with open(_SESSION_FILE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _resolve_address(args) -> str:
    if getattr(args, "address", None):
        return args.address
    env = os.environ.get("RT_ADDRESS")
    if env:
        return env
    sess = _load_session()
    if sess:
        return sess["gcs_address"]
    sys.exit(
        "no cluster address: pass --address, set RT_ADDRESS, or start one "
        "with `python -m ray_tpu start --head`"
    )


def cmd_start(args) -> None:
    from ray_tpu.core import node as node_mod

    session_dir = node_mod.default_session_dir()
    if args.head:
        gcs_proc, gcs_address = node_mod.start_gcs(session_dir)
    else:
        if not args.address:
            sys.exit("--address required to join an existing cluster")
        gcs_proc, gcs_address = None, args.address
    resources = node_mod.detect_resources(
        num_cpus=args.num_cpus, num_tpus=args.num_tpus
    )
    raylet_proc, raylet_addr, node_id, _store = node_mod.start_raylet(
        gcs_address, session_dir, resources
    )
    prev = _load_session() or {}
    _save_session({
        "gcs_address": gcs_address,
        "session_dir": session_dir,
        # a joining worker node must not clobber the recorded head pid
        "gcs_pid": gcs_proc.pid if gcs_proc else prev.get("gcs_pid"),
        "raylet_pids": prev.get("raylet_pids", []) + [raylet_proc.pid],
    })
    print(f"ray_tpu {'head' if args.head else 'worker node'} started")
    print(f"  GCS address: {gcs_address}")
    print(f"  node id:     {node_id}")
    print(f"  session dir: {session_dir}")
    print(f"connect with ray_tpu.init(address={gcs_address!r})")


def cmd_stop(args) -> None:
    import signal

    sess = _load_session()
    if not sess:
        sys.exit("no recorded CLI session")
    killed = 0
    for pid in sess.get("raylet_pids", []) + (
        [sess["gcs_pid"]] if sess.get("gcs_pid") else []
    ):
        try:
            os.kill(pid, signal.SIGTERM)
            killed += 1
        except ProcessLookupError:
            pass
    os.unlink(_SESSION_FILE)
    print(f"stopped {killed} control-plane processes")


def cmd_up(args) -> None:
    from ray_tpu.autoscaler import launcher

    state = launcher.up(args.config, wait_min_workers_s=args.wait)
    print(f"cluster {state['cluster_name']!r} is up")
    print(f"  GCS address: {state['gcs_address']}")
    print(f"  session dir: {state['session_dir']}")
    print(f"  monitor pid: {state['monitor_pid']}")
    print(
        f"connect with ray_tpu.init(address={state['gcs_address']!r}); "
        f"tear down with `ray_tpu down {args.config}`"
    )


def cmd_down(args) -> None:
    from ray_tpu.autoscaler import launcher

    stats = launcher.down(args.config)
    print(
        f"cluster down: {stats['provider_nodes']} provider nodes removed, "
        f"{stats['processes']} control-plane processes stopped"
    )


def _connect(args):
    import ray_tpu

    ray_tpu.init(address=_resolve_address(args))


def cmd_status(args) -> None:
    from ray_tpu.util import state

    _connect(args)
    s = state.summarize()
    print("======== cluster status ========")
    print(f"nodes:  {s['nodes_alive']}/{s['nodes_total']} alive")
    print(f"actors: {s['actors_alive']}/{s['actors_total']} alive")
    print("resources:")
    total, avail = s["resources_total"], s["resources_available"]
    for k in sorted(total):
        used = total[k] - avail.get(k, 0)
        print(f"  {used:g}/{total[k]:g} {k}")
    if s["pending_leases"] or s["pending_pg_bundles"]:
        print(
            f"pending demand: {s['pending_leases']} leases, "
            f"{s['pending_pg_bundles']} PG bundles"
        )


def cmd_list(args) -> None:
    from ray_tpu.util import state

    _connect(args)
    fn = {
        "actors": state.list_actors,
        "nodes": state.list_nodes,
        "tasks": state.list_tasks,
        "objects": state.list_objects,
        "workers": state.list_workers,
        "pgs": state.list_placement_groups,
    }[args.what]
    rows = fn()
    print(json.dumps(rows, indent=2, default=str))


def cmd_metrics(args) -> None:
    from ray_tpu.util import state

    _connect(args)
    print(json.dumps(state.get_metrics(), indent=2))


def cmd_stacks(args) -> None:
    """Per-thread Python stacks of a live worker (py-spy role)."""
    from ray_tpu.util import state

    _connect(args)
    if args.worker:
        dump = state.worker_stacks(args.worker)
        print(f"pid {dump['pid']}")
        for name, stack in dump["stacks"].items():
            print(f"\n--- {name} ---\n{stack}")
    else:
        for w in state.list_workers():
            print(json.dumps(
                {k: w.get(k) for k in ("worker_id", "pid", "actor_class")}
            ))


def cmd_memory(args) -> None:
    from ray_tpu.util import state

    _connect(args)
    print(json.dumps(state.memory_summary(), indent=2, default=str))


def cmd_events(args) -> None:
    from ray_tpu.util import events

    _connect(args)
    print(json.dumps(
        events.list_events(severity=args.severity), indent=2, default=str
    ))


def cmd_timeline(args) -> None:
    import ray_tpu

    _connect(args)
    trace = ray_tpu.timeline()
    if args.output:
        with open(args.output, "w") as f:
            json.dump(trace, f)
        print(f"wrote {len(trace)} events to {args.output}")
    else:
        print(json.dumps(trace, default=str))


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="ray_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a head or worker node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", help="GCS address to join (worker node)")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop CLI-started nodes")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser(
        "up", help="provision a cluster from cluster.yaml (head + "
                   "autoscaler monitor + min_workers)",
    )
    p.add_argument("config", help="path to cluster.yaml")
    p.add_argument(
        "--wait", type=float, default=0.0,
        help="block until min_workers are up (seconds)",
    )
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser(
        "down", help="tear a cluster down (provider nodes, monitor, head)"
    )
    p.add_argument("config", help="path to cluster.yaml")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser("status", help="cluster summary")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("memory", help="per-node object store usage")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("events", help="structured cluster events")
    p.add_argument("--severity", default=None)
    p.add_argument("--address")
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser("timeline", help="chrome-trace timeline export")
    p.add_argument("--output", "-o", default=None)
    p.add_argument("--address")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("list", help="list cluster entities")
    p.add_argument(
        "what",
        choices=["actors", "nodes", "tasks", "objects", "workers", "pgs"],
    )
    p.add_argument("--address")
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("metrics", help="aggregated application metrics")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser(
        "stacks",
        help="dump a live worker's thread stacks (no arg: list workers)",
    )
    p.add_argument("worker", nargs="?", help="worker id (hex)")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_stacks)

    args = ap.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
