"""Python client for the native shared-memory object store.

ctypes bindings over ``shm_store.cc`` (the plasma-equivalent; see that file's
header comment).  The C library owns allocation and the object index; the
data plane is a plain ``mmap`` of the same arena file, giving zero-copy
``memoryview`` reads of sealed objects (ray: plasma client.cc mmap-and-read
analogue, minus the socket protocol).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import subprocess
import threading
from typing import Optional

from ray_tpu.common import faults

_SRC = os.path.join(os.path.dirname(__file__), "shm_store.cc")
_SO = os.path.join(os.path.dirname(__file__), "libshm_store.so")

RT_OK = 0
RT_EXISTS = -1
RT_NOT_FOUND = -2
RT_NO_SPACE = -3
RT_ERR = -4
RT_NOT_SEALED = -5
RT_PINNED = -6
RT_TOO_MANY_PINS = -7
RT_NO_CLIENT_SLOT = -8

_RC_NAMES = {
    RT_OK: "OK",
    RT_EXISTS: "EXISTS",
    RT_NOT_FOUND: "NOT_FOUND",
    RT_NO_SPACE: "NO_SPACE",
    RT_ERR: "ERR",
    RT_NOT_SEALED: "NOT_SEALED",
    RT_PINNED: "PINNED",
    RT_TOO_MANY_PINS: "TOO_MANY_PINS",
    RT_NO_CLIENT_SLOT: "NO_CLIENT_SLOT",
}


def _rc_name(rc: int) -> str:
    return _RC_NAMES.get(rc, str(rc))


class StoreError(Exception):
    pass


class ObjectExistsError(StoreError):
    pass


class ObjectNotFoundError(StoreError):
    pass


class StoreFullError(StoreError):
    pass


def _build_library(force: bool = False) -> None:
    """Compile the .so if missing or older than the source (flock-guarded so
    concurrent workers don't race).  ``force`` rebuilds even when the
    binary looks fresh — used when dlopen rejects a prebuilt .so from a
    different toolchain (e.g. a newer-glibc build shipped into an older
    container)."""
    def _stat_sig():
        try:
            st = os.stat(_SO)
            return (st.st_mtime_ns, st.st_size, st.st_ino)
        except OSError:
            return None

    def fresh():
        return (
            not force
            and os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
        )

    if fresh():
        return
    pre_lock_sig = _stat_sig()
    lock_path = _SO + ".lock"
    with open(lock_path, "w") as lf:
        import fcntl

        fcntl.flock(lf, fcntl.LOCK_EX)
        if fresh():
            return
        if force and _stat_sig() != pre_lock_sig:
            # a peer that held the flock first already replaced the
            # binary — N workers failing dlopen together must not each
            # run a full recompile back-to-back
            return
        tmp = _SO + ".tmp"
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread",
             _SRC, "-o", tmp],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, _SO)


_lib = None
_lib_lock = threading.Lock()


def _get_lib():
    global _lib
    if _lib is None:
        with _lib_lock:
            if _lib is None:
                _build_library()
                try:
                    lib = ctypes.CDLL(_SO)
                except OSError:
                    # prebuilt binary from an incompatible toolchain
                    # (GLIBC version mismatch): rebuild from the bundled
                    # source with the local compiler and retry
                    _build_library(force=True)
                    lib = ctypes.CDLL(_SO)
                lib.rt_store_create.restype = ctypes.c_void_p
                lib.rt_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
                lib.rt_store_attach.restype = ctypes.c_void_p
                lib.rt_store_attach.argtypes = [ctypes.c_char_p]
                lib.rt_store_detach.argtypes = [ctypes.c_void_p]
                lib.rt_store_create_object.argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                    ctypes.POINTER(ctypes.c_uint64),
                ]
                lib.rt_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
                lib.rt_store_seal2.argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                ]
                lib.rt_store_reserve_slots.restype = ctypes.c_uint64
                lib.rt_store_reserve_slots.argtypes = [
                    ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
                    ctypes.POINTER(ctypes.c_uint64),
                ]
                lib.rt_store_release_slots.argtypes = [
                    ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
                    ctypes.c_uint64,
                ]
                lib.rt_store_publish_slot.argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                    ctypes.c_uint64, ctypes.c_int,
                ]
                lib.rt_store_max_slab_slots.restype = ctypes.c_uint64
                lib.rt_store_max_slab_slots.argtypes = []
                lib.rt_store_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
                lib.rt_store_get.argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p,
                    ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
                ]
                lib.rt_store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
                lib.rt_store_unpin.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
                lib.rt_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
                lib.rt_store_stats.argtypes = [ctypes.c_void_p] + [
                    ctypes.POINTER(ctypes.c_uint64)
                ] * 4
                lib.rt_store_protect.argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                ]
                lib.rt_store_list_spillable.restype = ctypes.c_uint64
                lib.rt_store_list_spillable.argtypes = [
                    ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
                    ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
                ]
                lib.rt_store_base.restype = ctypes.c_void_p
                lib.rt_store_base.argtypes = [ctypes.c_void_p]
                lib.rt_store_map_size.restype = ctypes.c_uint64
                lib.rt_store_map_size.argtypes = [ctypes.c_void_p]
                lib.rt_store_reap.argtypes = [ctypes.c_void_p]
                lib.rt_store_min_size.restype = ctypes.c_uint64
                lib.rt_store_min_size.argtypes = []
                lib.rt_store_max_pins.restype = ctypes.c_uint64
                lib.rt_store_max_pins.argtypes = []
                _lib = lib
    return _lib


def _check_id(object_id: bytes) -> bytes:
    """The C side reads exactly 16 bytes; anything else is an OOB read."""
    if not isinstance(object_id, (bytes, bytearray)) or len(object_id) != 16:
        raise ValueError(
            f"object id must be exactly 16 bytes, got "
            f"{type(object_id).__name__} of length "
            f"{len(object_id) if hasattr(object_id, '__len__') else '?'}"
        )
    return bytes(object_id)


class PinnedBuffer:
    """Zero-copy view of a sealed object; unpins on release/del."""

    __slots__ = ("store", "object_id", "view", "_released")

    def __init__(self, store: "ShmStore", object_id: bytes, view: memoryview):
        self.store = store
        self.object_id = object_id
        self.view = view
        self._released = False

    def release(self):
        if not self._released:
            self._released = True
            self.view.release()
            self.store._unpin(self.object_id)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass


class ShmStore:
    """One node's shared-memory object store (create or attach)."""

    def __init__(self, path: str, capacity_bytes: int = 0, create: bool = False):
        self.path = path
        self._lib = _get_lib()
        if create:
            min_size = self._lib.rt_store_min_size()
            if capacity_bytes < min_size:
                raise StoreError(
                    f"store capacity {capacity_bytes} below minimum {min_size} "
                    "(metadata + 16MB data floor)"
                )
            self._h = self._lib.rt_store_create(
                path.encode(), ctypes.c_uint64(capacity_bytes)
            )
            if not self._h:
                raise StoreError(f"failed to create store arena at {path}")
        else:
            self._h = self._lib.rt_store_attach(path.encode())
            if not self._h:
                raise StoreError(
                    f"failed to attach store arena at {path} "
                    "(missing, corrupt, or client slots exhausted)"
                )
        fd = os.open(path, os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, 0)
        finally:
            os.close(fd)
        self._mv = memoryview(self._mm)
        self._closed = False
        # pins outstanding in THIS client (zero-copy get() views the user
        # still holds).  The C ledger caps pins+creates at
        # kMaxPinsPerClient=1024; callers consult pin_headroom() to fall
        # back to copy-out gets before the ledger fills.  The lock fences
        # pin finalizers (any thread) against close()'s detach — unpin on
        # a detached handle would be use-after-free.
        self._pins_outstanding = 0
        # RLock, not Lock: critical sections allocate (int boxing, ctypes
        # marshalling), any allocation can trigger cyclic GC, and a
        # collected cycle can finalize a PinnedBuffer whose __del__ ->
        # _unpin re-enters this lock on the SAME thread.  Re-entrant
        # sections are interleave-safe (counter updates are complete
        # statements; after close() sets _closed the C call is skipped).
        self._pin_lock = threading.RLock()
        self._max_pins = int(self._lib.rt_store_max_pins())
        self._created_views: dict = {}  # object_id -> writable view until seal
        # First-touch page faults dominate large writes into fresh arena
        # regions (~0.7 GB/s trap-per-page vs ~6 GB/s on resident pages).
        # MADV_POPULATE_WRITE batch-faults a fresh range in-kernel; the
        # high-water mark keeps the steady state (recycled offsets, pages
        # already resident) at zero madvise overhead.
        self._populate_hw = 0
        self._can_populate = True
        # Inline-put slot slab (data plane v2): per-process batches of
        # pre-registered, pre-faulted fixed-size blocks in power-of-two
        # size classes (256 B .. put_inline_max_bytes, waste ≤ 2x).  A
        # payload under the threshold skips the create/seal round trip
        # entirely — write into a free slot of the smallest fitting
        # class, publish the sealed entry under ONE shard-lock
        # acquisition (rt_store_publish_slot).  Replenished in batches so
        # the allocator lock and the first-touch page faults are paid once
        # per batch, not per put (BENCH.md multi-client terms (a)+(b)).
        self._slab_lock = threading.Lock()
        self._slab_classes: dict = {}       # slot_size -> [free offsets]
        self._slab_pending: dict = {}       # oid -> (off, view, slot_size)
        self._slab_max = -1                 # -1 until sized from config
        self._slab_disabled = False         # arena pressure: fall back
        self._slab_misses = 0               # skips since disable (re-probe)
        self._slab_hits = 0                 # reservations served by slab

    # -- write path ------------------------------------------------------
    def _put_fault_check(self, object_id: bytes) -> None:
        """Chaos site ``store.put``: fires once per put/reserve attempt —
        the same point v1's create() fired — so seeded traces are
        unchanged by the vectored/inline rebuild."""
        fault_ctl = faults.ACTIVE  # bind once: clear() races the check
        if fault_ctl is not None:
            # an injected arena-pressure failure — callers must survive
            # it exactly like a genuinely full arena (spill request +
            # bounded retry in _write_to_store)
            plan = fault_ctl.hit(faults.SITE_STORE_PUT, object_id.hex())
            if plan is not None and plan.action == "error":
                raise StoreFullError(
                    f"injected arena put failure for {object_id.hex()[:12]}"
                )

    def create(self, object_id: bytes, size: int) -> memoryview:
        """Reserve space; returns a writable view. Must seal() or abort()."""
        object_id = _check_id(object_id)
        self._put_fault_check(object_id)
        return self._create_raw(object_id, size)

    def _create_raw(self, object_id: bytes, size: int) -> memoryview:
        off = ctypes.c_uint64()
        rc = self._lib.rt_store_create_object(
            self._h, object_id, ctypes.c_uint64(size), ctypes.byref(off)
        )
        if rc == RT_EXISTS:
            raise ObjectExistsError(object_id.hex())
        if rc == RT_NO_SPACE:
            raise StoreFullError(
                f"object of {size} bytes does not fit (capacity {self.capacity})"
            )
        if rc != RT_OK:
            raise StoreError(f"create failed: {_rc_name(rc)}")
        end = off.value + size
        if self._can_populate and end > self._populate_hw:
            start = max(off.value, self._populate_hw) & ~0xFFF
            try:
                # MADV_POPULATE_WRITE == 23 (Linux 5.14+); mmap.py lacks
                # the constant on this Python build
                self._mm.madvise(23, start, min(len(self._mm), end) - start)
            except (OSError, ValueError):
                self._can_populate = False  # older kernel: fall back to traps
            self._populate_hw = end
        view = self._mv[off.value : off.value + size]
        self._created_views[bytes(object_id)] = view
        return view

    def seal(self, object_id: bytes) -> None:
        object_id = _check_id(object_id)
        rc = self._lib.rt_store_seal(self._h, object_id)
        if rc != RT_OK:
            raise StoreError(f"seal failed: {_rc_name(rc)}")
        v = self._created_views.pop(bytes(object_id), None)
        if v is not None:
            v.release()

    def abort(self, object_id: bytes) -> None:
        object_id = _check_id(object_id)
        with self._slab_lock:
            pend = self._slab_pending.pop(object_id, None)
            if pend is not None:
                # slab reservation: the slot goes back to the freelist —
                # nothing was published, the index never saw the id
                off, view, slot_size = pend
                view.release()
                self._slab_classes.setdefault(slot_size, []).append(off)
                return
        self._lib.rt_store_abort(self._h, object_id)
        v = self._created_views.pop(bytes(object_id), None)
        if v is not None:
            v.release()

    # -- vectored single-pass put path (data plane v2) --------------------
    #
    # reserve() → write payload into the returned view → commit().  Small
    # payloads ride the pre-registered inline slab (one shard-lock publish,
    # no create/seal round trip, pages pre-faulted at batch-reserve time);
    # everything else rides create + the atomic protect+seal (seal2).  The
    # ``store.put`` chaos site fires once per reserve attempt, exactly
    # where v1's create() fired.

    _SLAB_MIN_CLASS = 256  # smallest slot class (bytes)

    def _slab_threshold(self) -> int:
        if self._slab_max >= 0:
            return self._slab_max
        from ray_tpu.common.config import cfg

        self._slab_max = max(0, cfg.put_inline_max_bytes)
        return self._slab_max

    @classmethod
    def _slab_class(cls, size: int) -> int:
        """Smallest power-of-two slot class holding ``size`` (waste
        stays under 2x the payload, not a full max-size slot)."""
        c = cls._SLAB_MIN_CLASS
        while c < size:
            c <<= 1
        return c

    def _slab_refill_locked(self, slot_size: int) -> bool:
        """Reserve a fresh batch of ``slot_size`` slots (caller holds
        _slab_lock)."""
        from ray_tpu.common.config import cfg

        batch = max(1, cfg.put_inline_slab_slots)
        offs = (ctypes.c_uint64 * batch)()
        got = self._lib.rt_store_reserve_slots(
            self._h, slot_size, batch, offs,
        )
        if not got:
            # arena pressure or ledger full: disable, re-probe after a
            # while (puts fall back to the evicting create path meanwhile)
            self._slab_disabled = True
            self._slab_misses = 0
            return False
        free = self._slab_classes.setdefault(slot_size, [])
        for i in range(got):
            off = offs[i]
            # touch-ahead: batch-fault the slot's pages ONCE here so no
            # put ever pays a first-touch trap (multi-client term (a)).
            # Gated on the same populate high-water mark the create path
            # keeps: recycled offsets are already resident, and an
            # madvise syscall per refilled slot on resident pages was
            # measurable against the slab's own win.
            end = off + slot_size
            if self._can_populate and end > self._populate_hw:
                try:
                    start = max(off, self._populate_hw) & ~0xFFF
                    self._mm.madvise(
                        23, start, min(len(self._mm), end) - start,
                    )
                except (OSError, ValueError):
                    self._can_populate = False
                self._populate_hw = end
            free.append(off)
        return True

    def set_slab_enabled(self, enabled: bool) -> None:
        """Force the inline slab off (sticky — no pressure re-probe) or
        re-arm it; the bench matrix's `_noinline` twin and tests use
        this to isolate the fast path."""
        if not enabled:
            self.shrink_slab()
            self._slab_forced_off = True
        else:
            self._slab_forced_off = False
            with self._slab_lock:
                self._slab_disabled = False
                self._slab_misses = 0

    _slab_forced_off = False

    def _slab_reserve(self, object_id: bytes, size: int):
        """A writable slot view for a small payload, or None (fall back)."""
        if self._slab_forced_off:
            return None
        with self._slab_lock:
            if self._slab_disabled:
                self._slab_misses += 1
                if self._slab_misses < 512:
                    return None
                # re-probe: pressure may have passed (spill/eviction)
                self._slab_disabled = False
            slot_size = self._slab_class(size)
            free = self._slab_classes.get(slot_size)
            if not free:
                if not self._slab_refill_locked(slot_size):
                    return None
                free = self._slab_classes[slot_size]
            off = free.pop()
            view = self._mv[off : off + size]
            self._slab_pending[object_id] = (off, view, slot_size)
            self._slab_hits += 1
            return view

    def shrink_slab(self) -> int:
        """Give free (unused) reserved slots back to the allocator —
        called under arena pressure before asking the raylet to spill.
        Returns the number of slots released."""
        with self._slab_lock:
            slots = [
                off for free in self._slab_classes.values() for off in free
            ]
            self._slab_classes.clear()
            self._slab_disabled = True
            self._slab_misses = 0
            if not slots:
                return 0
            offs = (ctypes.c_uint64 * len(slots))(*slots)
        self._lib.rt_store_release_slots(self._h, offs, len(slots))
        return len(slots)

    def reserve(self, object_id: bytes, size: int) -> memoryview:
        """Reserve space for a put; write the payload into the returned
        view, then commit() (or abort()).  Small payloads land in a
        pre-faulted inline slab slot; large ones in a fresh allocation."""
        object_id = _check_id(object_id)
        self._put_fault_check(object_id)
        if 0 < size <= self._slab_threshold():
            view = self._slab_reserve(object_id, size)
            if view is not None:
                return view
        return self._create_raw(object_id, size)

    def commit(self, object_id: bytes, *, protect: bool = False) -> None:
        """Make a reserved object visible: slab reservations publish the
        sealed entry in one shard-lock acquisition; created ones seal with
        the primary-copy flag applied atomically (no protect-vs-evict
        window, one lock round trip instead of protect + seal)."""
        object_id = _check_id(object_id)
        with self._slab_lock:
            pend = self._slab_pending.pop(object_id, None)
        if pend is not None:
            off, view, slot_size = pend
            size = view.nbytes
            rc = self._lib.rt_store_publish_slot(
                self._h, object_id, off, size, 1 if protect else 0,
            )
            if rc == RT_OK:
                view.release()
                return
            if rc == RT_EXISTS:
                # the slot went back to our slab ledger C-side; surface
                # the duplicate like create() would have
                view.release()
                with self._slab_lock:
                    self._slab_classes.setdefault(
                        slot_size, []
                    ).append(off)
                raise ObjectExistsError(object_id.hex())
            if rc == RT_NO_SPACE:
                # shard sub-table full: fall back through the evicting
                # create path.  The slot returns to the freelist only
                # AFTER the payload is copied out of it (a concurrent
                # reserve must not recycle it mid-read), and on a packed
                # arena (StoreFullError from create) the pending entry is
                # restored so the caller can spill and retry commit().
                try:
                    buf = self._create_raw(object_id, size)
                except StoreFullError:
                    with self._slab_lock:
                        self._slab_pending[object_id] = pend
                    raise
                except BaseException:
                    # duplicate/hard failure: commit is over either way,
                    # so the slot goes home
                    view.release()
                    with self._slab_lock:
                        self._slab_classes.setdefault(
                            slot_size, []
                        ).append(off)
                    raise
                try:
                    buf[:] = self._mv[off : off + size]
                    self._seal2(object_id, protect)
                finally:
                    view.release()
                    with self._slab_lock:
                        self._slab_classes.setdefault(
                            slot_size, []
                        ).append(off)
                return
            raise StoreError(f"publish failed: {_rc_name(rc)}")
        self._seal2(object_id, protect)

    def _seal2(self, object_id: bytes, protect: bool) -> None:
        rc = self._lib.rt_store_seal2(
            self._h, object_id, 1 if protect else 0
        )
        if rc != RT_OK:
            raise StoreError(f"seal failed: {_rc_name(rc)}")
        v = self._created_views.pop(bytes(object_id), None)
        if v is not None:
            v.release()

    def put(self, object_id: bytes, data, *, protect: bool = False) -> None:
        """One-shot single-pass put: reserve + one copy + commit (the
        single-segment case of ``put_vectored``).  ``protect=True``
        applies the primary-copy flag atomically with the seal/publish,
        so the entry is never LRU-evictable in between."""
        self.put_vectored(object_id, (data,), protect=protect)

    def put_vectored(self, object_id: bytes, segments, *,
                     protect: bool = False) -> int:
        """Single-pass put of one or more buffer segments written back to
        back through the reserve→write→commit flow, never concatenated
        into an intermediate bytes.  ``put`` (raylet pulls, spill
        restore, collective shm handoff) is the one-segment case.
        Returns total bytes written."""
        views = [
            m if m.format == "B" and m.ndim == 1 else m.cast("B")
            for m in map(memoryview, segments)
        ]
        total = sum(v.nbytes for v in views)
        buf = self.reserve(object_id, total)
        try:
            off = 0
            for v in views:
                buf[off : off + v.nbytes] = v
                off += v.nbytes
        except BaseException:
            self.abort(object_id)
            raise
        self.commit(object_id, protect=protect)
        return total

    # -- read path -------------------------------------------------------
    def get(self, object_id: bytes) -> Optional[PinnedBuffer]:
        """Zero-copy pinned view of a sealed object, or None if absent."""
        object_id = _check_id(object_id)
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.rt_store_get(
            self._h, object_id, ctypes.byref(off), ctypes.byref(size)
        )
        if rc in (RT_NOT_FOUND, RT_NOT_SEALED):
            return None
        if rc != RT_OK:
            raise StoreError(f"get failed: {_rc_name(rc)}")
        view = self._mv[off.value : off.value + size.value]
        pin = PinnedBuffer(self, object_id, view)
        with self._pin_lock:
            self._pins_outstanding += 1
        return pin

    def pin_headroom(self) -> int:
        """Ledger slots left before pins would starve creates.  The C
        ledger is shared by held pins AND unsealed creates
        (rt_store_max_pins slots per client), so both count."""
        with self._pin_lock:
            return (
                self._max_pins
                - self._pins_outstanding
                - len(self._created_views)
            )

    def contains(self, object_id: bytes) -> bool:
        object_id = _check_id(object_id)
        return bool(self._lib.rt_store_contains(self._h, object_id))

    def delete(self, object_id: bytes) -> bool:
        object_id = _check_id(object_id)
        rc = self._lib.rt_store_delete(self._h, object_id)
        return rc == RT_OK

    def _unpin(self, object_id: bytes) -> None:
        # under the lock: a finalizer-thread unpin racing close() must
        # not reach the C handle after rt_store_detach munmaps it
        with self._pin_lock:
            self._pins_outstanding -= 1
            if not self._closed:
                self._lib.rt_store_unpin(self._h, object_id)

    # -- admin -----------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.stats()["capacity"]

    def stats(self) -> dict:
        cap, used, objs, evs = (ctypes.c_uint64() for _ in range(4))
        self._lib.rt_store_stats(
            self._h, ctypes.byref(cap), ctypes.byref(used),
            ctypes.byref(objs), ctypes.byref(evs),
        )
        return {
            "capacity": cap.value,
            "used": used.value,
            "objects": objs.value,
            "evictions": evs.value,
            # process-local: inline-slab reservations served since open
            # (the data-plane pin for "small puts ride the slab")
            "slab_hits": self._slab_hits,
        }

    def reap(self) -> int:
        """Release pins held by dead client processes; returns clients reaped."""
        return self._lib.rt_store_reap(self._h)

    def protect(self, object_id: bytes, on: bool = True) -> bool:
        """Mark/unmark an object as a primary copy: LRU eviction skips
        protected entries, so the only copy of a value can never vanish
        silently — the raylet's spill manager writes protected entries to
        disk under memory pressure instead (reference role:
        local_object_manager.h pinned-primary + spill).

        Returns True iff the flag was applied.  False means the object is
        gone (deleted/evicted between create and protect, or a bad id) —
        callers that rely on the primary surviving LRU must check."""
        object_id = _check_id(object_id)
        rc = self._lib.rt_store_protect(self._h, object_id, 1 if on else 0)
        return rc == 0

    def list_spillable(self, max_n: int = 4096) -> list:
        """(object_id, size) of sealed, unpinned, protected entries in
        LRU order — the spill manager's victim candidates."""
        ids = (ctypes.c_uint8 * (16 * max_n))()
        sizes = (ctypes.c_uint64 * max_n)()
        n = self._lib.rt_store_list_spillable(
            self._h, ids, sizes, ctypes.c_uint64(max_n)
        )
        raw = bytes(ids)
        return [(raw[i * 16:(i + 1) * 16], sizes[i]) for i in range(n)]

    def close(self) -> None:
        if self._closed:
            return
        # Outstanding pins back zero-copy get() views the USER still
        # holds — do not force-release them; their owners' GC will (and
        # after _closed is set, their _unpin becomes a no-op).  Plasma
        # has the same contract: buffers read after client disconnect
        # are valid only until another attached client reuses the range
        # (a standalone shutdown tears the whole store down, so the
        # common case stays safe).
        for v in self._created_views.values():
            v.release()
        self._created_views.clear()
        with self._slab_lock:
            # unpublished slab reservations + free slots: views must drop
            # before the mmap closes; the block offsets themselves are
            # reclaimed by rt_store_detach's client-ledger release
            for _off, v, _cls in self._slab_pending.values():
                v.release()
            self._slab_pending.clear()
            self._slab_classes.clear()
        try:
            self._mv.release()
            self._mm.close()
        except BufferError:
            # live zero-copy views export the map; it must outlive them.
            # Leave it to process teardown — detaching the client ledger
            # below is what releases store-side state.
            pass
        with self._pin_lock:
            self._closed = True
            self._lib.rt_store_detach(self._h)

    def destroy(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


def default_store_path(node_id_hex: str) -> str:
    return f"/dev/shm/rt_store_{node_id_hex[:12]}"


def default_capacity() -> int:
    from ray_tpu.common.config import cfg

    if cfg.object_store_bytes:
        size = cfg.object_store_bytes
    else:
        try:
            st = os.statvfs("/dev/shm")
            avail = st.f_bavail * st.f_frsize
        except OSError:
            avail = 1 << 30
        size = min(int(avail * 0.3), cfg.object_store_auto_cap_bytes)
    return max(size, _get_lib().rt_store_min_size())
