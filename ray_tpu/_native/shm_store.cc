// Shared-memory object store: per-node arena with an allocator and object
// index living *inside* the shared mapping, so any local process can attach
// and read sealed objects zero-copy.
//
// Role-equivalent of the reference's Plasma store (ray:
// src/ray/object_manager/plasma/{store.h,object_lifecycle_manager.h,
// eviction_policy.h,dlmalloc.cc}) redesigned daemon-less: instead of a store
// server process brokering allocations over a unix socket with fd-passing,
// every client attaches the same file-backed mapping and allocation/index
// updates are serialized by a robust process-shared mutex.  This removes a
// socket round-trip from the put/get hot path entirely (the reference needs
// one per create/seal/get; here those are ~100ns lock acquisitions).
//
// Layout of the arena file:
//   [ Header | client slots | hash-table entries | data region ]
// All internal references are byte offsets, never pointers, so processes can
// map at different addresses.
//
// Crash tolerance without a daemon (the reference recovers reader pins via
// client-disconnect handling in the store server): every attached client owns
// a slot holding its pid and a ledger of its outstanding pins.  rt_store_reap
// (called by the raylet periodically, and by attach when slots run out)
// detects dead pids and releases their pins — aborting their half-created
// objects and unpinning their reads — so a crashed worker can never leak
// refcounts or arena space permanently.
//
// Concurrency model: one mutex per node arena guards allocator + index
// metadata only; object *payload* writes happen outside the lock (the object
// is invisible until sealed).  Robust mutex semantics recover the lock if a
// client dies while holding it.

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <new>

namespace {

constexpr uint64_t kMagic = 0x5254504c41534d42ULL;  // "RTPLASMB" (v2: Entry.flags)
constexpr uint64_t kAlign = 64;
constexpr uint32_t kIdLen = 16;
constexpr uint32_t kMaxClients = 128;
constexpr uint32_t kMaxPinsPerClient = 1024;

// Object states in the index.
enum : uint32_t {
  kEmpty = 0,
  kCreated = 1,
  kSealed = 2,
  kTombstone = 3,
};

// Return codes (keep in sync with ray_tpu/_native/store.py).
enum : int {
  RT_OK = 0,
  RT_EXISTS = -1,
  RT_NOT_FOUND = -2,
  RT_NO_SPACE = -3,
  RT_ERR = -4,
  RT_NOT_SEALED = -5,
  RT_PINNED = -6,
  RT_TOO_MANY_PINS = -7,
  RT_NO_CLIENT_SLOT = -8,
};

// Entry flag bits.
constexpr uint32_t kFlagProtected = 1u;  // primary copy: LRU must not evict

struct Entry {
  uint8_t id[kIdLen];
  uint64_t offset;       // data offset from arena base
  uint64_t size;         // payload size
  uint64_t last_access;  // logical clock for LRU eviction
  uint32_t state;
  uint32_t refcnt;       // pin count; pinned objects are never evicted
  uint32_t flags;        // kFlag* bits; protected entries spill before evict
  uint32_t pad;
};

struct PinRec {
  uint8_t id[kIdLen];
  uint32_t count;
  uint32_t pad;
};

struct ClientSlot {
  uint32_t pid;      // 0 = free
  uint32_t npins;    // used prefix of pins[]
  PinRec pins[kMaxPinsPerClient];
};

struct Header {
  uint64_t magic;
  uint64_t total_size;
  uint64_t clients_off;
  uint64_t table_off;
  uint64_t table_cap;   // number of Entry slots (power of two)
  uint64_t table_used;  // live + tombstone entries
  uint64_t tombstones;
  uint64_t live_objects;
  uint64_t data_off;
  uint64_t data_size;
  uint64_t used_bytes;   // allocated bytes incl. block headers
  uint64_t free_head;    // offset of first free block (0 = none)
  uint64_t access_clock; // bumped on every lookup, feeds last_access
  uint64_t num_evictions;
  pthread_mutex_t mutex;
};

// Every data block (free or allocated) carries a boundary-tag header and
// footer so free() can coalesce with neighbours in O(1).
struct BlockHeader {
  uint64_t size;  // total block size incl. header+footer; low bit = free flag
  uint64_t next_free;
  uint64_t prev_free;
};
constexpr uint64_t kBlockHdr = sizeof(BlockHeader);
constexpr uint64_t kBlockFtr = sizeof(uint64_t);
constexpr uint64_t kMinBlock = kBlockHdr + kBlockFtr + kAlign;

inline uint64_t block_size(uint64_t tag) { return tag & ~1ULL; }
inline bool block_free(uint64_t tag) { return tag & 1ULL; }

struct Store {
  uint8_t* base;
  uint64_t map_size;
  int fd;
  int32_t client_idx;  // this handle's slot in the client registry
  Header* hdr() { return reinterpret_cast<Header*>(base); }
  ClientSlot* clients() {
    return reinterpret_cast<ClientSlot*>(base + hdr()->clients_off);
  }
  Entry* table() { return reinterpret_cast<Entry*>(base + hdr()->table_off); }
  BlockHeader* block(uint64_t off) {
    return reinterpret_cast<BlockHeader*>(base + off);
  }
  uint64_t* footer(uint64_t off) {
    return reinterpret_cast<uint64_t*>(base + off + block_size(block(off)->size) -
                                       kBlockFtr);
  }
};

uint64_t round_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the 16-byte id.
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdLen; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

class Locker {
 public:
  explicit Locker(Store* s) : s_(s) {
    int rc = pthread_mutex_lock(&s_->hdr()->mutex);
    if (rc == EOWNERDEAD) {
      // A client died holding the lock. Metadata mutations are small and
      // ordered; worst case is a leaked created-but-unsealed object, which
      // rt_store_reap reclaims via the dead client's pin ledger.
      pthread_mutex_consistent(&s_->hdr()->mutex);
    }
  }
  ~Locker() { pthread_mutex_unlock(&s_->hdr()->mutex); }

 private:
  Store* s_;
};

// ---- free-list allocator ------------------------------------------------

void freelist_insert(Store* s, uint64_t off) {
  Header* h = s->hdr();
  BlockHeader* b = s->block(off);
  b->size |= 1ULL;  // mark free
  *s->footer(off) = b->size;
  b->next_free = h->free_head;
  b->prev_free = 0;
  if (h->free_head) s->block(h->free_head)->prev_free = off;
  h->free_head = off;
}

void freelist_remove(Store* s, uint64_t off) {
  Header* h = s->hdr();
  BlockHeader* b = s->block(off);
  if (b->prev_free)
    s->block(b->prev_free)->next_free = b->next_free;
  else
    h->free_head = b->next_free;
  if (b->next_free) s->block(b->next_free)->prev_free = b->prev_free;
  b->size &= ~1ULL;
  *s->footer(off) = b->size;
}

// Allocate a block with at least `payload` bytes of usable space.
// Returns data offset (past the header) or 0 on failure.
uint64_t arena_alloc(Store* s, uint64_t payload) {
  Header* h = s->hdr();
  uint64_t need = round_up(payload + kBlockHdr + kBlockFtr, kAlign);
  if (need < kMinBlock) need = kMinBlock;
  uint64_t off = h->free_head;
  while (off) {
    BlockHeader* b = s->block(off);
    uint64_t bsz = block_size(b->size);
    if (bsz >= need) {
      freelist_remove(s, off);
      if (bsz - need >= kMinBlock) {
        // split: tail becomes a new free block
        uint64_t tail = off + need;
        b->size = need;
        *s->footer(off) = need;
        BlockHeader* t = s->block(tail);
        t->size = bsz - need;
        *s->footer(tail) = t->size;
        freelist_insert(s, tail);
      }
      h->used_bytes += block_size(b->size);
      return off + kBlockHdr;
    }
    off = b->next_free;
  }
  return 0;
}

void arena_free(Store* s, uint64_t data_off) {
  Header* h = s->hdr();
  uint64_t off = data_off - kBlockHdr;
  BlockHeader* b = s->block(off);
  h->used_bytes -= block_size(b->size);
  // coalesce with next block
  uint64_t next = off + block_size(b->size);
  if (next < h->data_off + h->data_size) {
    BlockHeader* nb = s->block(next);
    if (block_free(nb->size)) {
      freelist_remove(s, next);
      b->size = block_size(b->size) + block_size(nb->size);
      *s->footer(off) = b->size;
    }
  }
  // coalesce with previous block
  if (off > h->data_off) {
    uint64_t prev_tag = *reinterpret_cast<uint64_t*>(s->base + off - kBlockFtr);
    if (block_free(prev_tag)) {
      uint64_t prev = off - block_size(prev_tag);
      freelist_remove(s, prev);
      s->block(prev)->size = block_size(prev_tag) + block_size(b->size);
      *s->footer(prev) = s->block(prev)->size;
      off = prev;
      b = s->block(off);
    }
  }
  freelist_insert(s, off);
}

// ---- index --------------------------------------------------------------

Entry* find_entry(Store* s, const uint8_t* id) {
  Header* h = s->hdr();
  uint64_t mask = h->table_cap - 1;
  uint64_t i = hash_id(id) & mask;
  for (uint64_t probes = 0; probes < h->table_cap; probes++, i = (i + 1) & mask) {
    Entry* e = &s->table()[i];
    if (e->state == kEmpty) return nullptr;
    if (e->state != kTombstone && memcmp(e->id, id, kIdLen) == 0) return e;
  }
  return nullptr;
}

// Rebuild the index without tombstones (uses a transient heap buffer; called
// under the lock).
void purge_tombstones(Store* s) {
  Header* h = s->hdr();
  uint64_t cap = h->table_cap;
  Entry* snapshot = static_cast<Entry*>(malloc(cap * sizeof(Entry)));
  if (!snapshot) return;
  memcpy(snapshot, s->table(), cap * sizeof(Entry));
  memset(s->table(), 0, cap * sizeof(Entry));
  uint64_t mask = cap - 1;
  uint64_t live = 0;
  for (uint64_t i = 0; i < cap; i++) {
    Entry* e = &snapshot[i];
    if (e->state == kCreated || e->state == kSealed) {
      uint64_t j = hash_id(e->id) & mask;
      while (s->table()[j].state != kEmpty) j = (j + 1) & mask;
      s->table()[j] = *e;
      live++;
    }
  }
  free(snapshot);
  h->table_used = live;
  h->tombstones = 0;
}

void make_tombstone(Store* s, Entry* e) {
  e->state = kTombstone;
  s->hdr()->tombstones++;
  s->hdr()->live_objects--;
}

// Find a slot for inserting `id`. Returns existing entry if the id is live.
Entry* find_slot(Store* s, const uint8_t* id, bool* reused_tombstone) {
  Header* h = s->hdr();
  uint64_t mask = h->table_cap - 1;
  uint64_t i = hash_id(id) & mask;
  Entry* first_tomb = nullptr;
  *reused_tombstone = false;
  for (uint64_t probes = 0; probes < h->table_cap; probes++, i = (i + 1) & mask) {
    Entry* e = &s->table()[i];
    if (e->state == kEmpty) {
      if (first_tomb) {
        *reused_tombstone = true;
        return first_tomb;
      }
      return e;
    }
    if (e->state == kTombstone) {
      if (!first_tomb) first_tomb = e;
    } else if (memcmp(e->id, id, kIdLen) == 0) {
      return e;  // caller checks state
    }
  }
  if (first_tomb) *reused_tombstone = true;
  return first_tomb;
}

// Evict least-recently-used sealed, unpinned objects until `needed_bytes`
// could plausibly be allocated AND at least `needed_entries` index slots are
// freed.  Single scan: collect candidates, sort by last_access, evict in
// order — the lock is held, so no O(table_cap x victims) rescans.
// (ray: eviction_policy.h LRUCache analogue, done inline.)
uint64_t evict_lru(Store* s, uint64_t needed_bytes, uint64_t needed_entries = 0) {
  Header* h = s->hdr();
  uint64_t byte_target = needed_bytes + (needed_bytes >> 2);
  struct Cand {
    uint64_t access;
    uint64_t idx;
  };
  Cand* cands = static_cast<Cand*>(malloc(h->table_cap * sizeof(Cand)));
  if (!cands) return 0;
  uint64_t n = 0;
  for (uint64_t i = 0; i < h->table_cap; i++) {
    Entry* e = &s->table()[i];
    if (e->state == kSealed && e->refcnt == 0 &&
        !(e->flags & kFlagProtected)) {
      cands[n].access = e->last_access;
      cands[n].idx = i;
      n++;
    }
  }
  qsort(cands, n, sizeof(Cand), [](const void* a, const void* b) {
    uint64_t aa = static_cast<const Cand*>(a)->access;
    uint64_t bb = static_cast<const Cand*>(b)->access;
    return (aa < bb) ? -1 : (aa > bb) ? 1 : 0;
  });
  uint64_t freed = 0, entries_freed = 0;
  for (uint64_t i = 0;
       i < n && (freed < byte_target || entries_freed < needed_entries); i++) {
    Entry* e = &s->table()[cands[i].idx];
    freed += e->size;
    entries_freed++;
    arena_free(s, e->offset);
    make_tombstone(s, e);
    h->num_evictions++;
  }
  free(cands);
  return freed;
}

// ---- client pin ledger --------------------------------------------------

int ledger_add(Store* s, const uint8_t* id) {
  ClientSlot* c = &s->clients()[s->client_idx];
  for (uint32_t i = 0; i < c->npins; i++) {
    if (memcmp(c->pins[i].id, id, kIdLen) == 0) {
      c->pins[i].count++;
      return RT_OK;
    }
  }
  if (c->npins >= kMaxPinsPerClient) return RT_TOO_MANY_PINS;
  memcpy(c->pins[c->npins].id, id, kIdLen);
  c->pins[c->npins].count = 1;
  c->npins++;
  return RT_OK;
}

void ledger_remove(Store* s, const uint8_t* id) {
  ClientSlot* c = &s->clients()[s->client_idx];
  for (uint32_t i = 0; i < c->npins; i++) {
    if (memcmp(c->pins[i].id, id, kIdLen) == 0) {
      if (--c->pins[i].count == 0) {
        c->pins[i] = c->pins[c->npins - 1];  // swap-remove
        c->npins--;
      }
      return;
    }
  }
}

// Release every pin a client slot holds: unpin sealed reads, abort
// half-created objects. Called on detach and on reaping a dead client.
void release_client_pins(Store* s, ClientSlot* c) {
  Header* h = s->hdr();
  for (uint32_t i = 0; i < c->npins; i++) {
    Entry* e = find_entry(s, c->pins[i].id);
    if (!e) continue;
    if (e->state == kCreated) {
      // creator died/left before sealing: reclaim the space
      arena_free(s, e->offset);
      make_tombstone(s, e);
    } else {
      uint32_t n = c->pins[i].count;
      e->refcnt = (e->refcnt > n) ? e->refcnt - n : 0;
    }
  }
  c->npins = 0;
  c->pid = 0;
}

// Reap clients whose pid no longer exists. Returns number reaped.
int reap_dead_clients(Store* s) {
  int reaped = 0;
  ClientSlot* slots = s->clients();
  for (uint32_t i = 0; i < kMaxClients; i++) {
    ClientSlot* c = &slots[i];
    if (c->pid != 0 && kill((pid_t)c->pid, 0) != 0 && errno == ESRCH) {
      release_client_pins(s, c);
      reaped++;
    }
  }
  return reaped;
}

int32_t claim_client_slot(Store* s) {
  ClientSlot* slots = s->clients();
  for (int pass = 0; pass < 2; pass++) {
    for (uint32_t i = 0; i < kMaxClients; i++) {
      if (slots[i].pid == 0) {
        slots[i].pid = (uint32_t)getpid();
        slots[i].npins = 0;
        return (int32_t)i;
      }
    }
    if (pass == 0 && reap_dead_clients(s) == 0) break;
  }
  return -1;
}

}  // namespace

extern "C" {

// Per-client ledger capacity (shared by pins and unsealed creates) so
// Python callers can gauge headroom without duplicating the constant.
uint64_t rt_store_max_pins() { return kMaxPinsPerClient; }

// Minimum arena size such that metadata plus a useful data region fit.
uint64_t rt_store_min_size() {
  uint64_t meta = round_up(sizeof(Header), kAlign) +
                  round_up(kMaxClients * sizeof(ClientSlot), kAlign) +
                  4096 * sizeof(Entry);
  return round_up(meta, kAlign) + (16ULL << 20);  // + 16MB data floor
}

// Create a new arena file of `size` bytes at `path` and initialize it.
// Returns an opaque handle or null.
void* rt_store_create(const char* path, uint64_t size) {
  if (size < rt_store_min_size()) return nullptr;
  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)size) != 0) {
    close(fd);
    unlink(path);
    return nullptr;
  }
  void* base = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    unlink(path);
    return nullptr;
  }
  Store* s = new Store{reinterpret_cast<uint8_t*>(base), size, fd, -1};
  Header* h = s->hdr();
  memset(h, 0, sizeof(Header));
  // Size the index at one slot per 4KB of arena, >= 4096 slots, power of 2.
  uint64_t cap = 4096;
  while (cap < size / 4096) cap <<= 1;
  h->total_size = size;
  h->clients_off = round_up(sizeof(Header), kAlign);
  h->table_off =
      round_up(h->clients_off + kMaxClients * sizeof(ClientSlot), kAlign);
  h->table_cap = cap;
  h->data_off = round_up(h->table_off + cap * sizeof(Entry), kAlign);
  if (size <= h->data_off + kMinBlock) {  // index for this size doesn't fit
    munmap(base, size);
    close(fd);
    unlink(path);
    delete s;
    return nullptr;
  }
  h->data_size = size - h->data_off;
  memset(s->clients(), 0, kMaxClients * sizeof(ClientSlot));
  memset(s->table(), 0, cap * sizeof(Entry));

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  // One giant free block spanning the data region.
  BlockHeader* b = s->block(h->data_off);
  b->size = h->data_size;
  *s->footer(h->data_off) = b->size;
  b->next_free = b->prev_free = 0;
  freelist_insert(s, h->data_off);

  s->client_idx = claim_client_slot(s);
  // Publish the magic LAST so a concurrent attach never sees a half-built
  // arena (attach fails cleanly until initialization completes).
  __atomic_store_n(&h->magic, kMagic, __ATOMIC_RELEASE);
  return s;
}

// Attach to an existing arena. Returns handle or null.
void* rt_store_attach(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Store* s =
      new Store{reinterpret_cast<uint8_t*>(base), (uint64_t)st.st_size, fd, -1};
  if (s->hdr()->magic != kMagic) {
    munmap(base, st.st_size);
    close(fd);
    delete s;
    return nullptr;
  }
  {
    Locker lock(s);
    s->client_idx = claim_client_slot(s);
  }
  if (s->client_idx < 0) {
    munmap(base, st.st_size);
    close(fd);
    delete s;
    return nullptr;
  }
  return s;
}

void rt_store_detach(void* handle) {
  Store* s = reinterpret_cast<Store*>(handle);
  if (s->client_idx >= 0) {
    Locker lock(s);
    release_client_pins(s, &s->clients()[s->client_idx]);
  }
  munmap(s->base, s->map_size);
  close(s->fd);
  delete s;
}

// Allocate space for an object. On success writes the payload offset (from
// arena base) to *out_offset; the caller memcpys payload there then seals.
// If the arena is full, evicts LRU sealed unpinned objects to make room.
int rt_store_create_object(void* handle, const uint8_t* id, uint64_t size,
                           uint64_t* out_offset) {
  Store* s = reinterpret_cast<Store*>(handle);
  if (s->client_idx < 0) return RT_NO_CLIENT_SLOT;
  Locker lock(s);
  Header* h = s->hdr();
  Entry* existing = find_entry(s, id);
  if (existing) return RT_EXISTS;
  // Keep the open-addressing table under 3/4 load: first purge tombstones;
  // if genuinely too many live objects, evict to make index room.
  if (h->table_used + 1 > (h->table_cap * 3) / 4) {
    if (h->tombstones > 0) purge_tombstones(s);
    if (h->live_objects + 1 > (h->table_cap * 3) / 4) {
      // index genuinely full of live objects: evict by entry count (an
      // eighth of the table), not bytes — small-object stores would
      // otherwise free one tiny victim and still report NO_SPACE
      evict_lru(s, size, h->table_cap / 8);
      purge_tombstones(s);
      if (h->live_objects + 1 > (h->table_cap * 3) / 4) return RT_NO_SPACE;
    }
  }
  uint64_t off = arena_alloc(s, size);
  if (!off) {
    evict_lru(s, size);
    off = arena_alloc(s, size);
    if (!off) return RT_NO_SPACE;
  }
  bool reused_tomb = false;
  Entry* e = find_slot(s, id, &reused_tomb);
  if (!e) {
    arena_free(s, off);
    return RT_NO_SPACE;
  }
  if (ledger_add(s, id) != RT_OK) {  // creator pin, reaped if creator dies
    arena_free(s, off);
    return RT_TOO_MANY_PINS;
  }
  if (e->state == kEmpty)
    h->table_used++;
  else if (reused_tomb)
    h->tombstones--;
  memcpy(e->id, id, kIdLen);
  e->offset = off;
  e->size = size;
  e->state = kCreated;
  e->refcnt = 1;  // creator holds a pin until seal/abort
  e->flags = 0;   // a reused tombstone may carry stale flag bits
  e->last_access = ++h->access_clock;
  h->live_objects++;
  *out_offset = off;
  return RT_OK;
}

int rt_store_seal(void* handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  Locker lock(s);
  Entry* e = find_entry(s, id);
  if (!e) return RT_NOT_FOUND;
  if (e->state != kCreated) return RT_ERR;
  e->state = kSealed;
  if (e->refcnt > 0) e->refcnt--;  // drop creator pin
  ledger_remove(s, id);
  return RT_OK;
}

// Abort an in-progress creation (e.g. serialization failed mid-write).
int rt_store_abort(void* handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  Locker lock(s);
  Entry* e = find_entry(s, id);
  if (!e) return RT_NOT_FOUND;
  if (e->state != kCreated) return RT_ERR;
  arena_free(s, e->offset);
  make_tombstone(s, e);
  ledger_remove(s, id);
  return RT_OK;
}

// Look up a sealed object; pins it (caller must rt_store_unpin).
int rt_store_get(void* handle, const uint8_t* id, uint64_t* out_offset,
                 uint64_t* out_size) {
  Store* s = reinterpret_cast<Store*>(handle);
  if (s->client_idx < 0) return RT_NO_CLIENT_SLOT;
  Locker lock(s);
  Entry* e = find_entry(s, id);
  if (!e) return RT_NOT_FOUND;
  if (e->state != kSealed) return RT_NOT_SEALED;
  int rc = ledger_add(s, id);
  if (rc != RT_OK) return rc;
  e->refcnt++;
  e->last_access = ++s->hdr()->access_clock;
  *out_offset = e->offset;
  *out_size = e->size;
  return RT_OK;
}

int rt_store_contains(void* handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  Locker lock(s);
  Entry* e = find_entry(s, id);
  return (e && e->state == kSealed) ? 1 : 0;
}

int rt_store_unpin(void* handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  Locker lock(s);
  Entry* e = find_entry(s, id);
  if (!e) return RT_NOT_FOUND;
  if (e->refcnt > 0) e->refcnt--;
  ledger_remove(s, id);
  return RT_OK;
}

// Delete a sealed object (refuses if pinned by readers).
int rt_store_delete(void* handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  Locker lock(s);
  Entry* e = find_entry(s, id);
  if (!e || e->state == kTombstone) return RT_NOT_FOUND;
  if (e->refcnt > 0) return RT_PINNED;
  arena_free(s, e->offset);
  make_tombstone(s, e);
  return RT_OK;
}

// Release pins of dead clients; returns number of clients reaped.
int rt_store_reap(void* handle) {
  Store* s = reinterpret_cast<Store*>(handle);
  Locker lock(s);
  return reap_dead_clients(s);
}

void rt_store_stats(void* handle, uint64_t* capacity, uint64_t* used,
                    uint64_t* objects, uint64_t* evictions) {
  Store* s = reinterpret_cast<Store*>(handle);
  Locker lock(s);
  Header* h = s->hdr();
  *capacity = h->data_size;
  *used = h->used_bytes;
  *objects = h->live_objects;
  *evictions = h->num_evictions;
}

// Set / clear the protected (primary-copy) bit.  Protected entries are
// skipped by LRU eviction; the node's spill manager writes them to disk
// and clears the bit (or deletes them) when the arena fills.
int rt_store_protect(void* handle, const uint8_t* id, int on) {
  Store* s = reinterpret_cast<Store*>(handle);
  Locker lock(s);
  Entry* e = find_entry(s, id);
  if (!e) return RT_NOT_FOUND;
  if (on)
    e->flags |= kFlagProtected;
  else
    e->flags &= ~kFlagProtected;
  return RT_OK;
}

// List spill candidates: sealed, unpinned, protected entries in LRU order
// (least recently used first).  Writes up to `max_n` ids (16 bytes each)
// into out_ids and their payload sizes into out_sizes; returns the count.
uint64_t rt_store_list_spillable(void* handle, uint8_t* out_ids,
                                 uint64_t* out_sizes, uint64_t max_n) {
  Store* s = reinterpret_cast<Store*>(handle);
  Locker lock(s);
  Header* h = s->hdr();
  struct Cand {
    uint64_t access;
    uint64_t idx;
  };
  Cand* cands = static_cast<Cand*>(malloc(h->table_cap * sizeof(Cand)));
  if (!cands) return 0;
  uint64_t n = 0;
  for (uint64_t i = 0; i < h->table_cap; i++) {
    Entry* e = &s->table()[i];
    if (e->state == kSealed && e->refcnt == 0 &&
        (e->flags & kFlagProtected)) {
      cands[n].access = e->last_access;
      cands[n].idx = i;
      n++;
    }
  }
  qsort(cands, n, sizeof(Cand), [](const void* a, const void* b) {
    uint64_t aa = static_cast<const Cand*>(a)->access;
    uint64_t bb = static_cast<const Cand*>(b)->access;
    return (aa < bb) ? -1 : (aa > bb) ? 1 : 0;
  });
  uint64_t count = n < max_n ? n : max_n;
  for (uint64_t i = 0; i < count; i++) {
    Entry* e = &s->table()[cands[i].idx];
    memcpy(out_ids + i * kIdLen, e->id, kIdLen);
    out_sizes[i] = e->size;
  }
  free(cands);
  return count;
}

// Base address of the mapping in this process (for zero-copy memoryviews).
void* rt_store_base(void* handle) {
  return reinterpret_cast<Store*>(handle)->base;
}

uint64_t rt_store_map_size(void* handle) {
  return reinterpret_cast<Store*>(handle)->map_size;
}

}  // extern "C"
