// Shared-memory object store: per-node arena with an allocator and object
// index living *inside* the shared mapping, so any local process can attach
// and read sealed objects zero-copy.
//
// Role-equivalent of the reference's Plasma store (ray:
// src/ray/object_manager/plasma/{store.h,object_lifecycle_manager.h,
// eviction_policy.h,dlmalloc.cc}) redesigned daemon-less: instead of a store
// server process brokering allocations over a unix socket with fd-passing,
// every client attaches the same file-backed mapping and allocation/index
// updates are serialized by robust process-shared mutexes.  This removes a
// socket round-trip from the put/get hot path entirely (the reference needs
// one per create/seal/get; here those are ~100ns lock acquisitions).
//
// Layout of the arena file (v3):
//   [ Header (incl. shard headers) | client slots | hash-table entries |
//     data region ]
// All internal references are byte offsets, never pointers, so processes can
// map at different addresses.
//
// Concurrency model (data plane v2): TWO lock tiers instead of the v2
// single mutex —
//   * the MAIN mutex guards the allocator (free list, used_bytes),
//     eviction/maintenance passes, and the client-slot registry;
//   * the index is split into kShards sub-tables, each guarded by its own
//     robust mutex, shard chosen by the low bits of the id hash — so
//     concurrent writers publishing/sealing different objects no longer
//     serialize on one lock (the multi-client put bottleneck, BENCH.md
//     term (b)).
// Lock order is MAIN < shard[i] (ascending for multi-shard maintenance);
// no path ever acquires MAIN while holding a shard lock.  The per-client
// pin/slab ledger is only ever mutated by its own (live) process — it is
// guarded by a process-LOCAL mutex on the handle; reap touches only DEAD
// clients' ledgers and runs stop-world (MAIN + every shard).
//
// Inline put fast path: rt_store_reserve_slots pre-allocates a batch of
// fixed-size blocks to a client (amortizing the allocator lock across many
// small puts and letting the client pre-fault the pages once);
// rt_store_publish_slot then inserts a SEALED index entry pointing at a
// reserved block under a single shard-lock acquisition — a small put costs
// one lock round trip instead of create+seal(+protect), and two clients
// publishing land on different shards with no contention at all.
//
// Crash tolerance without a daemon (the reference recovers reader pins via
// client-disconnect handling in the store server): every attached client
// owns a slot holding its pid, a ledger of its outstanding pins, and a
// ledger of its reserved-but-unpublished slab blocks.  rt_store_reap
// (called by the raylet periodically, and by attach when slots run out)
// detects dead pids and releases both ledgers — aborting half-created
// objects, unpinning reads, and freeing reserved slots — so a crashed
// worker can never leak refcounts or arena space permanently.  (The one
// crash window that can leak is between an allocator grant and its index/
// ledger record landing — worst case one block per crashed client,
// reclaimed when the arena is torn down.)
//
// Object *payload* writes happen outside every lock (the object is
// invisible until sealed/published).  Robust mutex semantics recover any
// lock if a client dies while holding it.

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <new>

namespace {

constexpr uint64_t kMagic = 0x5254504c41534d43ULL;  // "RTPLASMC" (v3: shards)
constexpr uint64_t kAlign = 64;
constexpr uint32_t kIdLen = 16;
constexpr uint32_t kMaxClients = 128;
constexpr uint32_t kMaxPinsPerClient = 1024;
constexpr uint32_t kMaxSlabSlots = 128;  // reserved inline slots per client
constexpr uint32_t kShards = 8;          // index sub-tables (power of two)

// Object states in the index.
enum : uint32_t {
  kEmpty = 0,
  kCreated = 1,
  kSealed = 2,
  kTombstone = 3,
};

// Return codes (keep in sync with ray_tpu/_native/store.py).
enum : int {
  RT_OK = 0,
  RT_EXISTS = -1,
  RT_NOT_FOUND = -2,
  RT_NO_SPACE = -3,
  RT_ERR = -4,
  RT_NOT_SEALED = -5,
  RT_PINNED = -6,
  RT_TOO_MANY_PINS = -7,
  RT_NO_CLIENT_SLOT = -8,
};

// Entry flag bits.
constexpr uint32_t kFlagProtected = 1u;  // primary copy: LRU must not evict

struct Entry {
  uint8_t id[kIdLen];
  uint64_t offset;       // data offset from arena base
  uint64_t size;         // payload size
  uint64_t last_access;  // logical clock for LRU eviction
  uint32_t state;
  uint32_t refcnt;       // pin count; pinned objects are never evicted
  uint32_t flags;        // kFlag* bits; protected entries spill before evict
  uint32_t pad;
};

struct PinRec {
  uint8_t id[kIdLen];
  uint32_t count;
  uint32_t pad;
};

struct ClientSlot {
  uint32_t pid;      // 0 = free
  uint32_t npins;    // used prefix of pins[]
  uint32_t nslabs;   // used prefix of slab_offs[]
  uint32_t pad;
  uint64_t slab_offs[kMaxSlabSlots];  // reserved, unpublished slot blocks
  PinRec pins[kMaxPinsPerClient];
};

// One index sub-table's metadata; the Entry array itself lives in the
// shared table region (shard i owns entries [i*shard_cap, (i+1)*shard_cap)).
struct Shard {
  pthread_mutex_t mutex;
  uint64_t used;        // live + tombstone entries
  uint64_t tombstones;
  uint64_t live;
};

struct Header {
  uint64_t magic;
  uint64_t total_size;
  uint64_t clients_off;
  uint64_t table_off;
  uint64_t table_cap;   // total Entry slots across shards (power of two)
  uint64_t data_off;
  uint64_t data_size;
  uint64_t used_bytes;   // allocated bytes incl. block headers (MAIN)
  uint64_t free_head;    // offset of first free block (0 = none) (MAIN)
  uint64_t access_clock; // atomic logical clock, feeds last_access
  uint64_t num_evictions;
  pthread_mutex_t mutex;  // MAIN: allocator + clients + maintenance
  Shard shards[kShards];
};

// Every data block (free or allocated) carries a boundary-tag header and
// footer so free() can coalesce with neighbours in O(1).
struct BlockHeader {
  uint64_t size;  // total block size incl. header+footer; low bit = free flag
  uint64_t next_free;
  uint64_t prev_free;
};
constexpr uint64_t kBlockHdr = sizeof(BlockHeader);
constexpr uint64_t kBlockFtr = sizeof(uint64_t);
constexpr uint64_t kMinBlock = kBlockHdr + kBlockFtr + kAlign;

inline uint64_t block_size(uint64_t tag) { return tag & ~1ULL; }
inline bool block_free(uint64_t tag) { return tag & 1ULL; }

struct Store {
  uint8_t* base;
  uint64_t map_size;
  int fd;
  int32_t client_idx;  // this handle's slot in the client registry
  // process-local guard for THIS client's pin/slab ledger: two threads of
  // one process may hit different shard locks concurrently, but they share
  // one ClientSlot (reap only touches dead clients' slots, so cross-
  // process exclusion is unnecessary for a live ledger)
  pthread_mutex_t ledger_mu;
  Header* hdr() { return reinterpret_cast<Header*>(base); }
  ClientSlot* clients() {
    return reinterpret_cast<ClientSlot*>(base + hdr()->clients_off);
  }
  Entry* table() { return reinterpret_cast<Entry*>(base + hdr()->table_off); }
  uint64_t shard_cap() { return hdr()->table_cap / kShards; }
  Entry* shard_table(uint32_t si) { return table() + si * shard_cap(); }
  BlockHeader* block(uint64_t off) {
    return reinterpret_cast<BlockHeader*>(base + off);
  }
  uint64_t* footer(uint64_t off) {
    return reinterpret_cast<uint64_t*>(base + off + block_size(block(off)->size) -
                                       kBlockFtr);
  }
};

uint64_t round_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the 16-byte id.
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdLen; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

inline uint32_t shard_of(const uint8_t* id) {
  return (uint32_t)(hash_id(id) & (kShards - 1));
}

void lock_robust(pthread_mutex_t* m) {
  int rc = pthread_mutex_lock(m);
  if (rc == EOWNERDEAD) {
    // A client died holding the lock. Metadata mutations are small and
    // ordered; worst case is a leaked created-but-unsealed object, which
    // rt_store_reap reclaims via the dead client's ledgers.
    pthread_mutex_consistent(m);
  }
}

// MAIN lock: allocator + clients + maintenance.
class MainLock {
 public:
  explicit MainLock(Store* s) : s_(s) { lock_robust(&s_->hdr()->mutex); }
  ~MainLock() { pthread_mutex_unlock(&s_->hdr()->mutex); }

 private:
  Store* s_;
};

// One shard's index lock.  NEVER acquire MAIN while holding one of these
// (lock order is MAIN < shard).
class ShardLock {
 public:
  ShardLock(Store* s, uint32_t si) : s_(s), si_(si) {
    lock_robust(&s_->hdr()->shards[si].mutex);
  }
  ~ShardLock() { pthread_mutex_unlock(&s_->hdr()->shards[si_].mutex); }

 private:
  Store* s_;
  uint32_t si_;
};

// This client's process-local ledger lock.
class LedgerLock {
 public:
  explicit LedgerLock(Store* s) : s_(s) { pthread_mutex_lock(&s_->ledger_mu); }
  ~LedgerLock() { pthread_mutex_unlock(&s_->ledger_mu); }

 private:
  Store* s_;
};

// ---- free-list allocator (caller holds MAIN) -----------------------------

void freelist_insert(Store* s, uint64_t off) {
  Header* h = s->hdr();
  BlockHeader* b = s->block(off);
  b->size |= 1ULL;  // mark free
  *s->footer(off) = b->size;
  b->next_free = h->free_head;
  b->prev_free = 0;
  if (h->free_head) s->block(h->free_head)->prev_free = off;
  h->free_head = off;
}

void freelist_remove(Store* s, uint64_t off) {
  Header* h = s->hdr();
  BlockHeader* b = s->block(off);
  if (b->prev_free)
    s->block(b->prev_free)->next_free = b->next_free;
  else
    h->free_head = b->next_free;
  if (b->next_free) s->block(b->next_free)->prev_free = b->prev_free;
  b->size &= ~1ULL;
  *s->footer(off) = b->size;
}

// Allocate a block with at least `payload` bytes of usable space.
// Returns data offset (past the header) or 0 on failure.
uint64_t arena_alloc(Store* s, uint64_t payload) {
  Header* h = s->hdr();
  uint64_t need = round_up(payload + kBlockHdr + kBlockFtr, kAlign);
  if (need < kMinBlock) need = kMinBlock;
  uint64_t off = h->free_head;
  while (off) {
    BlockHeader* b = s->block(off);
    uint64_t bsz = block_size(b->size);
    if (bsz >= need) {
      freelist_remove(s, off);
      if (bsz - need >= kMinBlock) {
        // split: tail becomes a new free block
        uint64_t tail = off + need;
        b->size = need;
        *s->footer(off) = need;
        BlockHeader* t = s->block(tail);
        t->size = bsz - need;
        *s->footer(tail) = t->size;
        freelist_insert(s, tail);
      }
      h->used_bytes += block_size(b->size);
      return off + kBlockHdr;
    }
    off = b->next_free;
  }
  return 0;
}

void arena_free(Store* s, uint64_t data_off) {
  Header* h = s->hdr();
  uint64_t off = data_off - kBlockHdr;
  BlockHeader* b = s->block(off);
  h->used_bytes -= block_size(b->size);
  // coalesce with next block
  uint64_t next = off + block_size(b->size);
  if (next < h->data_off + h->data_size) {
    BlockHeader* nb = s->block(next);
    if (block_free(nb->size)) {
      freelist_remove(s, next);
      b->size = block_size(b->size) + block_size(nb->size);
      *s->footer(off) = b->size;
    }
  }
  // coalesce with previous block
  if (off > h->data_off) {
    uint64_t prev_tag = *reinterpret_cast<uint64_t*>(s->base + off - kBlockFtr);
    if (block_free(prev_tag)) {
      uint64_t prev = off - block_size(prev_tag);
      freelist_remove(s, prev);
      s->block(prev)->size = block_size(prev_tag) + block_size(b->size);
      *s->footer(prev) = s->block(prev)->size;
      off = prev;
      b = s->block(off);
    }
  }
  freelist_insert(s, off);
}

// ---- index (per-shard; caller holds the shard's lock) --------------------

Entry* find_entry_in(Store* s, uint32_t si, const uint8_t* id) {
  uint64_t cap = s->shard_cap();
  uint64_t mask = cap - 1;
  Entry* tab = s->shard_table(si);
  uint64_t i = (hash_id(id) >> 3) & mask;
  for (uint64_t probes = 0; probes < cap; probes++, i = (i + 1) & mask) {
    Entry* e = &tab[i];
    if (e->state == kEmpty) return nullptr;
    if (e->state != kTombstone && memcmp(e->id, id, kIdLen) == 0) return e;
  }
  return nullptr;
}

// Rebuild one shard's sub-table without tombstones (transient heap buffer;
// caller holds the shard lock).
void purge_tombstones(Store* s, uint32_t si) {
  Shard* sh = &s->hdr()->shards[si];
  uint64_t cap = s->shard_cap();
  Entry* tab = s->shard_table(si);
  Entry* snapshot = static_cast<Entry*>(malloc(cap * sizeof(Entry)));
  if (!snapshot) return;
  memcpy(snapshot, tab, cap * sizeof(Entry));
  memset(tab, 0, cap * sizeof(Entry));
  uint64_t mask = cap - 1;
  uint64_t live = 0;
  for (uint64_t i = 0; i < cap; i++) {
    Entry* e = &snapshot[i];
    if (e->state == kCreated || e->state == kSealed) {
      uint64_t j = (hash_id(e->id) >> 3) & mask;
      while (tab[j].state != kEmpty) j = (j + 1) & mask;
      tab[j] = *e;
      live++;
    }
  }
  free(snapshot);
  sh->used = live;
  sh->tombstones = 0;
}

void make_tombstone(Store* s, uint32_t si, Entry* e) {
  Shard* sh = &s->hdr()->shards[si];
  e->state = kTombstone;
  sh->tombstones++;
  sh->live--;
}

// Find a slot for inserting `id` in its shard. Returns existing entry if the
// id is live.  Caller holds the shard lock.
Entry* find_slot_in(Store* s, uint32_t si, const uint8_t* id,
                    bool* reused_tombstone) {
  uint64_t cap = s->shard_cap();
  uint64_t mask = cap - 1;
  Entry* tab = s->shard_table(si);
  uint64_t i = (hash_id(id) >> 3) & mask;
  Entry* first_tomb = nullptr;
  *reused_tombstone = false;
  for (uint64_t probes = 0; probes < cap; probes++, i = (i + 1) & mask) {
    Entry* e = &tab[i];
    if (e->state == kEmpty) {
      if (first_tomb) {
        *reused_tombstone = true;
        return first_tomb;
      }
      return e;
    }
    if (e->state == kTombstone) {
      if (!first_tomb) first_tomb = e;
    } else if (memcmp(e->id, id, kIdLen) == 0) {
      return e;  // caller checks state
    }
  }
  if (first_tomb) *reused_tombstone = true;
  return first_tomb;
}

// Make room in shard si's sub-table (3/4 load ceiling).  Caller holds the
// shard lock.  Returns true when one more insert fits.
bool ensure_shard_room(Store* s, uint32_t si) {
  Shard* sh = &s->hdr()->shards[si];
  uint64_t cap = s->shard_cap();
  if (sh->used + 1 <= (cap * 3) / 4) return true;
  if (sh->tombstones > 0) purge_tombstones(s, si);
  return sh->used + 1 <= (cap * 3) / 4;
}

// Evict least-recently-used sealed, unpinned objects until `needed_bytes`
// could plausibly be allocated.  Caller holds MAIN; shard locks are taken
// one at a time (MAIN < shard order).  Single scan: collect candidates,
// sort by last_access, evict in order.
// (ray: eviction_policy.h LRUCache analogue, done inline.)
uint64_t evict_lru(Store* s, uint64_t needed_bytes) {
  Header* h = s->hdr();
  uint64_t byte_target = needed_bytes + (needed_bytes >> 2);
  struct Cand {
    uint64_t access;
    uint64_t idx;  // global table index
  };
  Cand* cands = static_cast<Cand*>(malloc(h->table_cap * sizeof(Cand)));
  if (!cands) return 0;
  uint64_t n = 0;
  uint64_t cap = s->shard_cap();
  for (uint32_t si = 0; si < kShards; si++) {
    ShardLock lk(s, si);
    Entry* tab = s->shard_table(si);
    for (uint64_t i = 0; i < cap; i++) {
      Entry* e = &tab[i];
      if (e->state == kSealed && e->refcnt == 0 &&
          !(e->flags & kFlagProtected)) {
        cands[n].access = e->last_access;
        cands[n].idx = si * cap + i;
        n++;
      }
    }
  }
  qsort(cands, n, sizeof(Cand), [](const void* a, const void* b) {
    uint64_t aa = static_cast<const Cand*>(a)->access;
    uint64_t bb = static_cast<const Cand*>(b)->access;
    return (aa < bb) ? -1 : (aa > bb) ? 1 : 0;
  });
  uint64_t freed = 0;
  for (uint64_t i = 0; i < n && freed < byte_target; i++) {
    uint32_t si = (uint32_t)(cands[i].idx / cap);
    ShardLock lk(s, si);
    Entry* e = &s->table()[cands[i].idx];
    // re-validate: the entry may have been pinned/protected/replaced
    // between the collect pass and now
    if (e->state != kSealed || e->refcnt != 0 ||
        (e->flags & kFlagProtected)) {
      continue;
    }
    freed += e->size;
    arena_free(s, e->offset);  // MAIN held by caller
    make_tombstone(s, si, e);
    h->num_evictions++;
  }
  free(cands);
  return freed;
}

// Evict by entry count from ONE shard whose sub-table is full of live
// objects (small-object stores would otherwise free one tiny victim and
// still report NO_SPACE).  Takes MAIN internally; caller holds NO locks.
void evict_for_shard_room(Store* s, uint32_t si) {
  MainLock main(s);
  Header* h = s->hdr();
  uint64_t cap = s->shard_cap();
  struct Cand {
    uint64_t access;
    uint64_t idx;
  };
  Cand* cands = static_cast<Cand*>(malloc(cap * sizeof(Cand)));
  if (!cands) return;
  ShardLock lk(s, si);
  Entry* tab = s->shard_table(si);
  uint64_t n = 0;
  for (uint64_t i = 0; i < cap; i++) {
    Entry* e = &tab[i];
    if (e->state == kSealed && e->refcnt == 0 &&
        !(e->flags & kFlagProtected)) {
      cands[n].access = e->last_access;
      cands[n].idx = i;
      n++;
    }
  }
  qsort(cands, n, sizeof(Cand), [](const void* a, const void* b) {
    uint64_t aa = static_cast<const Cand*>(a)->access;
    uint64_t bb = static_cast<const Cand*>(b)->access;
    return (aa < bb) ? -1 : (aa > bb) ? 1 : 0;
  });
  uint64_t target = cap / 8;
  for (uint64_t i = 0; i < n && i < target; i++) {
    Entry* e = &tab[cands[i].idx];
    arena_free(s, e->offset);
    make_tombstone(s, si, e);
    h->num_evictions++;
  }
  purge_tombstones(s, si);
  free(cands);
}

// ---- client pin ledger (caller holds the LOCAL ledger lock) --------------

int ledger_add(Store* s, const uint8_t* id) {
  ClientSlot* c = &s->clients()[s->client_idx];
  for (uint32_t i = 0; i < c->npins; i++) {
    if (memcmp(c->pins[i].id, id, kIdLen) == 0) {
      c->pins[i].count++;
      return RT_OK;
    }
  }
  if (c->npins >= kMaxPinsPerClient) return RT_TOO_MANY_PINS;
  memcpy(c->pins[c->npins].id, id, kIdLen);
  c->pins[c->npins].count = 1;
  c->npins++;
  return RT_OK;
}

void ledger_remove(Store* s, const uint8_t* id) {
  ClientSlot* c = &s->clients()[s->client_idx];
  for (uint32_t i = 0; i < c->npins; i++) {
    if (memcmp(c->pins[i].id, id, kIdLen) == 0) {
      if (--c->pins[i].count == 0) {
        c->pins[i] = c->pins[c->npins - 1];  // swap-remove
        c->npins--;
      }
      return;
    }
  }
}

// Remove one reserved slab offset from this client's ledger.  Returns true
// when the offset was present.
bool slab_ledger_remove(ClientSlot* c, uint64_t off) {
  for (uint32_t i = 0; i < c->nslabs; i++) {
    if (c->slab_offs[i] == off) {
      c->slab_offs[i] = c->slab_offs[c->nslabs - 1];
      c->nslabs--;
      return true;
    }
  }
  return false;
}

// Release every pin + reserved slot a client slot holds: unpin sealed
// reads, abort half-created objects, free unpublished slab blocks.
// Called on detach and on reaping a dead client — caller holds MAIN and
// EVERY shard lock (stop-world), so plain index access is safe.
void release_client_state(Store* s, ClientSlot* c) {
  for (uint32_t i = 0; i < c->npins; i++) {
    uint32_t si = shard_of(c->pins[i].id);
    Entry* e = find_entry_in(s, si, c->pins[i].id);
    if (!e) continue;
    if (e->state == kCreated) {
      // creator died/left before sealing: reclaim the space
      arena_free(s, e->offset);
      make_tombstone(s, si, e);
    } else {
      uint32_t n = c->pins[i].count;
      e->refcnt = (e->refcnt > n) ? e->refcnt - n : 0;
    }
  }
  c->npins = 0;
  for (uint32_t i = 0; i < c->nslabs; i++) {
    arena_free(s, c->slab_offs[i]);
  }
  c->nslabs = 0;
  c->pid = 0;
}

// Reap clients whose pid no longer exists. Caller holds MAIN + all shards.
int reap_dead_clients(Store* s) {
  int reaped = 0;
  ClientSlot* slots = s->clients();
  for (uint32_t i = 0; i < kMaxClients; i++) {
    ClientSlot* c = &slots[i];
    if (c->pid != 0 && kill((pid_t)c->pid, 0) != 0 && errno == ESRCH) {
      release_client_state(s, c);
      reaped++;
    }
  }
  return reaped;
}

// Stop-world RAII for maintenance ops touching every shard: MAIN first,
// then shards in ascending order (the one place multiple shard locks are
// held at once).
class StopWorld {
 public:
  explicit StopWorld(Store* s) : s_(s) {
    lock_robust(&s_->hdr()->mutex);
    for (uint32_t i = 0; i < kShards; i++) {
      lock_robust(&s_->hdr()->shards[i].mutex);
    }
  }
  ~StopWorld() {
    for (uint32_t i = kShards; i > 0; i--) {
      pthread_mutex_unlock(&s_->hdr()->shards[i - 1].mutex);
    }
    pthread_mutex_unlock(&s_->hdr()->mutex);
  }

 private:
  Store* s_;
};

int32_t claim_client_slot(Store* s) {
  // caller holds MAIN + all shards (reap on pass 2 needs them)
  ClientSlot* slots = s->clients();
  for (int pass = 0; pass < 2; pass++) {
    for (uint32_t i = 0; i < kMaxClients; i++) {
      if (slots[i].pid == 0) {
        slots[i].pid = (uint32_t)getpid();
        slots[i].npins = 0;
        slots[i].nslabs = 0;
        return (int32_t)i;
      }
    }
    if (pass == 0 && reap_dead_clients(s) == 0) break;
  }
  return -1;
}

void init_robust_mutex(pthread_mutex_t* m) {
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(m, &attr);
  pthread_mutexattr_destroy(&attr);
}

Store* new_store(void* base, uint64_t size, int fd) {
  Store* s = new Store{reinterpret_cast<uint8_t*>(base), size, fd, -1, {}};
  pthread_mutex_init(&s->ledger_mu, nullptr);
  return s;
}

}  // namespace

extern "C" {

// Per-client ledger capacity (shared by pins and unsealed creates) so
// Python callers can gauge headroom without duplicating the constant.
uint64_t rt_store_max_pins() { return kMaxPinsPerClient; }

// Per-client reserved-slot ledger capacity (the inline-put slab).
uint64_t rt_store_max_slab_slots() { return kMaxSlabSlots; }

// Minimum arena size such that metadata plus a useful data region fit.
uint64_t rt_store_min_size() {
  uint64_t meta = round_up(sizeof(Header), kAlign) +
                  round_up(kMaxClients * sizeof(ClientSlot), kAlign) +
                  4096 * sizeof(Entry);
  return round_up(meta, kAlign) + (16ULL << 20);  // + 16MB data floor
}

// Create a new arena file of `size` bytes at `path` and initialize it.
// Returns an opaque handle or null.
void* rt_store_create(const char* path, uint64_t size) {
  if (size < rt_store_min_size()) return nullptr;
  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)size) != 0) {
    close(fd);
    unlink(path);
    return nullptr;
  }
  void* base = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    unlink(path);
    return nullptr;
  }
  Store* s = new_store(base, size, fd);
  Header* h = s->hdr();
  memset(h, 0, sizeof(Header));
  // Size the index at one slot per 4KB of arena, >= 4096 slots, power of 2
  // (shard sub-tables are table_cap/kShards each, also powers of two).
  uint64_t cap = 4096;
  while (cap < size / 4096) cap <<= 1;
  h->total_size = size;
  h->clients_off = round_up(sizeof(Header), kAlign);
  h->table_off =
      round_up(h->clients_off + kMaxClients * sizeof(ClientSlot), kAlign);
  h->table_cap = cap;
  h->data_off = round_up(h->table_off + cap * sizeof(Entry), kAlign);
  if (size <= h->data_off + kMinBlock) {  // index for this size doesn't fit
    munmap(base, size);
    close(fd);
    unlink(path);
    delete s;
    return nullptr;
  }
  h->data_size = size - h->data_off;
  memset(s->clients(), 0, kMaxClients * sizeof(ClientSlot));
  memset(s->table(), 0, cap * sizeof(Entry));

  init_robust_mutex(&h->mutex);
  for (uint32_t i = 0; i < kShards; i++) {
    init_robust_mutex(&h->shards[i].mutex);
    h->shards[i].used = h->shards[i].tombstones = h->shards[i].live = 0;
  }

  // One giant free block spanning the data region.
  BlockHeader* b = s->block(h->data_off);
  b->size = h->data_size;
  *s->footer(h->data_off) = b->size;
  b->next_free = b->prev_free = 0;
  freelist_insert(s, h->data_off);

  s->client_idx = claim_client_slot(s);  // fresh arena: no lock contention
  // Publish the magic LAST so a concurrent attach never sees a half-built
  // arena (attach fails cleanly until initialization completes).
  __atomic_store_n(&h->magic, kMagic, __ATOMIC_RELEASE);
  return s;
}

// Attach to an existing arena. Returns handle or null.
void* rt_store_attach(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Store* s = new_store(base, (uint64_t)st.st_size, fd);
  if (s->hdr()->magic != kMagic) {
    munmap(base, st.st_size);
    close(fd);
    delete s;
    return nullptr;
  }
  {
    StopWorld lock(s);
    s->client_idx = claim_client_slot(s);
  }
  if (s->client_idx < 0) {
    munmap(base, st.st_size);
    close(fd);
    delete s;
    return nullptr;
  }
  return s;
}

void rt_store_detach(void* handle) {
  Store* s = reinterpret_cast<Store*>(handle);
  if (s->client_idx >= 0) {
    StopWorld lock(s);
    release_client_state(s, &s->clients()[s->client_idx]);
  }
  munmap(s->base, s->map_size);
  close(s->fd);
  pthread_mutex_destroy(&s->ledger_mu);
  delete s;
}

// Allocate space for an object. On success writes the payload offset (from
// arena base) to *out_offset; the caller memcpys payload there then seals.
// If the arena is full, evicts LRU sealed unpinned objects to make room.
int rt_store_create_object(void* handle, const uint8_t* id, uint64_t size,
                           uint64_t* out_offset) {
  Store* s = reinterpret_cast<Store*>(handle);
  if (s->client_idx < 0) return RT_NO_CLIENT_SLOT;
  uint32_t si = shard_of(id);
  // Pass 1 (shard only): duplicate check + index-room check.  A duplicate
  // create racing us between this check and the insert below is caught
  // again at insert time.
  bool room;
  {
    ShardLock lk(s, si);
    if (find_entry_in(s, si, id)) return RT_EXISTS;
    room = ensure_shard_room(s, si);
  }
  if (!room) {
    // sub-table genuinely full of live objects: evict by entry count
    evict_for_shard_room(s, si);
    ShardLock lk(s, si);
    if (!ensure_shard_room(s, si)) return RT_NO_SPACE;
  }
  // Pass 2 (MAIN): allocate payload space, evicting LRU bytes on pressure.
  uint64_t off;
  {
    MainLock main(s);
    off = arena_alloc(s, size);
    if (!off) {
      evict_lru(s, size);
      off = arena_alloc(s, size);
      if (!off) return RT_NO_SPACE;
    }
  }
  // Creator pin BEFORE the insert: a crash after the entry exists must be
  // reapable through the pin ledger (reap aborts kCreated entries).
  bool pinned;
  {
    LedgerLock led(s);
    pinned = ledger_add(s, id) == RT_OK;
  }
  if (!pinned) {
    // unwind OUTSIDE the ledger scope: taking MAIN under ledger_mu
    // inverts the MAIN < shard < ledger order and closes a deadlock
    // cycle against publish_slot's shard->ledger hold (rtlint RT304)
    MainLock main(s);
    arena_free(s, off);
    return RT_TOO_MANY_PINS;
  }
  // Pass 3 (shard): insert.  A lost race (concurrent creator of the same
  // id, or the shard filling meanwhile) unwinds: drop the creator pin,
  // release the shard lock, THEN free the block (MAIN may not be taken
  // while a shard lock is held).
  int lose_rc = RT_OK;
  {
    ShardLock lk(s, si);
    bool reused_tomb = false;
    Entry* e = find_slot_in(s, si, id, &reused_tomb);
    if (e && (e->state == kCreated || e->state == kSealed)) {
      lose_rc = RT_EXISTS;
    } else if (!e) {
      lose_rc = RT_NO_SPACE;
    } else {
      Shard* sh = &s->hdr()->shards[si];
      if (e->state == kEmpty)
        sh->used++;
      else if (reused_tomb)
        sh->tombstones--;
      memcpy(e->id, id, kIdLen);
      e->offset = off;
      e->size = size;
      e->state = kCreated;
      e->refcnt = 1;  // creator holds a pin until seal/abort
      e->flags = 0;   // a reused tombstone may carry stale flag bits
      e->last_access =
          __atomic_add_fetch(&s->hdr()->access_clock, 1, __ATOMIC_RELAXED);
      sh->live++;
      *out_offset = off;
      return RT_OK;
    }
  }
  {
    LedgerLock led(s);
    ledger_remove(s, id);
  }
  MainLock main(s);
  arena_free(s, off);
  return lose_rc;
}

// Seal with an optional atomic protect: state flips to kSealed and the
// primary-copy flag lands under ONE shard-lock acquisition, so there is no
// window where a sealed primary is LRU-evictable (v2 needed separate
// protect + seal calls, two lock round trips, protect-before-seal ordered
// by the caller).
int rt_store_seal2(void* handle, const uint8_t* id, int protect) {
  Store* s = reinterpret_cast<Store*>(handle);
  uint32_t si = shard_of(id);
  {
    ShardLock lk(s, si);
    Entry* e = find_entry_in(s, si, id);
    if (!e) return RT_NOT_FOUND;
    if (e->state != kCreated) return RT_ERR;
    if (protect) e->flags |= kFlagProtected;
    e->state = kSealed;
    if (e->refcnt > 0) e->refcnt--;  // drop creator pin
  }
  LedgerLock led(s);
  ledger_remove(s, id);
  return RT_OK;
}

int rt_store_seal(void* handle, const uint8_t* id) {
  return rt_store_seal2(handle, id, 0);
}

// Abort an in-progress creation (e.g. serialization failed mid-write).
int rt_store_abort(void* handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  uint32_t si = shard_of(id);
  uint64_t off;
  {
    ShardLock lk(s, si);
    Entry* e = find_entry_in(s, si, id);
    if (!e) return RT_NOT_FOUND;
    if (e->state != kCreated) return RT_ERR;
    off = e->offset;
    make_tombstone(s, si, e);
  }
  {
    LedgerLock led(s);
    ledger_remove(s, id);
  }
  MainLock main(s);
  arena_free(s, off);
  return RT_OK;
}

// ---- inline-put slot slab -------------------------------------------------

// Reserve up to `n` fixed-size blocks for this client's inline-put slab.
// One MAIN acquisition amortizes the allocator across the whole batch; the
// client pre-faults the returned ranges once, and each small put then costs
// a single shard-lock publish.  Reserved blocks are recorded in the
// client's slab ledger so reap/detach reclaims them.  Returns the number
// actually reserved (0 under arena pressure — callers fall back to the
// create path, which can evict; reservation itself never evicts).
uint64_t rt_store_reserve_slots(void* handle, uint64_t slot_size, uint64_t n,
                                uint64_t* out_offsets) {
  Store* s = reinterpret_cast<Store*>(handle);
  if (s->client_idx < 0) return 0;
  ClientSlot* c = &s->clients()[s->client_idx];
  uint64_t got = 0;
  {
    LedgerLock led(s);
    uint64_t room = kMaxSlabSlots - c->nslabs;
    if (n > room) n = room;
  }
  if (n == 0) return 0;
  {
    MainLock main(s);
    for (uint64_t i = 0; i < n; i++) {
      uint64_t off = arena_alloc(s, slot_size);
      if (!off) break;
      out_offsets[got++] = off;
    }
  }
  {
    LedgerLock led(s);
    for (uint64_t i = 0; i < got && c->nslabs < kMaxSlabSlots; i++) {
      c->slab_offs[c->nslabs++] = out_offsets[i];
    }
  }
  return got;
}

// Return unused reserved slots to the general allocator (slab shrink /
// close-time cleanup).
void rt_store_release_slots(void* handle, const uint64_t* offsets,
                            uint64_t n) {
  Store* s = reinterpret_cast<Store*>(handle);
  if (s->client_idx < 0) return;
  ClientSlot* c = &s->clients()[s->client_idx];
  {
    LedgerLock led(s);
    for (uint64_t i = 0; i < n; i++) slab_ledger_remove(c, offsets[i]);
  }
  MainLock main(s);
  for (uint64_t i = 0; i < n; i++) arena_free(s, offsets[i]);
}

// Publish a payload written into a reserved slot as a SEALED object: one
// shard-lock acquisition, no allocator traffic, no creator-pin round trip.
// `size` is the actual payload length (<= the reserved slot size; the
// block's boundary tags keep the true block size for the eventual free).
// On RT_EXISTS / RT_NO_SPACE the slot stays in the client's slab ledger
// for reuse.
int rt_store_publish_slot(void* handle, const uint8_t* id, uint64_t offset,
                          uint64_t size, int protect) {
  Store* s = reinterpret_cast<Store*>(handle);
  if (s->client_idx < 0) return RT_NO_CLIENT_SLOT;
  ClientSlot* c = &s->clients()[s->client_idx];
  uint32_t si = shard_of(id);
  // Consume the slab ledger entry FIRST: once the sealed entry is visible,
  // the block belongs to the index (freed via delete/evict), and a crash
  // must never leave it in BOTH ledgers (reap would free a live entry's
  // block).  A crash in the window after this and before the insert leaks
  // the block — bounded, and reclaimed at arena teardown.
  {
    LedgerLock led(s);
    if (!slab_ledger_remove(c, offset)) return RT_ERR;  // not ours
  }
  {
    ShardLock lk(s, si);
    if (!ensure_shard_room(s, si)) {
      LedgerLock led(s);
      if (c->nslabs < kMaxSlabSlots) c->slab_offs[c->nslabs++] = offset;
      return RT_NO_SPACE;
    }
    bool reused_tomb = false;
    Entry* e = find_slot_in(s, si, id, &reused_tomb);
    if (e && (e->state == kCreated || e->state == kSealed)) {
      LedgerLock led(s);
      if (c->nslabs < kMaxSlabSlots) c->slab_offs[c->nslabs++] = offset;
      return RT_EXISTS;
    }
    if (!e) {
      LedgerLock led(s);
      if (c->nslabs < kMaxSlabSlots) c->slab_offs[c->nslabs++] = offset;
      return RT_NO_SPACE;
    }
    Shard* sh = &s->hdr()->shards[si];
    if (e->state == kEmpty)
      sh->used++;
    else if (reused_tomb)
      sh->tombstones--;
    memcpy(e->id, id, kIdLen);
    e->offset = offset;
    e->size = size;
    e->state = kSealed;
    e->refcnt = 0;
    e->flags = protect ? kFlagProtected : 0;
    e->last_access =
        __atomic_add_fetch(&s->hdr()->access_clock, 1, __ATOMIC_RELAXED);
    sh->live++;
  }
  return RT_OK;
}

// Look up a sealed object; pins it (caller must rt_store_unpin).
int rt_store_get(void* handle, const uint8_t* id, uint64_t* out_offset,
                 uint64_t* out_size) {
  Store* s = reinterpret_cast<Store*>(handle);
  if (s->client_idx < 0) return RT_NO_CLIENT_SLOT;
  uint32_t si = shard_of(id);
  // ledger first: a crash between ledger_add and refcnt++ leaves a pin
  // record for an un-bumped refcnt, which release_client_state clamps
  ShardLock lk(s, si);
  Entry* e = find_entry_in(s, si, id);
  if (!e) return RT_NOT_FOUND;
  if (e->state != kSealed) return RT_NOT_SEALED;
  {
    LedgerLock led(s);
    int rc = ledger_add(s, id);
    if (rc != RT_OK) return rc;
  }
  e->refcnt++;
  e->last_access =
      __atomic_add_fetch(&s->hdr()->access_clock, 1, __ATOMIC_RELAXED);
  *out_offset = e->offset;
  *out_size = e->size;
  return RT_OK;
}

int rt_store_contains(void* handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  uint32_t si = shard_of(id);
  ShardLock lk(s, si);
  Entry* e = find_entry_in(s, si, id);
  return (e && e->state == kSealed) ? 1 : 0;
}

int rt_store_unpin(void* handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  uint32_t si = shard_of(id);
  {
    ShardLock lk(s, si);
    Entry* e = find_entry_in(s, si, id);
    if (!e) return RT_NOT_FOUND;
    if (e->refcnt > 0) e->refcnt--;
  }
  LedgerLock led(s);
  ledger_remove(s, id);
  return RT_OK;
}

// Delete a sealed object (refuses if pinned by readers).
int rt_store_delete(void* handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  uint32_t si = shard_of(id);
  uint64_t off;
  {
    ShardLock lk(s, si);
    Entry* e = find_entry_in(s, si, id);
    if (!e || e->state == kTombstone) return RT_NOT_FOUND;
    if (e->refcnt > 0) return RT_PINNED;
    off = e->offset;
    make_tombstone(s, si, e);
  }
  MainLock main(s);
  arena_free(s, off);
  return RT_OK;
}

// Release pins of dead clients; returns number of clients reaped.
int rt_store_reap(void* handle) {
  Store* s = reinterpret_cast<Store*>(handle);
  StopWorld lock(s);
  return reap_dead_clients(s);
}

void rt_store_stats(void* handle, uint64_t* capacity, uint64_t* used,
                    uint64_t* objects, uint64_t* evictions) {
  Store* s = reinterpret_cast<Store*>(handle);
  MainLock main(s);
  Header* h = s->hdr();
  uint64_t live = 0;
  for (uint32_t i = 0; i < kShards; i++) {
    // relaxed read: live is mutated under the shard lock; stats tolerate
    // a torn-by-one snapshot (they always did — the old single lock only
    // ordered against writers, not against the world changing after)
    live += __atomic_load_n(&h->shards[i].live, __ATOMIC_RELAXED);
  }
  *capacity = h->data_size;
  *used = h->used_bytes;
  *objects = live;
  *evictions = h->num_evictions;
}

// Set / clear the protected (primary-copy) bit.  Protected entries are
// skipped by LRU eviction; the node's spill manager writes them to disk
// and clears the bit (or deletes them) when the arena fills.
int rt_store_protect(void* handle, const uint8_t* id, int on) {
  Store* s = reinterpret_cast<Store*>(handle);
  uint32_t si = shard_of(id);
  ShardLock lk(s, si);
  Entry* e = find_entry_in(s, si, id);
  if (!e || e->state == kTombstone || e->state == kEmpty) return RT_NOT_FOUND;
  if (on)
    e->flags |= kFlagProtected;
  else
    e->flags &= ~kFlagProtected;
  return RT_OK;
}

// List spill candidates: sealed, unpinned, protected entries in LRU order
// (least recently used first).  Writes up to `max_n` ids (16 bytes each)
// into out_ids and their payload sizes into out_sizes; returns the count.
uint64_t rt_store_list_spillable(void* handle, uint8_t* out_ids,
                                 uint64_t* out_sizes, uint64_t max_n) {
  Store* s = reinterpret_cast<Store*>(handle);
  Header* h = s->hdr();
  // id/size are captured here, under the shard lock — a concurrent
  // create in the same shard may rewrite the sub-table (tombstone
  // purge), so entry pointers must not be dereferenced after the lock
  // is dropped.
  struct Cand {
    uint64_t access;
    uint64_t size;
    uint8_t id[kIdLen];
  };
  Cand* cands = static_cast<Cand*>(malloc(h->table_cap * sizeof(Cand)));
  if (!cands) return 0;
  uint64_t n = 0;
  uint64_t cap = s->shard_cap();
  for (uint32_t si = 0; si < kShards; si++) {
    ShardLock lk(s, si);
    Entry* tab = s->shard_table(si);
    for (uint64_t i = 0; i < cap; i++) {
      Entry* e = &tab[i];
      if (e->state == kSealed && e->refcnt == 0 &&
          (e->flags & kFlagProtected)) {
        cands[n].access = e->last_access;
        cands[n].size = e->size;
        memcpy(cands[n].id, e->id, kIdLen);
        n++;
      }
    }
  }
  qsort(cands, n, sizeof(Cand), [](const void* a, const void* b) {
    uint64_t aa = static_cast<const Cand*>(a)->access;
    uint64_t bb = static_cast<const Cand*>(b)->access;
    return (aa < bb) ? -1 : (aa > bb) ? 1 : 0;
  });
  uint64_t count = n < max_n ? n : max_n;
  for (uint64_t i = 0; i < count; i++) {
    memcpy(out_ids + i * kIdLen, cands[i].id, kIdLen);
    out_sizes[i] = cands[i].size;
  }
  free(cands);
  return count;
}

// Base address of the mapping in this process (for zero-copy memoryviews).
void* rt_store_base(void* handle) {
  return reinterpret_cast<Store*>(handle)->base;
}

uint64_t rt_store_map_size(void* handle) {
  return reinterpret_cast<Store*>(handle)->map_size;
}

}  // extern "C"
