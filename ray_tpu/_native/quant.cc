// Fused block-quantization kernels for the collective wire codecs
// (ray_tpu/util/collective/quantize.py).
//
// The numpy reference implementation is 5+ full-size memory passes per
// encode (max/min reductions, scaled multiply, rint, cast-copy); on a
// CPU-bound host those passes compete with the transport's memcpys for
// the same cores and dominate the quantized ring's wall clock.  These
// kernels fuse each direction into the minimum number of passes:
//
//   int8 encode  = 1 read pass (absmax + finite check) +
//                  1 read/write pass (scale, round-half-even, cast)
//   int8 decode  = 1 pass (cast + scale), optionally fused with the
//                  ring reduce-scatter accumulation (decode_add)
//   bf16 encode  = 1 pass (round-to-nearest-even bit math + finite)
//   bf16 decode  = 1 pass (shift), optionally fused with accumulate
//
// Numerics are kept bit-identical to the numpy path: float32 ops in
// the same order (scale = absmax/127, q = roundeven(a * (127/absmax)),
// out = q * scale), compiled with -ffp-contract=off so no FMA
// contraction sneaks in — a fleet mixing native and numpy ranks must
// produce identical wire bytes and identical decodes.
//
// Non-finite input returns 1 (the Python layer raises); note NaN never
// survives a `v > amax` comparison, so the finite check is an explicit
// `!(v <= FLT_MAX)` per element, which catches NaN and +/-inf alike.

#include <cstdint>
#include <cmath>
#include <algorithm>

namespace {
constexpr float kFltMax = 3.402823466e38f;
}

extern "C" {

int rt_quant_int8_encode(const float* a, int64_t n, int64_t block,
                         float* scales, int8_t* q) {
    if (n <= 0) return 0;
    int64_t nb = (n + block - 1) / block;
    for (int64_t b = 0; b < nb; ++b) {
        const int64_t lo = b * block;
        const int64_t hi = std::min(n, lo + block);
        float amax = 0.0f;
        int bad = 0;  // branchless accumulation keeps the loop SIMD
        for (int64_t i = lo; i < hi; ++i) {
            float v = std::fabs(a[i]);
            bad |= !(v <= kFltMax);  // catches NaN (compare false) + inf
            amax = v > amax ? v : amax;
        }
        if (bad) return 1;
        const float scale = amax / 127.0f;
        const float recip = amax > 0.0f ? 127.0f / amax : 0.0f;
        scales[b] = scale;
        for (int64_t i = lo; i < hi; ++i) {
            // round-half-even (lrintf under the default FE_TONEAREST ==
            // np.rint; vectorizes to cvtps2dq); |a*recip| <= 127(1+eps)
            q[i] = (int8_t)lrintf(a[i] * recip);
        }
    }
    return 0;
}

void rt_quant_int8_decode(const float* scales, const int8_t* q,
                          int64_t n, int64_t block, float* out) {
    if (n <= 0) return;
    int64_t nb = (n + block - 1) / block;
    for (int64_t b = 0; b < nb; ++b) {
        const int64_t lo = b * block;
        const int64_t hi = std::min(n, lo + block);
        const float s = scales[b];
        for (int64_t i = lo; i < hi; ++i) {
            out[i] = (float)q[i] * s;
        }
    }
}

void rt_quant_int8_decode_add(const float* scales, const int8_t* q,
                              int64_t n, int64_t block, float* acc) {
    if (n <= 0) return;
    int64_t nb = (n + block - 1) / block;
    for (int64_t b = 0; b < nb; ++b) {
        const int64_t lo = b * block;
        const int64_t hi = std::min(n, lo + block);
        const float s = scales[b];
        for (int64_t i = lo; i < hi; ++i) {
            acc[i] += (float)q[i] * s;
        }
    }
}

int rt_quant_bf16_encode(const uint32_t* bits, int64_t n, uint16_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        const uint32_t b = bits[i];
        if ((b & 0x7f800000u) == 0x7f800000u) return 1;  // NaN/inf
        out[i] = (uint16_t)((b + 0x7fffu + ((b >> 16) & 1u)) >> 16);
    }
    return 0;
}

void rt_quant_bf16_decode(const uint16_t* in, int64_t n, uint32_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        out[i] = ((uint32_t)in[i]) << 16;
    }
}

void rt_quant_bf16_decode_add(const uint16_t* in, int64_t n, float* acc) {
    for (int64_t i = 0; i < n; ++i) {
        union { uint32_t u; float f; } v;
        v.u = ((uint32_t)in[i]) << 16;
        acc[i] += v.f;
    }
}

}  // extern "C"
