"""ctypes loader for the fused quantization kernels (quant.cc).

Same build discipline as ``_native/store.py``: compile the bundled
source on first use when the .so is missing or stale (flock-guarded so
concurrent workers don't race), force-rebuild when dlopen rejects a
binary from a foreign toolchain.  ``lib()`` returns None when no
compiler is available — the numpy reference path in
``util/collective/quantize.py`` is always there as the fallback, and
both produce bit-identical wire bytes (quant.cc builds with
-ffp-contract=off for exactly that reason).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "quant.cc")
_SO = os.path.join(_DIR, "libquant.so")

_lib = None
_lib_lock = threading.Lock()


def _build(force: bool = False) -> None:
    def fresh():
        return (
            not force
            and os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
        )

    if fresh():
        return
    with open(_SO + ".lock", "w") as lf:
        import fcntl

        fcntl.flock(lf, fcntl.LOCK_EX)
        if fresh():
            return
        tmp = _SO + ".tmp"
        # -march=native is safe here: the .so is always compiled on the
        # host that dlopens it (build-at-first-use, foreign binaries are
        # rebuilt), and it unlocks the wide-SIMD quant loops.  Retry
        # without it for exotic toolchains that reject the flag.
        base = ["g++", "-O3", "-ffp-contract=off", "-fno-math-errno",
                "-fPIC", "-shared", "-std=c++17", _SRC, "-o", tmp]
        try:
            subprocess.run(
                base[:1] + ["-march=native"] + base[1:],
                check=True, capture_output=True,
            )
        except subprocess.CalledProcessError:
            subprocess.run(base, check=True, capture_output=True)
        os.replace(tmp, _SO)


def _bind(lib) -> None:
    i64, fp, i8p, u16p, u32p = (
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int8),
        ctypes.POINTER(ctypes.c_uint16),
        ctypes.POINTER(ctypes.c_uint32),
    )
    lib.rt_quant_int8_encode.restype = ctypes.c_int
    lib.rt_quant_int8_encode.argtypes = [fp, i64, i64, fp, i8p]
    lib.rt_quant_int8_decode.restype = None
    lib.rt_quant_int8_decode.argtypes = [fp, i8p, i64, i64, fp]
    lib.rt_quant_int8_decode_add.restype = None
    lib.rt_quant_int8_decode_add.argtypes = [fp, i8p, i64, i64, fp]
    lib.rt_quant_bf16_encode.restype = ctypes.c_int
    lib.rt_quant_bf16_encode.argtypes = [u32p, i64, u16p]
    lib.rt_quant_bf16_decode.restype = None
    lib.rt_quant_bf16_decode.argtypes = [u16p, i64, u32p]
    lib.rt_quant_bf16_decode_add.restype = None
    lib.rt_quant_bf16_decode_add.argtypes = [u16p, i64, fp]


def lib():
    """The loaded kernel library, or None when it cannot be built
    (no compiler in the image): callers fall back to numpy."""
    global _lib
    if _lib is False:
        return None
    if _lib is None:
        with _lib_lock:
            if _lib is None:
                try:
                    _build()
                    try:
                        loaded = ctypes.CDLL(_SO)
                    except OSError:
                        _build(force=True)  # foreign-toolchain binary
                        loaded = ctypes.CDLL(_SO)
                    _bind(loaded)
                    _lib = loaded
                except Exception:
                    _lib = False
                    return None
    return _lib or None
