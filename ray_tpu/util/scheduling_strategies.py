"""Scheduling strategy objects passed as ``scheduling_strategy=`` options.

Role-equivalent of ray: python/ray/util/scheduling_strategies.py
(PlacementGroupSchedulingStrategy:15, NodeAffinitySchedulingStrategy:41).
Each strategy lowers to a plain dict shipped with the lease request; the
GCS scheduler interprets it (core/gcs.py Scheduler.pick_node and
_request_pg_lease).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

if TYPE_CHECKING:
    from ray_tpu.util.placement_group import PlacementGroup


@dataclass
class PlacementGroupSchedulingStrategy:
    """Run the task/actor inside a placement-group bundle.

    ``placement_group_bundle_index=-1`` means any bundle with room.
    """

    placement_group: "PlacementGroup"
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False

    def to_dict(self) -> dict:
        return {
            "type": "placement_group",
            "pg_id": self.placement_group.id.hex(),
            "bundle_index": self.placement_group_bundle_index,
        }


@dataclass
class NodeAffinitySchedulingStrategy:
    """Pin to a node by id; ``soft=True`` allows fallback elsewhere."""

    node_id: str
    soft: bool = False

    def to_dict(self) -> dict:
        return {"type": "node_affinity", "node_id": self.node_id, "soft": self.soft}


@dataclass
class SpreadSchedulingStrategy:
    """Prefer the least-utilized node (ray: "SPREAD" string strategy)."""

    def to_dict(self) -> dict:
        return {"type": "spread"}
