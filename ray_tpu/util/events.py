"""Structured cluster events.

Role-equivalent of ray: src/ray/util/event.h:41 (RAY_EVENT macro) +
dashboard/modules/event/ — collapsed to a bounded GCS-side log.  Core
transitions (node death, actor restart, OOM kills) record
automatically; applications report their own:

    from ray_tpu.util import events
    events.report("WARNING", "ingest", "falling behind", lag_s=4.2)
    events.list_events(severity="ERROR")
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR", "FATAL")


def report(severity: str, source: str, message: str, **fields) -> None:
    """Record one structured event in the cluster event log."""
    if severity not in SEVERITIES:
        raise ValueError(f"severity must be one of {SEVERITIES}")
    from ray_tpu.core.runtime import get_runtime

    rt = get_runtime()
    rt._run(rt.gcs.call("report_event", {
        "severity": severity,
        "source": source,
        "message": message,
        "fields": fields,
    }))


def list_events(severity: Optional[str] = None,
                limit: int = 500) -> List[Dict[str, Any]]:
    from ray_tpu.core.runtime import get_runtime

    rt = get_runtime()
    return rt._run(rt.gcs.call("list_events", {
        "severity": severity,
        "limit": limit,
    }))
