"""Distributed tracing: spans around task/actor submit + execute with
W3C trace context propagated in the TaskSpec.

Role-equivalent of ray: python/ray/util/tracing/tracing_helper.py:34
(_OpenTelemetryProxy + the submit/execute span wrappers, context carried
in TaskOptions["_ray_trace_ctx"]).  Design differences, TPU-image
reality: the OpenTelemetry *API* is available but no SDK is baked in, so
spans are recorded by a built-in lightweight tracer (W3C-compatible
trace/span ids, bounded in-process ring + optional GCS event export) and
BRIDGED to OpenTelemetry when an application has installed a real
TracerProvider — `pip install opentelemetry-sdk` + set_tracer_provider
and ray_tpu spans appear in your OTel backend with no further wiring.

Tracing is off by default (zero overhead on the hot paths: one module
flag check).  Enable with ``ray_tpu.util.tracing.enable()`` in the
driver or ``RT_TRACING_ENABLED=1`` cluster-wide (workers inherit env).
"""

from __future__ import annotations

import contextvars
import os
import secrets
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

_enabled: Optional[bool] = None  # tri-state: None = read env on first use
_SPANS: deque = deque(maxlen=4096)  # newest-last ring of finished spans
_LOCK = threading.Lock()

#: current span context: (trace_id_hex32, span_id_hex16) or None
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "rt_trace_ctx", default=None
)

CARRIER_KEY = "traceparent"  # W3C trace context header


def enable() -> None:
    global _enabled
    _enabled = True
    os.environ["RT_TRACING_ENABLED"] = "1"  # workers spawned later inherit


def disable() -> None:
    global _enabled
    _enabled = False
    # mirror enable(): workers spawned from now on must not inherit a
    # stale flag and keep exporting span events forever
    os.environ.pop("RT_TRACING_ENABLED", None)


def enabled() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get("RT_TRACING_ENABLED", "") in (
            "1", "true", "True",
        )
    return _enabled


# -- context propagation (W3C traceparent) ---------------------------------


def inject() -> Optional[Dict[str, str]]:
    """Carrier dict for the current trace context, to ride a TaskSpec.
    Starts a fresh trace when none is active (every task belongs to some
    trace once tracing is on)."""
    cur = _CURRENT.get()
    if cur is None:
        cur = (secrets.token_hex(16), secrets.token_hex(8))
    return {CARRIER_KEY: f"00-{cur[0]}-{cur[1]}-01"}


def _extract(carrier: Optional[Dict[str, str]]):
    if not carrier:
        return None
    try:
        _ver, trace_id, span_id, _flags = carrier[CARRIER_KEY].split("-")
        return (trace_id, span_id)
    except (KeyError, ValueError):
        return None


# -- spans -----------------------------------------------------------------


class Span:
    """One span; context-manager.  Records into the process-local ring
    and mirrors to an OpenTelemetry tracer when a real provider is
    installed."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start", "end",
        "attrs", "_token", "_otel_span", "_otel_token",
    )

    def __init__(self, name: str, parent, attrs: Dict[str, Any]):
        self.name = name
        self.trace_id = parent[0] if parent else secrets.token_hex(16)
        self.span_id = secrets.token_hex(8)
        self.parent_id = parent[1] if parent else None
        self.start = time.time()
        self.end = None
        self.attrs = attrs
        self._token = None
        self._otel_span = None
        self._otel_token = None

    def __enter__(self):
        self._token = _CURRENT.set((self.trace_id, self.span_id))
        otel = _otel_tracer()
        if otel is not None:
            try:
                from opentelemetry import context as otel_ctx
                from opentelemetry import trace as otel_trace
                from opentelemetry.trace.propagation.tracecontext import (
                    TraceContextTextMapPropagator,
                )

                parent_ctx = None
                if self.parent_id:
                    parent_ctx = TraceContextTextMapPropagator().extract({
                        CARRIER_KEY:
                            f"00-{self.trace_id}-{self.parent_id}-01",
                    })
                self._otel_span = otel.start_span(
                    self.name, context=parent_ctx, attributes=self.attrs
                )
                self._otel_token = otel_ctx.attach(
                    otel_trace.set_span_in_context(self._otel_span)
                )
            except Exception:
                self._otel_span = None
        return self

    def __exit__(self, exc_type, exc, tb):
        self.end = time.time()
        if exc_type is not None:
            self.attrs["error"] = f"{exc_type.__name__}"
        _CURRENT.reset(self._token)
        if self._otel_span is not None:
            try:
                from opentelemetry import context as otel_ctx

                if exc is not None:
                    self._otel_span.record_exception(exc)
                self._otel_span.end()
                if self._otel_token is not None:
                    otel_ctx.detach(self._otel_token)
            except Exception:
                pass
        d = self.to_dict()
        with _LOCK:
            _SPANS.append(d)
        # aggregate cluster-wide via the GCS event ring (queryable with
        # events.list_events / the dashboard), fire-and-forget so a span
        # exit never blocks the worker's io loop
        if os.environ.get("RT_TRACING_EXPORT_EVENTS", "1") == "1":
            try:
                from ray_tpu.core.runtime import get_runtime

                rt = get_runtime()
                rt._spawn(rt.gcs.notify("report_event", {
                    "severity": "DEBUG",
                    "source": "tracing",
                    "message": self.name,
                    "fields": d,
                }))
            except Exception:
                pass  # no runtime (unit test) / shutdown race
        return False

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "end": self.end,
            "duration_ms": round(((self.end or self.start) - self.start)
                                 * 1e3, 3),
            "attributes": dict(self.attrs),
            "pid": os.getpid(),
        }


def _otel_tracer():
    """An OpenTelemetry tracer IFF the app installed a real provider
    (the API's default ProxyTracerProvider is a no-op — bridging to it
    would just burn cycles)."""
    try:
        from opentelemetry import trace as otel_trace

        provider = otel_trace.get_tracer_provider()
        if type(provider).__name__ in (
            "ProxyTracerProvider", "NoOpTracerProvider",
        ):
            return None
        return otel_trace.get_tracer("ray_tpu")
    except Exception:
        return None


def span(name: str, carrier: Optional[Dict[str, str]] = None,
         **attrs) -> Span:
    """Start a span.  ``carrier``: remote parent context (a TaskSpec's
    trace_ctx); otherwise the ambient context is the parent."""
    parent = _extract(carrier) if carrier is not None else _CURRENT.get()
    return Span(name, parent, attrs)


def spans(trace_id: Optional[str] = None) -> List[dict]:
    """Finished spans recorded in THIS process (newest last)."""
    with _LOCK:
        out = list(_SPANS)
    if trace_id:
        out = [s for s in out if s["trace_id"] == trace_id]
    return out


def clear() -> None:
    with _LOCK:
        _SPANS.clear()
