"""Block-quantized wire codecs for runtime collectives (Collectives v2).

EQuARX-style (arxiv 2506.17615) payload compression: a float32 tensor
is encoded per contiguous *block* into a compact wire format and
decoded back to float32 at the receiving hop, trading a bounded
per-block error for 2x (bf16) or ~4x (int8) fewer wire bytes.  Opt-in
per group (``GroupOptions.wire_dtype``) or per op
(``allreduce(..., wire_dtype="int8")``); the default path never
imports this module's kernels and ships raw fp32 bytes bit-for-bit.

Codec contract (all arrays are 1-D contiguous):

- ``encode(flat_f32) -> uint8 wire buffer`` — deterministic: the same
  input always produces the same bytes (round-half-even, no RNG), so
  every receiver of one encoding decodes bit-identical values.
- ``decode(wire_u8, n_elems) -> float32`` — total: any buffer of the
  right size decodes (garbage in, garbage out, never a crash).
- ``encoded_nbytes(n_elems)`` — exact wire size, known to both sides
  up front (the chunked transport needs the expected byte count).
- ``error_bound(flat_f32) -> float`` — max |x - decode(encode(x))|
  guaranteed element-wise for FINITE input; the property tests hold
  every codec to it on adversarial distributions.

Non-finite input (inf/nan) is REJECTED at encode: a quantized scale
derived from an inf absmax silently zeroes the whole block, which is a
training-quality bug worth failing loudly over.

int8 layout: ``[n_blocks x f32 scale][n_elems x int8]`` — per-block
absmax/127 scales, round-half-even quantization.  Error bound per
element: ``scale/2`` of its block = ``absmax_block / 254``.

bf16 layout: ``[n_elems x u16]`` — round-to-nearest-even truncation of
the f32 bit pattern (pure bit math, no ml_dtypes dependency).  Error
bound per element: ``|x| * 2**-8`` (one ulp of an 8-bit mantissa,
conservative).
"""

from __future__ import annotations

import ctypes
from typing import Optional

from ray_tpu.common.config import cfg
from ray_tpu.util.collective.types import CollectiveError


def _qlib():
    """The fused native kernels (ray_tpu/_native/quant.cc), or None
    when the image has no compiler — numpy paths below are the
    bit-identical fallback."""
    from ray_tpu._native import quant

    return quant.lib()


def _fptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _i8ptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int8))


def _u16ptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))


def _u32ptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))


def _require_f32(flat):
    import numpy as np

    a = np.ascontiguousarray(flat).reshape(-1)
    if a.dtype != np.float32:
        raise CollectiveError(
            f"wire_dtype quantization needs float32 tensors, got "
            f"{a.dtype} — cast explicitly or drop wire_dtype for the "
            f"raw path (any dtype)"
        )
    return a


def _reject_non_finite():
    raise CollectiveError(
        "non-finite values (inf/nan) in a tensor bound for a "
        "quantized collective: block scales would be poisoned and "
        "the whole block silently zeroed.  Clean the tensor or use "
        "the fp32 path."
    )


class Int8BlockCodec:
    """Per-block absmax int8: ~3.9x smaller on the wire at block=2048.

    Kernels are written for a CPU-bound host (every pass competes with
    the transport's memcpys for the same cores): full blocks run
    vectorized with ONE large temporary (the scaled f32 view), the
    int8 cast lands straight into the wire buffer via ``np.copyto``,
    and the non-finite check rides the per-block max/min reductions
    (NaN/inf propagate through max) instead of a full-size
    ``isfinite`` pass.
    """

    name = "int8"

    def __init__(self, block: Optional[int] = None):
        self.block = int(block or cfg.collective_quant_block)
        self._scratch = {}  # shape -> f32 work buffer (encode temp)

    def _n_blocks(self, n_elems: int) -> int:
        return max((n_elems + self.block - 1) // self.block, 0)

    def encoded_nbytes(self, n_elems: int) -> int:
        return 4 * self._n_blocks(n_elems) + n_elems

    def _block_encode(self, b2, scales, q2):
        """Encode ``b2`` (nb, block) into scales + int8 rows in place."""
        import numpy as np

        # absmax via max/min reductions: no |a|-sized temporary, and
        # NaN/inf propagate so the finite check is O(n_blocks)
        mx = b2.max(axis=1)
        np.negative(b2.min(axis=1), out=scales)
        np.maximum(scales, mx, out=scales)
        if scales.size and not np.isfinite(scales).all():
            _reject_non_finite()
        recip = np.divide(
            np.float32(127.0), scales,
            out=np.zeros_like(scales), where=scales > 0.0,
        )  # zero blocks encode to zeros (scale 0), no divide warning
        scales /= np.float32(127.0)
        scaled = self._scratch.get(b2.shape)
        if scaled is None:
            if len(self._scratch) > 8:  # bound the cache (odd sizes)
                self._scratch.clear()
            scaled = self._scratch[b2.shape] = np.empty(
                b2.shape, np.float32
            )
        np.multiply(b2, recip[:, None], out=scaled)
        # round-half-even (np.rint): deterministic, matches IEEE
        # default.  |scaled| <= 127*(1+eps) by construction, so the
        # int8 cast cannot overflow — no clip pass needed.
        np.rint(scaled, out=scaled)
        np.copyto(q2, scaled, casting="unsafe")

    def encode(self, flat, out=None):
        """Encode to the wire buffer.  ``out`` (uint8, exact encoded
        size) lets ring hops reuse one buffer instead of re-faulting a
        fresh allocation per hop — chunk sends complete before the
        caller's next reuse (every chunk rpc is awaited)."""
        import numpy as np

        a = _require_f32(flat)
        n = a.size
        nb = self._n_blocks(n)
        if out is None:
            out = np.empty(self.encoded_nbytes(n), dtype=np.uint8)
        if not n:
            return out
        scales = out[: 4 * nb].view(np.float32)
        q = out[4 * nb:].view(np.int8)
        lib = _qlib()
        if lib is not None:
            if lib.rt_quant_int8_encode(
                _fptr(a), n, self.block, _fptr(scales), _i8ptr(q)
            ):
                _reject_non_finite()
            return out
        full = n // self.block
        if full:
            self._block_encode(
                a[: full * self.block].reshape(full, self.block),
                scales[:full], q[: full * self.block].reshape(full, -1),
            )
        if nb > full:  # ragged tail block (tiny)
            tail = a[full * self.block:]
            self._block_encode(
                tail.reshape(1, -1), scales[full:],
                q[full * self.block:].reshape(1, -1),
            )
        return out

    def decode_into(self, wire, out) -> None:
        """Decode straight into a writable contiguous f32 view (ring
        hops decode into the result tensor's segment — no intermediate
        allocation or copy pass)."""
        import numpy as np

        buf = np.ascontiguousarray(wire).reshape(-1).view(np.uint8)
        n_elems = out.size
        nb = self._n_blocks(n_elems)
        if buf.size != self.encoded_nbytes(n_elems):
            raise CollectiveError(
                f"int8 wire buffer is {buf.size} bytes, expected "
                f"{self.encoded_nbytes(n_elems)} for {n_elems} elems"
            )
        if not n_elems:
            return
        scales = buf[: 4 * nb].view(np.float32)
        q = buf[4 * nb:].view(np.int8)
        lib = _qlib()
        if lib is not None:
            lib.rt_quant_int8_decode(
                _fptr(scales), _i8ptr(q), n_elems, self.block, _fptr(out)
            )
            return
        full = n_elems // self.block
        if full:
            o2 = out[: full * self.block].reshape(full, self.block)
            np.copyto(
                o2, q[: full * self.block].reshape(full, -1),
                casting="unsafe",
            )  # int8 -> f32 straight into the output
            o2 *= scales[:full, None]
        if nb > full:
            tail = out[full * self.block:]
            np.copyto(tail, q[full * self.block:], casting="unsafe")
            tail *= scales[full]

    def decode_add_into(self, wire, acc) -> None:
        """``acc += decode(wire)`` in one pass — the ring reduce-scatter
        accumulation fused with the decode (SUM/MEAN fast path)."""
        import numpy as np

        buf = np.ascontiguousarray(wire).reshape(-1).view(np.uint8)
        n_elems = acc.size
        nb = self._n_blocks(n_elems)
        if buf.size != self.encoded_nbytes(n_elems):
            raise CollectiveError(
                f"int8 wire buffer is {buf.size} bytes, expected "
                f"{self.encoded_nbytes(n_elems)} for {n_elems} elems"
            )
        if not n_elems:
            return
        lib = _qlib()
        if lib is not None:
            lib.rt_quant_int8_decode_add(
                _fptr(buf[: 4 * nb].view(np.float32)),
                _i8ptr(buf[4 * nb:].view(np.int8)),
                n_elems, self.block, _fptr(acc),
            )
            return
        scratch = self._scratch.get(("dec", n_elems))
        if scratch is None:
            if len(self._scratch) > 8:
                self._scratch.clear()
            scratch = self._scratch[("dec", n_elems)] = np.empty(
                n_elems, np.float32
            )
        self.decode_into(buf, scratch)
        np.add(acc, scratch, out=acc)

    def decode(self, wire, n_elems: int):
        import numpy as np

        out = np.empty(n_elems, dtype=np.float32)
        self.decode_into(wire, out)
        return out

    def error_bound(self, flat) -> float:
        import numpy as np

        a = _require_f32(flat)
        if not a.size:
            return 0.0
        nb = self._n_blocks(a.size)
        pad = nb * self.block - a.size
        blocks = (np.pad(a, (0, pad)) if pad else a).reshape(nb, self.block)
        # scale/2 per block + fp slop for the divide/multiply round trip
        bound = np.abs(blocks).max(axis=1) / 254.0
        return float(bound.max() * (1.0 + 1e-5) + 1e-30)


class Bf16Codec:
    """Round-to-nearest-even f32 -> bf16 truncation: 2x smaller."""

    name = "bf16"

    def __init__(self, block: Optional[int] = None):
        self._scratch = {}  # size -> u32 work buffer

    def encoded_nbytes(self, n_elems: int) -> int:
        return 2 * n_elems

    def encode(self, flat, out=None):
        import numpy as np

        a = _require_f32(flat)
        if out is None:
            out = np.empty(2 * a.size, dtype=np.uint8)
        if not a.size:
            return out
        bits = a.view(np.uint32)
        lib = _qlib()
        if lib is not None:
            if lib.rt_quant_bf16_encode(
                _u32ptr(bits), a.size, _u16ptr(out.view(np.uint16))
            ):
                _reject_non_finite()
            return out
        if not (np.isfinite(a.max()) and np.isfinite(a.min())):
            # reductions propagate NaN/inf: no full-size isfinite pass
            _reject_non_finite()
        rounded = self._scratch.get(a.size)
        if rounded is None:
            if len(self._scratch) > 8:
                self._scratch.clear()
            rounded = self._scratch[a.size] = np.empty(a.size, np.uint32)
        # round to nearest even on the dropped 16 bits
        np.right_shift(bits, np.uint32(16), out=rounded)
        rounded &= np.uint32(1)
        rounded += bits
        rounded += np.uint32(0x7FFF)
        rounded >>= np.uint32(16)
        np.copyto(out.view(np.uint16), rounded, casting="unsafe")
        return out

    def decode_into(self, wire, out) -> None:
        import numpy as np

        buf = np.ascontiguousarray(wire).reshape(-1).view(np.uint8)
        if buf.size != 2 * out.size:
            raise CollectiveError(
                f"bf16 wire buffer is {buf.size} bytes, expected "
                f"{2 * out.size} for {out.size} elems"
            )
        if not out.size:
            return
        lib = _qlib()
        if lib is not None:
            lib.rt_quant_bf16_decode(
                _u16ptr(buf.view(np.uint16)), out.size,
                _u32ptr(out.view(np.uint32)),
            )
            return
        u32 = out.view(np.uint32)
        np.copyto(u32, buf.view(np.uint16), casting="unsafe")
        u32 <<= np.uint32(16)

    def decode_add_into(self, wire, acc) -> None:
        """``acc += decode(wire)`` fused (SUM/MEAN ring fast path)."""
        import numpy as np

        buf = np.ascontiguousarray(wire).reshape(-1).view(np.uint8)
        if buf.size != 2 * acc.size:
            raise CollectiveError(
                f"bf16 wire buffer is {buf.size} bytes, expected "
                f"{2 * acc.size} for {acc.size} elems"
            )
        if not acc.size:
            return
        lib = _qlib()
        if lib is not None:
            lib.rt_quant_bf16_decode_add(
                _u16ptr(buf.view(np.uint16)), acc.size, _fptr(acc)
            )
            return
        scratch = self._scratch.get(("dec", acc.size))
        if scratch is None:
            if len(self._scratch) > 8:
                self._scratch.clear()
            scratch = self._scratch[("dec", acc.size)] = np.empty(
                acc.size, np.float32
            )
        self.decode_into(buf, scratch)
        np.add(acc, scratch, out=acc)

    def decode(self, wire, n_elems: int):
        import numpy as np

        out = np.empty(n_elems, dtype=np.float32)
        self.decode_into(wire, out)
        return out

    def error_bound(self, flat) -> float:
        import numpy as np

        a = _require_f32(flat)
        if not a.size:
            return 0.0
        return float(np.abs(a).max() * 2.0 ** -8 + 1e-30)


_CODECS = {"int8": Int8BlockCodec, "bf16": Bf16Codec}


def get_codec(wire_dtype: Optional[str], block: Optional[int] = None):
    """The codec instance for ``wire_dtype`` — or None for the raw
    fp32 path (None or "fp32"), which must never pay a codec call."""
    if wire_dtype is None or wire_dtype == "fp32":
        return None
    cls = _CODECS.get(wire_dtype)
    if cls is None:
        raise CollectiveError(
            f"unknown wire_dtype {wire_dtype!r}; known: "
            f"{['fp32'] + sorted(_CODECS)}"
        )
    return cls(block)
